// End-to-end harness: a real Java edge client joining a fedml_tpu
// cross-device run.  Compile with the SDK sources and run against a
// LocalBroker + cross-device server started from Python
// (tests/test_java_sdk.py runs this automatically when a JDK is present):
//
//   javac -d build android/sdk/src/main/java/ai/fedml/tpu/*.java \
//         android/sdk/harness/EdgeHarness.java
//   java -cp build -Djava.library.path=native/build EdgeHarness \
//        <host> <port> <runId> <rank> <dataPath> <uploadDir>
//
// Prints one line per round and "HARNESS-FINISHED <rounds>" on S2C_FINISH.

import java.io.File;
import java.util.concurrent.CountDownLatch;

import ai.fedml.tpu.FedEdgeManager;
import ai.fedml.tpu.OnTrainProgressListener;

public final class EdgeHarness {
    public static void main(String[] args) throws Exception {
        String host = args[0];
        int port = Integer.parseInt(args[1]);
        String runId = args[2];
        long rank = Long.parseLong(args[3]);
        String dataPath = args[4];
        File uploadDir = new File(args[5]);

        CountDownLatch done = new CountDownLatch(1);
        FedEdgeManager edge = FedEdgeManager.builder()
                .broker(host, port)
                .runId(runId)
                .rank(rank)
                .dataPath(dataPath)
                .uploadDir(uploadDir)
                .hyperParams(32, 0.1, 1)
                .listener(new OnTrainProgressListener() {
                    @Override
                    public void onRoundCompleted(int roundIdx, double loss, long n) {
                        System.out.println("round " + roundIdx + " loss=" + loss + " n=" + n);
                    }

                    @Override
                    public void onFinished(int roundsTrained) {
                        System.out.println("HARNESS-FINISHED " + roundsTrained);
                        done.countDown();
                    }
                })
                .build();
        edge.start();
        done.await();
        System.exit(0);
    }
}
