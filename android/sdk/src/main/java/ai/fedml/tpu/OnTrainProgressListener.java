package ai.fedml.tpu;

/**
 * App-facing training callbacks (reference role:
 * android/fedmlsdk/.../OnTrainProgressListener.java + OnTrainingStatusListener).
 */
public interface OnTrainProgressListener {
    /** A round's local training finished; loss scaled back from the native 1e6 fixed point. */
    void onRoundCompleted(int roundIdx, double loss, long numSamples);

    /** The server ended the run. */
    void onFinished(int roundsTrained);
}
