package ai.fedml.tpu;

import java.io.DataInputStream;
import java.io.DataOutputStream;
import java.io.EOFException;
import java.io.IOException;
import java.net.Socket;
import java.nio.charset.StandardCharsets;
import java.util.LinkedHashMap;
import java.util.Map;

/**
 * The broker wire: 4-byte big-endian length + UTF-8 JSON dict frames, ops
 * SUB / UNSUB / PUB / WILL / DISCONNECT, deliveries arriving as MSG frames.
 *
 * This is the JSON interop encoding of the fedml_tpu pub/sub broker
 * (fedml_tpu/core/distributed/communication/mqtt_s3/broker.py — the broker
 * sniffs each connection's encoding and answers JSON clients in JSON), so a
 * device JVM joins the same broker the Python silos use.  Reference role:
 * the paho MqttAndroidClient inside EdgeCommunicator.java.
 */
public final class BrokerConnection implements AutoCloseable {
    /** Topic + decoded payload callback, invoked on the receive thread. */
    public interface OnMessage {
        void onMessage(String topic, Object payload);
    }

    private final Socket socket;
    private final DataOutputStream out;
    private final DataInputStream in;
    private final OnMessage onMessage;
    private final Thread recvThread;
    private volatile boolean running = true;
    private volatile Runnable onConnectionLost;

    /** Invoked once from the receive thread if the wire dies while the
     *  client did NOT call disconnect() — without it a broker crash would
     *  leave the app waiting forever with the failure visible only
     *  server-side (via the last will). */
    public void setOnConnectionLost(Runnable callback) {
        this.onConnectionLost = callback;
    }

    public BrokerConnection(String host, int port, OnMessage onMessage) throws IOException {
        this.socket = new Socket(host, port);
        this.socket.setTcpNoDelay(true);
        this.out = new DataOutputStream(socket.getOutputStream());
        this.in = new DataInputStream(socket.getInputStream());
        this.onMessage = onMessage;
        this.recvThread = new Thread(this::recvLoop, "broker-recv");
        this.recvThread.setDaemon(true);
        this.recvThread.start();
    }

    public void subscribe(String topic) throws IOException {
        send(frame("SUB", topic, null));
    }

    public void unsubscribe(String topic) throws IOException {
        send(frame("UNSUB", topic, null));
    }

    public void publish(String topic, Object payload) throws IOException {
        send(frame("PUB", topic, payload));
    }

    /** Broker publishes this if the socket dies without DISCONNECT. */
    public void setLastWill(String topic, Object payload) throws IOException {
        send(frame("WILL", topic, payload));
    }

    public void disconnect() {
        // graceful close: DISCONNECT, half-close (FIN), drain inbound to
        // EOF, then close.  An immediate close() with undrained wildcard
        // deliveries in our receive buffer sends a TCP RST, and an RST
        // discards our still-unread frames at the broker — it can lose the
        // tail of our own just-published uploads.
        running = false;
        try {
            // fence the half-close with the sends (same monitor as send()):
            // a publish slipping between DISCONNECT and FIN would make the
            // broker break at DISCONNECT with unread data -> RST back at us
            synchronized (this) {
                Map<String, Object> f = new LinkedHashMap<>();
                f.put("op", "DISCONNECT");
                send(f);
                socket.shutdownOutput();
            }
        } catch (IOException ignored) {
            // socket already gone: the broker fires the last will instead
        }
        if (Thread.currentThread() == recvThread) {
            // called from an onMessage handler: the recv loop (this thread)
            // resumes draining when the handler returns, closing at EOF
            return;
        }
        try {
            recvThread.join(5000); // recv loop drains until broker EOF
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
        }
        try {
            socket.close();
        } catch (IOException ignored) {
        }
    }

    @Override
    public void close() {
        disconnect();
    }

    private static Map<String, Object> frame(String op, String topic, Object payload) {
        Map<String, Object> f = new LinkedHashMap<>();
        f.put("op", op);
        f.put("topic", topic);
        if (payload != null) f.put("payload", payload);
        return f;
    }

    private synchronized void send(Map<String, Object> frame) throws IOException {
        byte[] body = Json.encode(frame).getBytes(StandardCharsets.UTF_8);
        out.writeInt(body.length);
        out.write(body);
        out.flush();
    }

    private void recvLoop() {
        try {
            // reads to EOF even after disconnect() flips running: draining
            // the inbound stream keeps the close RST-free (see disconnect)
            while (true) {
                int n = in.readInt();
                if (n < 0) {
                    throw new IOException("corrupt frame length " + n);
                }
                byte[] body = new byte[n];
                in.readFully(body);
                try {
                    Map<String, Object> f =
                            Json.decodeObject(new String(body, StandardCharsets.UTF_8));
                    if ("MSG".equals(f.get("op")) && onMessage != null) {
                        onMessage.onMessage(String.valueOf(f.get("topic")), f.get("payload"));
                    }
                } catch (RuntimeException e) {
                    // an undecodable frame means the stream is desynced: a
                    // silently-dead receive thread would keep the socket open
                    // and the broker would never fire our OFFLINE last will —
                    // tear the connection down instead
                    System.err.println("fedml broker frame decode failed: " + e);
                    break;
                }
            }
        } catch (EOFException | java.net.SocketException e) {
            // broker closed or we disconnected: normal shutdown path
        } catch (IOException e) {
            if (running) {
                System.err.println("fedml broker recv failed: " + e);
            }
        } finally {
            boolean unclean = running;
            // the recv loop owns the final close when disconnect() was
            // issued from this thread (idempotent otherwise); on unclean
            // exit the close makes the broker publish our last will
            try {
                socket.close();
            } catch (IOException ignored) {
            }
            if (unclean) {
                Runnable cb = onConnectionLost;
                if (cb != null) {
                    try {
                        cb.run();
                    } catch (RuntimeException e) {
                        System.err.println("fedml connection-lost callback raised: " + e);
                    }
                }
            }
        }
    }
}
