package ai.fedml.tpu;

import java.io.File;
import java.io.IOException;

/**
 * The FL client scheduler: drives one edge rank through the cross-device
 * round protocol — the Java twin of the Python device managers
 * (fedml_tpu/cross_device/fake_device.py handler-for-handler, which is
 * itself the protocol the server in fedml_tpu/cross_device/
 * fedml_server_manager.py expects; reference role:
 * android/fedmlsdk/.../service/ClientManager.java).
 *
 * Protocol walked:
 * <ol>
 *   <li>connection_ready → C2S_CLIENT_STATUS ONLINE (handshake);</li>
 *   <li>S2C_CHECK_CLIENT_STATUS → re-announce ONLINE;</li>
 *   <li>S2C_INIT_CONFIG / S2C_SYNC_MODEL_TO_CLIENT → download the model
 *       FILE, train natively off-thread, upload the trained file with the
 *       ROUND TAG (straggler-tolerant servers drop uploads whose tag
 *       mismatches the open round) and the sample count;</li>
 *   <li>S2C_FINISH → stop.</li>
 * </ol>
 */
public final class ClientManager implements TrainingExecutor.OnRoundDone {
    private final EdgeCommunicator comm;
    private final TrainingExecutor executor;
    private final long rank;
    private final File uploadDir;
    private final OnTrainProgressListener listener;
    private final java.util.concurrent.atomic.AtomicBoolean finished =
            new java.util.concurrent.atomic.AtomicBoolean(false);
    private volatile int roundsTrained = 0;

    public ClientManager(EdgeCommunicator comm, TrainingExecutor executor, long rank,
                         File uploadDir, OnTrainProgressListener listener) {
        this.comm = comm;
        this.executor = executor;
        this.rank = rank;
        this.uploadDir = uploadDir;
        this.listener = listener;
        comm.register(MessageDefine.MSG_TYPE_CONNECTION_READY, m -> announceOnline());
        comm.register(MessageDefine.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, m -> announceOnline());
        comm.register(MessageDefine.MSG_TYPE_S2C_INIT_CONFIG, this::onModel);
        comm.register(MessageDefine.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, this::onModel);
        comm.register(MessageDefine.MSG_TYPE_S2C_FINISH, m -> finish());
        // broker death must not strand the app waiting on onFinished
        comm.setOnConnectionLost(() -> {
            System.err.println("fedml broker connection lost: leaving the run");
            finish();
        });
    }

    /** Begin participating (raises connection_ready → ONLINE handshake). */
    public void run() {
        comm.start();
    }

    private void announceOnline() {
        Message m = new Message(MessageDefine.MSG_TYPE_C2S_CLIENT_STATUS, rank, 0);
        m.add(MessageDefine.MSG_ARG_KEY_CLIENT_STATUS, MessageDefine.CLIENT_STATUS_ONLINE);
        sendOrWarn(m);
    }

    private void onModel(Message msg) {
        String modelFile = msg.getString(MessageDefine.MSG_ARG_KEY_MODEL_PARAMS_FILE);
        int roundIdx = (int) msg.getLong(MessageDefine.MSG_ARG_KEY_ROUND_INDEX, 0);
        if (modelFile == null) {
            System.err.println("fedml round " + roundIdx + ": no model file in sync msg");
            return;
        }
        File out = new File(uploadDir, "model_r" + roundIdx + "_c" + rank + ".ftem");
        // seed matches the Python fake device: per-(round, rank) determinism
        executor.submit(roundIdx, modelFile, out.getAbsolutePath(),
                        roundIdx * 1000L + rank, this);
    }

    @Override
    public void onRoundDone(int roundIdx, TrainingExecutor.RoundResult result) {
        roundsTrained++;
        Message m = new Message(MessageDefine.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rank, 0);
        m.add(MessageDefine.MSG_ARG_KEY_ROUND_INDEX, roundIdx);
        m.add(MessageDefine.MSG_ARG_KEY_MODEL_PARAMS_FILE, result.modelOutPath);
        m.add(MessageDefine.MSG_ARG_KEY_NUM_SAMPLES, result.numSamples);
        sendOrWarn(m);
        if (listener != null) {
            listener.onRoundCompleted(roundIdx, result.loss, result.numSamples);
        }
    }

    @Override
    public void onRoundFailed(int roundIdx, String error) {
        // no upload: a straggler-tolerant server closes the round without us
        System.err.println("fedml round " + roundIdx + " failed on-device: " + error);
    }

    /** Leave the run: stop local training, drop the transport, report.
     *  Idempotent — reachable from S2C_FINISH, connection loss, and the
     *  app's FedEdgeManager.stop(). */
    public void finish() {
        if (!finished.compareAndSet(false, true)) {
            return;
        }
        // shutdown() blocks until the in-flight round resolves, so a final
        // onRoundCompleted lands BEFORE onFinished and roundsTrained is
        // complete when reported
        executor.shutdown();
        comm.stop();
        if (listener != null) {
            listener.onFinished(roundsTrained);
        }
    }

    private void sendOrWarn(Message m) {
        try {
            comm.send(m);
        } catch (IOException e) {
            System.err.println("fedml send failed (type " + m.getType() + "): " + e);
        }
    }
}
