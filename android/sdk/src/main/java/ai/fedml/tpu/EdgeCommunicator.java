package ai.fedml.tpu;

import java.io.IOException;
import java.util.Map;
import java.util.concurrent.ConcurrentHashMap;

/**
 * FL message plane for one edge rank — the Java twin of the Python
 * MqttS3CommManager in MNN mode
 * (fedml_tpu/core/distributed/communication/mqtt_s3/mqtt_s3_comm_manager.py;
 * reference role: android/fedmlsdk/.../service/communicator/EdgeCommunicator.java):
 *
 * <ul>
 *   <li>per-pair topics {@code fedml/{runId}/{sender}/{receiver}} — this rank
 *       subscribes to the run's prefix and filters on receiver;</li>
 *   <li>status topic {@code fedml/{runId}/status} with an OFFLINE last will
 *       (server-side liveness detection);</li>
 *   <li>handlers registered per message type; a local
 *       {@code connection_ready} fires once the wire is up (same bootstrap
 *       contract as every Python comm manager).</li>
 * </ul>
 */
public final class EdgeCommunicator implements BrokerConnection.OnMessage {
    public interface MessageHandler {
        void onMessage(Message msg);
    }

    private final String runId;
    private final long rank;
    private final BrokerConnection conn;
    private final Map<String, MessageHandler> handlers = new ConcurrentHashMap<>();

    public EdgeCommunicator(String host, int port, String runId, long rank)
            throws IOException {
        this.runId = runId;
        this.rank = rank;
        this.conn = new BrokerConnection(host, port, this);
        Map<String, Object> will = new java.util.LinkedHashMap<>();
        will.put("rank", rank);
        will.put("status", MessageDefine.CLIENT_STATUS_OFFLINE);
        conn.setLastWill(statusTopic(), Json.encode(will));
        conn.subscribe("fedml/" + runId + "/#");
    }

    public void register(int msgType, MessageHandler handler) {
        handlers.put(String.valueOf(msgType), handler);
    }

    public void register(String msgType, MessageHandler handler) {
        handlers.put(msgType, handler);
    }

    /** Surface transport death to the app layer (see BrokerConnection). */
    public void setOnConnectionLost(Runnable callback) {
        conn.setOnConnectionLost(callback);
    }

    /** Call after registering handlers: raises the local connection_ready. */
    public void start() {
        MessageHandler h = handlers.get(MessageDefine.MSG_TYPE_CONNECTION_READY);
        if (h != null) {
            h.onMessage(new Message(MessageDefine.MSG_TYPE_CONNECTION_READY, rank, rank));
        }
    }

    public void send(Message msg) throws IOException {
        conn.publish(topic(msg.getSenderId(), msg.getReceiverId()), msg.getParams());
    }

    public void broadcastStatus(String status) throws IOException {
        Map<String, Object> m = new java.util.LinkedHashMap<>();
        m.put("rank", rank);
        m.put("status", status);
        conn.publish(statusTopic(), Json.encode(m));
    }

    public void stop() {
        conn.disconnect();
    }

    private String topic(long sender, long receiver) {
        return "fedml/" + runId + "/" + sender + "/" + receiver;
    }

    private String statusTopic() {
        return "fedml/" + runId + "/status";
    }

    @Override
    @SuppressWarnings("unchecked")
    public void onMessage(String topic, Object payload) {
        if (statusTopic().equals(topic)) {
            return; // liveness plane: observed server-side
        }
        // topic = fedml/{runId}/{sender}/{receiver}
        String[] parts = topic.split("/");
        if (parts.length != 4) return;
        long receiver;
        try {
            receiver = Long.parseLong(parts[3]);
        } catch (NumberFormatException e) {
            return;
        }
        if (receiver != rank || !(payload instanceof Map)) return;
        Message msg = Message.fromParams((Map<String, Object>) payload);
        MessageHandler h = handlers.get(msg.getType());
        if (h != null) {
            h.onMessage(msg);
        }
    }
}
