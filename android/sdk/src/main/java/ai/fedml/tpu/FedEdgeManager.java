package ai.fedml.tpu;

import java.io.File;
import java.io.IOException;

/**
 * Public SDK facade — what an app links (reference role:
 * android/fedmlsdk/.../FedEdgeManager.java + FedEdgeApi.java):
 *
 * <pre>
 *   FedEdgeManager edge = FedEdgeManager.builder()
 *       .broker(host, port).runId("mnist-1").rank(1)
 *       .dataPath("/data/local_data.ftem")
 *       .uploadDir(context.getCacheDir())
 *       .hyperParams(32, 0.1, 1)
 *       .listener(myListener)
 *       .build();
 *   edge.start();   // joins the run, trains every round until S2C_FINISH
 *   ...
 *   edge.stop();    // leave early (server's straggler tolerance covers us)
 * </pre>
 */
public final class FedEdgeManager {
    private final ClientManager client;

    private FedEdgeManager(ClientManager client) {
        this.client = client;
    }

    public static Builder builder() {
        return new Builder();
    }

    public void start() {
        client.run();
    }

    /** Leave the run early: stops local training (cooperatively, discarding
     *  queued rounds) AND the transport; the server's straggler tolerance
     *  covers the missing upload.  BLOCKS until the in-flight round reaches
     *  its next batch boundary (up to ~10s) so the final callbacks arrive
     *  in order — call from a background thread, never the Android main
     *  thread (ANR). */
    public void stop() {
        client.finish();
    }

    public static final class Builder {
        private String host = "127.0.0.1";
        private int port;
        private String runId = "0";
        private long rank = 1;
        private String dataPath;
        private File uploadDir;
        private int batchSize = 32;
        private double lr = 0.1;
        private int epochs = 1;
        private OnTrainProgressListener listener;

        public Builder broker(String host, int port) {
            this.host = host;
            this.port = port;
            return this;
        }

        public Builder runId(String runId) {
            this.runId = runId;
            return this;
        }

        public Builder rank(long rank) {
            this.rank = rank;
            return this;
        }

        /** FTEM file with the device's local (x, y) shard. */
        public Builder dataPath(String dataPath) {
            this.dataPath = dataPath;
            return this;
        }

        public Builder uploadDir(File uploadDir) {
            this.uploadDir = uploadDir;
            return this;
        }

        public Builder hyperParams(int batchSize, double lr, int epochs) {
            this.batchSize = batchSize;
            this.lr = lr;
            this.epochs = epochs;
            return this;
        }

        public Builder listener(OnTrainProgressListener listener) {
            this.listener = listener;
            return this;
        }

        public FedEdgeManager build() throws IOException {
            if (dataPath == null || uploadDir == null) {
                throw new IllegalStateException("dataPath and uploadDir are required");
            }
            if (!uploadDir.isDirectory() && !uploadDir.mkdirs()) {
                throw new IOException("cannot create upload dir " + uploadDir);
            }
            EdgeCommunicator comm = new EdgeCommunicator(host, port, runId, rank);
            TrainingExecutor exec = new TrainingExecutor(dataPath, batchSize, lr, epochs);
            ClientManager client = new ClientManager(comm, exec, rank, uploadDir, listener);
            return new FedEdgeManager(client);
        }
    }
}
