package ai.fedml.tpu;

/**
 * Binding to the native edge runtime (libfedml_jni.so, built from
 * native/android/fedml_jni.cpp over the stable C ABI in native/capi.cpp;
 * reference role: android/fedmlsdk/.../nativemobilenn/NativeFedMLClientManager.java).
 *
 * The method list below is the EXACT export surface of fedml_jni.cpp —
 * tests/test_java_sdk.py cross-checks every native method here against the
 * {@code Java_ai_fedml_tpu_NativeFedMLTrainer_*} symbols in the C++ file.
 *
 * Model/data travel as FTEM files (fedml_tpu/cross_device/edge_model.py):
 * Java never parses tensors, it hands paths to the native trainer.
 */
public final class NativeFedMLTrainer {
    static {
        System.loadLibrary("fedml_jni");
    }

    private NativeFedMLTrainer() {}

    // ---- plain on-device trainer -----------------------------------------
    public static native long create(String modelPath, String dataPath,
                                     int batch, double lr, int epochs, long seed);

    /** 0 on success; see {@link #lastError()} otherwise. */
    public static native int train(long handle);

    public static native int save(long handle, String outPath);

    /** {acc*1e6, loss*1e6}; {-1} on error. */
    public static native long[] evaluate(long handle);

    /** {epoch, loss*1e6} of the last finished epoch. */
    public static native long[] epochLoss(long handle);

    public static native long numSamples(long handle);

    /** Cooperative stop: the training loop exits at the next batch. */
    public static native void stop(long handle);

    public static native void destroy(long handle);

    public static native String lastError();

    // ---- LightSecAgg client (secure aggregation on-device) ----------------
    public static native long clientCreate(String modelPath, String dataPath,
                                           int batch, double lr, int epochs, long seed);

    public static native int clientTrain(long handle);

    public static native int clientSaveMasked(long handle, int qBits,
                                              long maskSeed, String outPath);

    public static native long clientMaskDim(long handle);

    public static native long[] clientEncodeMask(long handle, int n, int t,
                                                 int u, long maskSeed);

    public static native void clientDestroy(long handle);
}
