package ai.fedml.tpu;

import java.util.LinkedHashMap;
import java.util.Map;

/**
 * One FL control-plane message: a param map with typed accessors — the Java
 * twin of fedml_tpu/core/distributed/communication/message.py (reference
 * role: the JSON messages EdgeCommunicator hands to its listeners).
 */
public final class Message {
    private final Map<String, Object> params;

    public Message(String type, long senderId, long receiverId) {
        params = new LinkedHashMap<>();
        params.put(MessageDefine.MSG_ARG_KEY_TYPE, type);
        params.put(MessageDefine.MSG_ARG_KEY_SENDER, senderId);
        params.put(MessageDefine.MSG_ARG_KEY_RECEIVER, receiverId);
    }

    public Message(int type, long senderId, long receiverId) {
        this(String.valueOf(type), senderId, receiverId);
    }

    private Message(Map<String, Object> params) {
        this.params = params;
    }

    /** Rebuild from a received param map (the payload of a broker frame). */
    public static Message fromParams(Map<String, Object> params) {
        return new Message(new LinkedHashMap<>(params));
    }

    public String getType() {
        return String.valueOf(params.get(MessageDefine.MSG_ARG_KEY_TYPE));
    }

    public long getSenderId() {
        return asLong(params.get(MessageDefine.MSG_ARG_KEY_SENDER), 0);
    }

    public long getReceiverId() {
        return asLong(params.get(MessageDefine.MSG_ARG_KEY_RECEIVER), 0);
    }

    public Message add(String key, Object value) {
        params.put(key, value);
        return this;
    }

    public Object get(String key) {
        return params.get(key);
    }

    public String getString(String key) {
        Object v = params.get(key);
        return v == null ? null : String.valueOf(v);
    }

    public long getLong(String key, long dflt) {
        return asLong(params.get(key), dflt);
    }

    public Map<String, Object> getParams() {
        return params;
    }

    private static long asLong(Object v, long dflt) {
        if (v instanceof Number) return ((Number) v).longValue();
        if (v instanceof String) {
            try {
                return Long.parseLong((String) v);
            } catch (NumberFormatException ignored) {
                return dflt;
            }
        }
        return dflt;
    }
}
