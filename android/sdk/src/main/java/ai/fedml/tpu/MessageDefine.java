package ai.fedml.tpu;

/**
 * The FL round message vocabulary — field-for-field mirror of the Python
 * contract (fedml_tpu/cross_silo/message_define.py and
 * fedml_tpu/cross_device/message_define.py; reference role:
 * android/fedmlsdk/.../EdgeMessageDefine.java).
 *
 * tests/test_java_sdk.py parses this file and asserts every constant equals
 * its Python twin, so the two sides cannot drift silently.
 */
public final class MessageDefine {
    private MessageDefine() {}

    // server -> client
    public static final int MSG_TYPE_S2C_INIT_CONFIG = 1;
    public static final int MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2;
    public static final int MSG_TYPE_S2C_CHECK_CLIENT_STATUS = 6;
    public static final int MSG_TYPE_S2C_FINISH = 7;

    // client -> server
    public static final int MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3;
    public static final int MSG_TYPE_C2S_SEND_STATS_TO_SERVER = 4;
    public static final int MSG_TYPE_C2S_CLIENT_STATUS = 5;

    public static final String MSG_ARG_KEY_TYPE = "msg_type";
    public static final String MSG_ARG_KEY_SENDER = "sender";
    public static final String MSG_ARG_KEY_RECEIVER = "receiver";

    public static final String MSG_ARG_KEY_NUM_SAMPLES = "num_samples";
    public static final String MSG_ARG_KEY_MODEL_PARAMS = "model_params";
    public static final String MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url";
    public static final String MSG_ARG_KEY_MODEL_PARAMS_FILE = "model_params_file";
    public static final String MSG_ARG_KEY_CLIENT_INDEX = "client_idx";
    public static final String MSG_ARG_KEY_CLIENT_STATUS = "client_status";
    public static final String MSG_ARG_KEY_ROUND_INDEX = "round_idx";

    // reliability headers (additive wire change): per-incarnation message id
    // ("rank:nonce:seq") for ack/dedup, and the client incarnation epoch the
    // server uses to recognise a mid-run rejoin and resync the model
    public static final String MSG_ARG_KEY_MSG_ID = "msg_id";
    public static final String MSG_ARG_KEY_CLIENT_EPOCH = "client_epoch";

    public static final String MSG_ARG_KEY_TRAIN_CORRECT = "train_correct";
    public static final String MSG_ARG_KEY_TRAIN_ERROR = "train_error";
    public static final String MSG_ARG_KEY_TRAIN_NUM = "train_num_sample";

    public static final String CLIENT_STATUS_OFFLINE = "OFFLINE";
    public static final String CLIENT_STATUS_IDLE = "IDLE";
    public static final String CLIENT_STATUS_ONLINE = "ONLINE";

    /** Local pseudo-message the communicator raises once the socket is up. */
    public static final String MSG_TYPE_CONNECTION_READY = "connection_ready";
}
