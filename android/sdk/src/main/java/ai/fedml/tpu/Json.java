package ai.fedml.tpu;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

/**
 * Minimal dependency-free JSON codec for the broker wire frames — the SDK
 * runs on bare JVMs/Android without pulling Gson/Jackson (the reference SDK
 * bundles Gson; this rebuild keeps the edge artifact dependency-free).
 *
 * Supports exactly what the control plane needs: objects, arrays, strings,
 * longs, doubles, booleans, null.  Numbers decode as Long when integral,
 * Double otherwise.
 */
public final class Json {
    private Json() {}

    // ---- encode -----------------------------------------------------------
    public static String encode(Object v) {
        StringBuilder sb = new StringBuilder();
        write(sb, v);
        return sb.toString();
    }

    private static void write(StringBuilder sb, Object v) {
        if (v == null) {
            sb.append("null");
        } else if (v instanceof String) {
            writeString(sb, (String) v);
        } else if (v instanceof Boolean) {
            sb.append(v.toString());
        } else if (v instanceof Double || v instanceof Float) {
            double d = ((Number) v).doubleValue();
            if (Double.isNaN(d) || Double.isInfinite(d)) {
                throw new IllegalArgumentException("non-finite number in JSON");
            }
            sb.append(d);
        } else if (v instanceof Number) {
            sb.append(((Number) v).longValue());
        } else if (v instanceof Map) {
            sb.append('{');
            boolean first = true;
            for (Map.Entry<?, ?> e : ((Map<?, ?>) v).entrySet()) {
                if (!first) sb.append(',');
                first = false;
                writeString(sb, String.valueOf(e.getKey()));
                sb.append(':');
                write(sb, e.getValue());
            }
            sb.append('}');
        } else if (v instanceof List) {
            sb.append('[');
            boolean first = true;
            for (Object e : (List<?>) v) {
                if (!first) sb.append(',');
                first = false;
                write(sb, e);
            }
            sb.append(']');
        } else {
            throw new IllegalArgumentException("unsupported JSON type: " + v.getClass());
        }
    }

    private static void writeString(StringBuilder sb, String s) {
        sb.append('"');
        for (int i = 0; i < s.length(); i++) {
            char c = s.charAt(i);
            switch (c) {
                case '"': sb.append("\\\""); break;
                case '\\': sb.append("\\\\"); break;
                case '\n': sb.append("\\n"); break;
                case '\r': sb.append("\\r"); break;
                case '\t': sb.append("\\t"); break;
                case '\b': sb.append("\\b"); break;
                case '\f': sb.append("\\f"); break;
                default:
                    if (c < 0x20) {
                        sb.append(String.format("\\u%04x", (int) c));
                    } else {
                        sb.append(c);
                    }
            }
        }
        sb.append('"');
    }

    // ---- decode -----------------------------------------------------------
    public static Object decode(String text) {
        Parser p = new Parser(text);
        Object v = p.value();
        p.skipWs();
        if (!p.done()) throw new IllegalArgumentException("trailing JSON garbage");
        return v;
    }

    @SuppressWarnings("unchecked")
    public static Map<String, Object> decodeObject(String text) {
        Object v = decode(text);
        if (!(v instanceof Map)) throw new IllegalArgumentException("not a JSON object");
        return (Map<String, Object>) v;
    }

    private static final class Parser {
        private final String s;
        private int i = 0;

        Parser(String s) { this.s = s; }

        boolean done() { return i >= s.length(); }

        void skipWs() {
            while (i < s.length() && Character.isWhitespace(s.charAt(i))) i++;
        }

        char peek() {
            if (done()) throw new IllegalArgumentException("unexpected end of JSON");
            return s.charAt(i);
        }

        void expect(char c) {
            if (done() || s.charAt(i) != c) {
                throw new IllegalArgumentException("expected '" + c + "' at " + i);
            }
            i++;
        }

        Object value() {
            skipWs();
            char c = peek();
            if (c == '{') return object();
            if (c == '[') return array();
            if (c == '"') return string();
            if (c == 't') { literal("true"); return Boolean.TRUE; }
            if (c == 'f') { literal("false"); return Boolean.FALSE; }
            if (c == 'n') { literal("null"); return null; }
            return number();
        }

        private void literal(String lit) {
            if (!s.startsWith(lit, i)) throw new IllegalArgumentException("bad literal at " + i);
            i += lit.length();
        }

        private Map<String, Object> object() {
            expect('{');
            Map<String, Object> out = new LinkedHashMap<>();
            skipWs();
            if (peek() == '}') { i++; return out; }
            while (true) {
                skipWs();
                String k = string();
                skipWs();
                expect(':');
                out.put(k, value());
                skipWs();
                char c = peek();
                i++;
                if (c == '}') return out;
                if (c != ',') throw new IllegalArgumentException("expected ',' at " + (i - 1));
            }
        }

        private List<Object> array() {
            expect('[');
            List<Object> out = new ArrayList<>();
            skipWs();
            if (peek() == ']') { i++; return out; }
            while (true) {
                out.add(value());
                skipWs();
                char c = peek();
                i++;
                if (c == ']') return out;
                if (c != ',') throw new IllegalArgumentException("expected ',' at " + (i - 1));
            }
        }

        private char next() {
            if (done()) {
                throw new IllegalArgumentException("unexpected end of JSON string");
            }
            return s.charAt(i++);
        }

        private String string() {
            expect('"');
            StringBuilder sb = new StringBuilder();
            while (true) {
                char c = next();
                if (c == '"') return sb.toString();
                if (c == '\\') {
                    char e = next();
                    switch (e) {
                        case '"': sb.append('"'); break;
                        case '\\': sb.append('\\'); break;
                        case '/': sb.append('/'); break;
                        case 'n': sb.append('\n'); break;
                        case 'r': sb.append('\r'); break;
                        case 't': sb.append('\t'); break;
                        case 'b': sb.append('\b'); break;
                        case 'f': sb.append('\f'); break;
                        case 'u':
                            if (i + 4 > s.length()) {
                                throw new IllegalArgumentException(
                                        "truncated \\u escape at " + i);
                            }
                            sb.append((char) Integer.parseInt(s.substring(i, i + 4), 16));
                            i += 4;
                            break;
                        default: throw new IllegalArgumentException("bad escape \\" + e);
                    }
                } else {
                    sb.append(c);
                }
            }
        }

        private Object number() {
            int start = i;
            if (peek() == '-') i++;
            boolean isDouble = false;
            while (!done()) {
                char c = s.charAt(i);
                if (c >= '0' && c <= '9') { i++; continue; }
                if (c == '.' || c == 'e' || c == 'E' || c == '+' || c == '-') {
                    isDouble = true;
                    i++;
                    continue;
                }
                break;
            }
            String num = s.substring(start, i);
            return isDouble ? (Object) Double.parseDouble(num) : (Object) Long.parseLong(num);
        }
    }
}
