package ai.fedml.tpu;

import java.util.concurrent.ExecutorService;
import java.util.concurrent.Executors;
import java.util.concurrent.TimeUnit;

/**
 * Single-thread training executor: the round handler returns immediately and
 * the (seconds-long) native training runs off the communicator's receive
 * thread — same split as the reference's service/TrainingExecutor.java.
 */
public final class TrainingExecutor {
    /** Result of one local round driven through the native trainer. */
    public static final class RoundResult {
        public final String modelOutPath;
        public final long numSamples;
        public final double loss;

        RoundResult(String modelOutPath, long numSamples, double loss) {
            this.modelOutPath = modelOutPath;
            this.numSamples = numSamples;
            this.loss = loss;
        }
    }

    public interface OnRoundDone {
        void onRoundDone(int roundIdx, RoundResult result);

        void onRoundFailed(int roundIdx, String error);
    }

    private final ExecutorService pool = Executors.newSingleThreadExecutor(r -> {
        Thread t = new Thread(r, "fedml-train");
        t.setDaemon(true);
        return t;
    });
    private final String dataPath;
    private final int batchSize;
    private final double lr;
    private final int epochs;
    // guards the active native handle: stop() from another thread must
    // never race the worker's destroy() (native use-after-free)
    private final Object handleLock = new Object();
    private long activeHandle = 0;
    private volatile boolean stopping = false;

    public TrainingExecutor(String dataPath, int batchSize, double lr, int epochs) {
        this.dataPath = dataPath;
        this.batchSize = batchSize;
        this.lr = lr;
        this.epochs = epochs;
    }

    /** Train the downloaded model file, save to outPath, report via callback. */
    public void submit(int roundIdx, String modelPath, String outPath, long seed,
                       OnRoundDone callback) {
        pool.execute(() -> {
            if (stopping) {
                return; // a round queued behind shutdown must not train
            }
            long h = NativeFedMLTrainer.create(modelPath, dataPath, batchSize, lr,
                                               epochs, seed);
            if (h == 0) {
                callback.onRoundFailed(roundIdx, NativeFedMLTrainer.lastError());
                return;
            }
            synchronized (handleLock) {
                activeHandle = h;
                if (stopping) {
                    // shutdown raced the create window: stop before training
                    NativeFedMLTrainer.stop(h);
                }
            }
            try {
                if (NativeFedMLTrainer.train(h) != 0
                        || NativeFedMLTrainer.save(h, outPath) != 0) {
                    callback.onRoundFailed(roundIdx, NativeFedMLTrainer.lastError());
                    return;
                }
                long[] el = NativeFedMLTrainer.epochLoss(h);
                double loss = el.length == 2 ? el[1] / 1e6 : Double.NaN;
                callback.onRoundDone(
                        roundIdx,
                        new RoundResult(outPath, NativeFedMLTrainer.numSamples(h), loss));
            } finally {
                synchronized (handleLock) {
                    activeHandle = 0;
                    NativeFedMLTrainer.destroy(h);
                }
            }
        });
    }

    /** Cooperative stop of the in-flight round; queued rounds never start.
     *  BLOCKS (up to 10s) so the in-flight round resolves and its callback
     *  fires BEFORE the caller reports completion — do not call on a UI
     *  thread (FedEdgeManager.stop documents the same). */
    public void shutdown() {
        stopping = true;
        synchronized (handleLock) {
            if (activeHandle != 0) {
                // exits at the next batch boundary; handle cannot be
                // destroyed concurrently (worker holds this lock for it)
                NativeFedMLTrainer.stop(activeHandle);
            }
        }
        pool.shutdown();
        try {
            pool.awaitTermination(10, TimeUnit.SECONDS);
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
        }
    }
}
