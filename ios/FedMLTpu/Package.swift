// swift-tools-version:5.5
// FedMLTpu — Swift binding to the fedml_tpu native edge runtime.
// The C target vendors the canonical C ABI header (native/include/
// fedml_capi.h — byte-identity asserted by tests/test_ios_package.py);
// link libfedml_edge built from native/ for the target platform.
import PackageDescription

let package = Package(
    name: "FedMLTpu",
    products: [
        .library(name: "FedMLTpu", targets: ["FedMLTpu"]),
    ],
    targets: [
        .systemLibrary(name: "CFedML", path: "Sources/CFedML"),
        .target(
            name: "FedMLTpu",
            dependencies: ["CFedML"],
            path: "Sources/FedMLTpu",
            linkerSettings: [.linkedLibrary("fedml_edge")]
        ),
    ]
)
