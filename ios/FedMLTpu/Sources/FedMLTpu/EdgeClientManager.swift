// The FL client scheduler for iOS — the Swift twin of
// ai.fedml.tpu.ClientManager (Java) and the Python fake device
// (fedml_tpu/cross_device/fake_device.py), walking the cross-device round
// protocol over the broker wire:
//
// 1. connection ready -> C2S_CLIENT_STATUS ONLINE (handshake);
// 2. S2C_CHECK_CLIENT_STATUS -> re-announce ONLINE;
// 3. S2C_INIT_CONFIG / S2C_SYNC_MODEL_TO_CLIENT -> train the downloaded
//    model FILE with the native runtime, upload the trained file with the
//    ROUND TAG and the sample count;
// 4. S2C_FINISH -> stop.

import Foundation

public final class EdgeClientManager {
    public typealias OnRoundCompleted = (_ roundIdx: Int, _ loss: Double,
                                         _ numSamples: Int64) -> Void
    public typealias OnFinished = (_ roundsTrained: Int) -> Void

    /// Late-bound message handler: the connection needs a callback at
    /// construction, the callback needs self — the box breaks the cycle.
    private final class HandlerBox {
        var fn: (String, Any?) -> Void = { _, _ in }
    }

    private let conn: BrokerConnection
    private let handlerBox = HandlerBox()
    private let runId: String
    private let rank: Int
    private let dataPath: String
    private let uploadDir: URL
    private let batchSize: Int32
    private let learningRate: Double
    private let epochs: Int32
    private let queue = DispatchQueue(label: "fedml-train")
    private let finishLock = NSLock()  // NOT the train queue: finish() must
    private var roundsTrained = 0      // be safe from its own callbacks
    private var finished = false
    public var onRoundCompleted: OnRoundCompleted?
    public var onFinished: OnFinished?

    public init(host: String, port: Int32, runId: String, rank: Int,
                dataPath: String, uploadDir: URL, batchSize: Int32 = 32,
                learningRate: Double = 0.1, epochs: Int32 = 1) throws {
        self.runId = runId
        self.rank = rank
        self.dataPath = dataPath
        self.uploadDir = uploadDir
        self.batchSize = batchSize
        self.learningRate = learningRate
        self.epochs = epochs
        try FileManager.default.createDirectory(at: uploadDir,
                                                withIntermediateDirectories: true)
        let box = handlerBox
        conn = try BrokerConnection(host: host, port: port) { topic, payload in
            box.fn(topic, payload)
        }
        box.fn = { [weak self] topic, payload in
            self?.dispatch(topic: topic, payload: payload)
        }
        conn.onConnectionLost = { [weak self] in
            FileHandle.standardError.write(
                Data("fedml broker connection lost: leaving the run\n".utf8))
            self?.finish()
        }
        let will: [String: Any] = ["rank": rank,
                                   "status": MessageDefine.CLIENT_STATUS_OFFLINE]
        try conn.setLastWill(statusTopic(), jsonString(will))
        try conn.subscribe("fedml/\(runId)/#")
    }

    /// Join the run (announces ONLINE; the same bootstrap contract every
    /// comm manager follows on connection_ready).
    public func start() {
        announceOnline()
    }

    /// Leave early; the server's straggler tolerance covers the missing
    /// upload.  Safe to call from any thread.
    public func stop() {
        finish()
    }

    // MARK: - protocol

    private func topic(toServer: Bool) -> String {
        toServer ? "fedml/\(runId)/\(rank)/0" : "fedml/\(runId)/0/\(rank)"
    }

    private func statusTopic() -> String {
        "fedml/\(runId)/status"
    }

    private func dispatch(topic: String, payload: Any?) {
        let parts = topic.split(separator: "/").map(String.init)
        guard parts.count == 4, parts[3] == String(rank),
              let msg = payload as? [String: Any] else { return }
        let type = String(describing: msg[MessageDefine.MSG_ARG_KEY_TYPE] ?? "")
        switch type {
        case String(MessageDefine.MSG_TYPE_S2C_CHECK_CLIENT_STATUS):
            announceOnline()
        case String(MessageDefine.MSG_TYPE_S2C_INIT_CONFIG),
             String(MessageDefine.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT):
            onModel(msg)
        case String(MessageDefine.MSG_TYPE_S2C_FINISH):
            finish()
        default:
            break
        }
    }

    private func announceOnline() {
        sendOrWarn([
            MessageDefine.MSG_ARG_KEY_TYPE:
                String(MessageDefine.MSG_TYPE_C2S_CLIENT_STATUS),
            MessageDefine.MSG_ARG_KEY_SENDER: rank,
            MessageDefine.MSG_ARG_KEY_RECEIVER: 0,
            MessageDefine.MSG_ARG_KEY_CLIENT_STATUS:
                MessageDefine.CLIENT_STATUS_ONLINE,
        ])
    }

    private func onModel(_ msg: [String: Any]) {
        guard let modelFile = msg[MessageDefine.MSG_ARG_KEY_MODEL_PARAMS_FILE]
                as? String else { return }
        let roundIdx = (msg[MessageDefine.MSG_ARG_KEY_ROUND_INDEX] as? Int) ?? 0
        // train off the receive thread (rounds take seconds on-device)
        queue.async { [weak self] in
            guard let self = self, !self.isFinished() else { return }
            let out = self.uploadDir
                .appendingPathComponent("model_r\(roundIdx)_c\(self.rank).ftem").path
            do {
                // seed matches the Java/Python devices: (round, rank)
                let trainer = try FedMLTrainer(
                    modelPath: modelFile, dataPath: self.dataPath,
                    batchSize: self.batchSize, learningRate: self.learningRate,
                    epochs: self.epochs,
                    seed: UInt64(roundIdx * 1000 + self.rank))
                try trainer.train()
                try trainer.save(to: out)
                self.roundsTrained += 1
                self.sendOrWarn([
                    MessageDefine.MSG_ARG_KEY_TYPE:
                        String(MessageDefine.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER),
                    MessageDefine.MSG_ARG_KEY_SENDER: self.rank,
                    MessageDefine.MSG_ARG_KEY_RECEIVER: 0,
                    MessageDefine.MSG_ARG_KEY_ROUND_INDEX: roundIdx,
                    MessageDefine.MSG_ARG_KEY_MODEL_PARAMS_FILE: out,
                    MessageDefine.MSG_ARG_KEY_NUM_SAMPLES: Int(trainer.numSamples),
                ])
                self.onRoundCompleted?(roundIdx, trainer.lastEpochLoss.loss,
                                       trainer.numSamples)
            } catch {
                // no upload: a straggler-tolerant server closes without us
                FileHandle.standardError.write(
                    Data("fedml round \(roundIdx) failed on-device: \(error)\n".utf8))
            }
        }
    }

    private func isFinished() -> Bool {
        finishLock.lock()
        defer { finishLock.unlock() }
        return finished
    }

    private func finish() {
        // idempotent: reachable from S2C_FINISH, connection loss, stop(),
        // and the app's own callbacks (a queue.sync guard would deadlock a
        // stop() issued from onRoundCompleted, which runs on the train queue)
        finishLock.lock()
        let first = !finished
        finished = true
        finishLock.unlock()
        guard first else { return }
        conn.disconnect()
        onFinished?(roundsTrained)
    }

    private func sendOrWarn(_ params: [String: Any]) {
        do {
            try conn.publish(topic(toServer: true), params)
        } catch {
            FileHandle.standardError.write(
                Data("fedml send failed: \(error)\n".utf8))
        }
    }

    private func jsonString(_ obj: [String: Any]) -> String {
        guard let d = try? JSONSerialization.data(withJSONObject: obj),
              let s = String(data: d, encoding: .utf8) else { return "{}" }
        return s
    }
}
