// The FL round message vocabulary — field-for-field mirror of the Python
// contract (fedml_tpu/cross_device/message_define.py) and the Java
// MessageDefine.java.  tests/test_ios_package.py parses this file and
// asserts every constant equals its Python twin, so the three sides
// cannot drift silently.

public enum MessageDefine {
    // server -> client
    public static let MSG_TYPE_S2C_INIT_CONFIG = 1
    public static let MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    public static let MSG_TYPE_S2C_CHECK_CLIENT_STATUS = 6
    public static let MSG_TYPE_S2C_FINISH = 7

    // client -> server
    public static let MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
    public static let MSG_TYPE_C2S_SEND_STATS_TO_SERVER = 4
    public static let MSG_TYPE_C2S_CLIENT_STATUS = 5

    public static let MSG_ARG_KEY_TYPE = "msg_type"
    public static let MSG_ARG_KEY_SENDER = "sender"
    public static let MSG_ARG_KEY_RECEIVER = "receiver"

    public static let MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    public static let MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    public static let MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url"
    public static let MSG_ARG_KEY_MODEL_PARAMS_FILE = "model_params_file"
    public static let MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    public static let MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    public static let MSG_ARG_KEY_ROUND_INDEX = "round_idx"

    // reliability headers (additive wire change): per-incarnation message id
    // ("rank:nonce:seq") for ack/dedup, and the client incarnation epoch the
    // server uses to recognise a mid-run rejoin and resync the model
    public static let MSG_ARG_KEY_MSG_ID = "msg_id"
    public static let MSG_ARG_KEY_CLIENT_EPOCH = "client_epoch"

    public static let MSG_ARG_KEY_TRAIN_CORRECT = "train_correct"
    public static let MSG_ARG_KEY_TRAIN_ERROR = "train_error"
    public static let MSG_ARG_KEY_TRAIN_NUM = "train_num_sample"

    public static let CLIENT_STATUS_OFFLINE = "OFFLINE"
    public static let CLIENT_STATUS_IDLE = "IDLE"
    public static let CLIENT_STATUS_ONLINE = "ONLINE"

    /// Local pseudo-message raised once the wire is up.
    public static let MSG_TYPE_CONNECTION_READY = "connection_ready"
}
