// The broker wire: 4-byte big-endian length + UTF-8 JSON dict frames, ops
// SUB / UNSUB / PUB / WILL / DISCONNECT, deliveries arriving as MSG frames —
// the Swift twin of ai.fedml.tpu.BrokerConnection (Java) over the same
// JSON interop encoding the fedml_tpu broker sniffs per connection
// (fedml_tpu/core/distributed/communication/mqtt_s3/broker.py).
//
// Close semantics mirror the Java/Python clients: DISCONNECT + half-close
// (FIN) + drain inbound to EOF + close.  An abrupt close with undrained
// wildcard deliveries in our receive buffer sends a TCP RST, and an RST
// discards our still-unread frames at the broker — it can lose the tail of
// our own just-published uploads.
//
// Raw-fd lifecycle: the RECEIVE THREAD is the sole owner of close(fd) (it
// closes exactly once at loop exit and invalidates fd to -1 under the
// state lock) — no other thread ever closes, so a recycled fd number can
// never be written to or closed by a stale reference.  disconnect() only
// sends DISCONNECT + shutdown(SHUT_WR) and waits for the drain.

import Foundation

public final class BrokerConnection {
    public typealias OnMessage = (_ topic: String, _ payload: Any?) -> Void

    /// Frames larger than this are a desynced stream, not data (the control
    /// plane ships file PATHS; models never ride it).
    private static let maxFrame = 64 * 1024 * 1024

    // state lock: serializes writes AND guards fd/running
    private let lock = NSLock()
    private var fd: Int32  // -1 once the recv thread has closed it
    private var running = true

    private let onMessage: OnMessage
    private var recvThread: Thread?
    /// Invoked once from the receive thread if the wire dies while we did
    /// NOT call disconnect() — without it the app would wait forever with
    /// the failure visible only server-side (via the last will).
    public var onConnectionLost: (() -> Void)?

    public init(host: String, port: Int32, onMessage: @escaping OnMessage) throws {
        self.onMessage = onMessage
        fd = socket(AF_INET, Int32(SOCK_STREAM.rawValue), 0)
        guard fd >= 0 else {
            throw FedMLError.native("socket() failed: errno \(errno)")
        }
        var flag: Int32 = 1
        setsockopt(fd, Int32(IPPROTO_TCP), TCP_NODELAY, &flag,
                   socklen_t(MemoryLayout<Int32>.size))
        var addr = sockaddr_in()
        addr.sin_family = sa_family_t(AF_INET)
        addr.sin_port = in_port_t(UInt16(port).bigEndian)
        guard inet_pton(AF_INET, host, &addr.sin_addr) == 1 else {
            close(fd)
            throw FedMLError.native("bad broker host \(host)")
        }
        let rc = withUnsafePointer(to: &addr) {
            $0.withMemoryRebound(to: sockaddr.self, capacity: 1) {
                connect(fd, $0, socklen_t(MemoryLayout<sockaddr_in>.size))
            }
        }
        guard rc == 0 else {
            close(fd)
            throw FedMLError.native("connect to \(host):\(port) failed: errno \(errno)")
        }
        let t = Thread { [weak self] in self?.recvLoop() }
        t.name = "broker-recv"
        t.start()
        recvThread = t
    }

    public func subscribe(_ topic: String) throws {
        try send(frame("SUB", topic: topic, payload: nil))
    }

    public func unsubscribe(_ topic: String) throws {
        try send(frame("UNSUB", topic: topic, payload: nil))
    }

    public func publish(_ topic: String, _ payload: Any) throws {
        try send(frame("PUB", topic: topic, payload: payload))
    }

    /// Broker publishes this if the socket dies without a clean DISCONNECT.
    public func setLastWill(_ topic: String, _ payload: Any) throws {
        try send(frame("WILL", topic: topic, payload: payload))
    }

    /// Idempotent, callable from any thread including the receive thread
    /// (from inside an onMessage handler the loop resumes draining when the
    /// handler returns and performs the close at EOF).
    public func disconnect() {
        lock.lock()
        let wasRunning = running
        running = false
        if wasRunning, fd >= 0 {
            // fence DISCONNECT + FIN with the sends: a publish slipping in
            // between would make the broker break at DISCONNECT with unread
            // data -> RST right back at us
            if let data = try? Self.encodeFrame(["op": "DISCONNECT"]) {
                _ = writeAllLocked(data)
            }
            shutdown(fd, Int32(SHUT_WR))
        }
        lock.unlock()
        if let t = recvThread, Thread.current !== t {
            // the recv loop drains to broker EOF, then closes the fd (it is
            // the close's sole owner; a stuck drain leaks the fd rather than
            // risk closing under a blocked read)
            let deadline = Date().addingTimeInterval(5)
            while !t.isFinished && Date() < deadline {
                usleep(20_000)
            }
        }
    }

    // MARK: - framing

    private func frame(_ op: String, topic: String, payload: Any?) -> [String: Any] {
        var f: [String: Any] = ["op": op, "topic": topic]
        if let payload = payload { f["payload"] = payload }
        return f
    }

    private static func encodeFrame(_ obj: [String: Any]) throws -> Data {
        let body = try JSONSerialization.data(withJSONObject: obj)
        var n = UInt32(body.count).bigEndian
        var out = Data(bytes: &n, count: 4)
        out.append(body)
        return out
    }

    private func send(_ obj: [String: Any]) throws {
        let data = try Self.encodeFrame(obj)
        lock.lock()
        defer { lock.unlock() }
        guard running, fd >= 0 else {
            throw FedMLError.native("broker connection is closed")
        }
        guard writeAllLocked(data) else {
            throw FedMLError.native("broker send failed: errno \(errno)")
        }
    }

    /// (lock held) write the whole buffer, retrying on EINTR.
    private func writeAllLocked(_ data: Data) -> Bool {
        var sent = 0
        return data.withUnsafeBytes { (raw: UnsafeRawBufferPointer) in
            while sent < data.count {
                let n = write(fd, raw.baseAddress!.advanced(by: sent), data.count - sent)
                if n < 0 && errno == EINTR { continue }
                guard n > 0 else { return false }
                sent += n
            }
            return true
        }
    }

    private func readExact(_ sock: Int32, _ count: Int) -> Data? {
        var buf = Data(capacity: count)
        var chunk = [UInt8](repeating: 0, count: 64 * 1024)
        while buf.count < count {
            let want = min(chunk.count, count - buf.count)
            let n = read(sock, &chunk, want)
            if n < 0 && errno == EINTR { continue }  // signal, not death
            guard n > 0 else { return nil }
            buf.append(contentsOf: chunk[0..<n])
        }
        return buf
    }

    private func recvLoop() {
        // the recv thread reads its own fd without the lock: it is the only
        // thread that ever invalidates it, so the value it sees is live
        let sock = fd
        // reads to EOF even after disconnect() flips running: draining the
        // inbound stream keeps the close RST-free (see disconnect)
        while true {
            guard let hdr = readExact(sock, 4) else { break }
            let n = Int(UInt32(bigEndian: hdr.withUnsafeBytes { $0.load(as: UInt32.self) }))
            guard n <= Self.maxFrame, let body = readExact(sock, n) else {
                // oversized length = desynced stream: tear down so the
                // broker notices and publishes our last will
                break
            }
            guard
                let obj = try? JSONSerialization.jsonObject(with: body) as? [String: Any],
                obj["op"] as? String == "MSG",
                let topic = obj["topic"] as? String
            else {
                if (try? JSONSerialization.jsonObject(with: body)) == nil {
                    break  // undecodable frame: desynced, tear down
                }
                continue  // decodable non-MSG frame: ignore
            }
            onMessage(topic, obj["payload"])
        }
        // single close owner: invalidate fd first so no sender can touch a
        // recycled descriptor number, then close the real one
        lock.lock()
        let unclean = running
        running = false
        let sockToClose = fd
        fd = -1
        lock.unlock()
        if sockToClose >= 0 {
            close(sockToClose)
        }
        if unclean {
            onConnectionLost?()
        }
    }
}
