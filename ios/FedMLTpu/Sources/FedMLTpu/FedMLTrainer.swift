// FedMLTrainer — Swift wrapper over the native edge runtime's C ABI
// (CFedML / native/include/fedml_capi.h; runtime built from native/).
// Role of the reference's ios/ integration surface (the reference ships a
// README-only placeholder there; this package is a working binding).
//
// Model and data travel as FTEM files (fedml_tpu/cross_device/
// edge_model.py) — Swift hands paths to the native trainer exactly like
// the Java NativeFedMLTrainer and the Python ctypes binding.

import CFedML
import Foundation

public enum FedMLError: Error {
    case native(String)

    static func last() -> FedMLError {
        .native(String(cString: fedml_last_error()))
    }
}

/// One on-device local-training session (reference FedMLBaseTrainer
/// contract): create from a downloaded global-model FTEM file + the
/// device's local-data FTEM file, train, save the update for upload.
public final class FedMLTrainer {
    private var handle: UnsafeMutableRawPointer?

    public init(modelPath: String, dataPath: String, batchSize: Int32 = 32,
                learningRate: Double = 0.1, epochs: Int32 = 1,
                seed: UInt64 = 0) throws {
        handle = fedml_trainer_create(modelPath, dataPath, batchSize,
                                      learningRate, epochs, seed)
        if handle == nil {
            throw FedMLError.last()
        }
    }

    deinit {
        if let h = handle {
            fedml_trainer_destroy(h)
        }
    }

    public func train() throws {
        guard fedml_trainer_train(handle) == 0 else {
            throw FedMLError.last()
        }
    }

    public func save(to outPath: String) throws {
        guard fedml_trainer_save(handle, outPath) == 0 else {
            throw FedMLError.last()
        }
    }

    public func evaluate() throws -> (accuracy: Double, loss: Double) {
        var acc = 0.0
        var loss = 0.0
        guard fedml_trainer_eval(handle, &acc, &loss) == 0 else {
            throw FedMLError.last()
        }
        return (acc, loss)
    }

    public var lastEpochLoss: (epoch: Int32, loss: Double) {
        var epoch: Int32 = 0
        var loss = 0.0
        fedml_trainer_epoch_loss(handle, &epoch, &loss)
        return (epoch, loss)
    }

    public var numSamples: Int64 {
        fedml_trainer_num_samples(handle)
    }

    /// Cooperative stop: the native loop exits at the next batch boundary.
    public func stop() {
        fedml_trainer_stop(handle)
    }
}

/// LightSecAgg on-device client: train + upload a MASKED model so the
/// server only ever sees the aggregate (mirrors the Java clientCreate/
/// clientSaveMasked leg and fedml_tpu/cross_silo/lightsecagg).
public final class FedMLSecureClient {
    private var handle: UnsafeMutableRawPointer?

    public init(modelPath: String, dataPath: String, batchSize: Int32 = 32,
                learningRate: Double = 0.1, epochs: Int32 = 1,
                seed: UInt64 = 0) throws {
        handle = fedml_client_create(modelPath, dataPath, batchSize,
                                     learningRate, epochs, seed)
        if handle == nil {
            throw FedMLError.last()
        }
    }

    deinit {
        if let h = handle {
            fedml_client_destroy(h)
        }
    }

    public func train() throws {
        guard fedml_client_train(handle) == 0 else {
            throw FedMLError.last()
        }
    }

    public func saveMaskedModel(qBits: Int32, maskSeed: UInt64,
                                to outPath: String) throws {
        guard fedml_client_save_masked_model(handle, qBits, maskSeed,
                                             outPath) == 0 else {
            throw FedMLError.last()
        }
    }

    public var maskDimension: Int64 {
        fedml_client_mask_dim(handle)
    }

    /// LCC-encode this client's mask into n shares ([n * chunk] int64).
    public func encodeMask(n: Int32, t: Int32, u: Int32,
                           maskSeed: UInt64) throws -> [Int64] {
        let chunk = fedml_lsa_chunk(Int32(maskDimension), t, u)
        guard chunk > 0, n > 0 else {
            // fedml_lsa_chunk returns -1 for invalid (t, u): surface it as
            // the thrown error this API promises, not a negative-count trap
            throw FedMLError.native("invalid LightSecAgg parameters: need "
                                    + "t < u <= n (n=\(n), t=\(t), u=\(u))")
        }
        var out = [Int64](repeating: 0, count: Int(n) * Int(chunk))
        let rc = out.withUnsafeMutableBufferPointer {
            fedml_client_encode_mask(handle, n, t, u, maskSeed, $0.baseAddress)
        }
        guard rc == 0 else {
            throw FedMLError.last()
        }
        return out
    }
}
