/* fedml_capi.h — the stable C ABI of the native edge runtime.
 *
 * THE integration surface for every host binding: Python (ctypes,
 * fedml_tpu/native/__init__.py), Android/Java (JNI shim
 * native/android/fedml_jni.cpp), iOS/Swift (ios/FedMLTpu — its vendored
 * copy of this header is asserted byte-identical by
 * tests/test_ios_package.py).  capi.cpp includes this header, so any
 * signature drift between declaration and definition is a COMPILE error in
 * the native build.
 *
 * Conventions: functions returning int yield 0 on success, -1 on error
 * with the message in fedml_last_error() (thread-local); create functions
 * return NULL on error.  C++ exceptions never cross this boundary.
 */
#ifndef FEDML_CAPI_H
#define FEDML_CAPI_H

#ifdef __cplusplus
extern "C" {
#endif

const char* fedml_last_error(void);

/* -- dataset converters (device-side idx/bin -> FTEM) -------------------- */
int fedml_mnist_idx_to_ftem(const char* images, const char* labels,
                            const char* out, int limit);
int fedml_cifar10_bin_to_ftem(const char* bin_path, const char* out, int limit);

/* -- trainer (reference FedMLBaseTrainer contract) ------------------------ */
void* fedml_trainer_create(const char* model_path, const char* data_path,
                           int batch, double lr, int epochs,
                           unsigned long long seed);
typedef void (*fedml_progress_cb)(int epoch, double loss);
void fedml_trainer_set_callback(void* h, fedml_progress_cb cb);
int fedml_trainer_train(void* h);
void fedml_trainer_epoch_loss(void* h, int* epoch, double* loss);
void fedml_trainer_stop(void* h);
long long fedml_trainer_num_samples(void* h);
int fedml_trainer_save(void* h, const char* out_path);
int fedml_trainer_eval(void* h, double* acc, double* loss);
void fedml_trainer_destroy(void* h);

/* -- LightSecAgg primitives ----------------------------------------------- */
int fedml_lsa_chunk(int d, int t, int u);
int fedml_lsa_mask_encoding(int d, int n, int t, int u, const long long* mask,
                            unsigned long long seed, long long* out);
int fedml_lsa_aggregate_decode(const long long* rows, const int* ids,
                               int n_ids, int t, int u, int d, int chunk,
                               long long* out);

/* -- client manager (trainer + LightSecAgg on-device leg) ----------------- */
void* fedml_client_create(const char* model_path, const char* data_path,
                          int batch, double lr, int epochs,
                          unsigned long long seed);
int fedml_client_train(void* h);
int fedml_client_save_model(void* h, const char* out_path);
int fedml_client_save_masked_model(void* h, int q_bits,
                                   unsigned long long mask_seed,
                                   const char* out_path);
long long fedml_client_mask_dim(void* h);
int fedml_client_encode_mask(void* h, int n, int t, int u,
                             unsigned long long mask_seed, long long* out);
void fedml_client_destroy(void* h);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* FEDML_CAPI_H */
