"""North-star benchmark: FedAvg ResNet-56 CIFAR-10, 100 simulated clients,
Parrot-XLA simulator (BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value = local-training samples/sec/chip (the throughput half of the
north-star; accuracy parity is covered by the test suite on real data when
mounted).  vs_baseline divides by A100_NCCL_SPS — the single-A100 NCCL
-simulator throughput for ResNet-56/CIFAR-10 b=64 fp32.  The reference
publishes no wall-clock numbers (BASELINE.md), so this constant is an
estimate from public A100 ResNet-56 training benchmarks; the >=8x-on-16-chips
target from BASELINE.json corresponds to vs_baseline >= 0.5 per chip.

Runs on the real TPU chip (default env). Main thread, single process — the
axon tunnel is not thread-safe (see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import json
import sys
import time

A100_NCCL_SPS = 2000.0  # estimated single-A100 NCCL-simulator samples/s


def main() -> None:
    import jax

    import fedml_tpu
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.xla.fed_sim import XLASimulator

    n_chips = len(jax.devices())
    args = Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "bench"},
            "data_args": {
                "dataset": "cifar10",
                "data_cache_dir": "./fedml_data",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
            },
            "model_args": {"model": "resnet56"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 100,
                "client_num_per_round": min(100, max(8, n_chips * 8)) if n_chips > 1 else 8,
                "comm_round": 6,  # round 0 compiles, round 1 uploads data; 2-5 are steady state
                "epochs": 1,
                "batch_size": 64,
                "client_optimizer": "sgd",
                "learning_rate": 0.001,
            },
            "validation_args": {"frequency_of_the_test": 0},  # 0 disables eval
            "comm_args": {"backend": "XLA"},
        }
    ).validate()
    args = fedml_tpu.init(args, should_init_logs=False)
    from fedml_tpu import data, models

    dataset, out_dim = data.load(args)
    model = models.create(args, out_dim)
    sim = XLASimulator(args, dataset, model)
    sim.train()

    # median per-round throughput over post-compile rounds: the steady-state
    # rate (compile + one-time dataset upload amortized out; see
    # XLASimulator.throughput for the exact semantics)
    sps = sim.throughput()["samples_per_sec"]
    sps_per_chip = sps / max(n_chips, 1)
    print(
        json.dumps(
            {
                "metric": "fedavg_resnet56_cifar10_100clients_samples_per_sec_per_chip",
                "value": round(sps_per_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(sps_per_chip / A100_NCCL_SPS, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
