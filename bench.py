"""North-star benchmark: FedAvg ResNet-56 CIFAR-10, 100 simulated clients,
Parrot-XLA simulator (BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras},
stamped with the schema-2 provenance fields {"bench_schema", "mode":
full|degraded|failed, "degraded_reason" (non-full only), "git_rev"} that
``tools/perf_gate.py`` validates.  The one-line contract holds on EVERY
path: a crash or early exit still emits a ``mode: "failed"`` record before
the nonzero rc (r03-r05 left empty tails; never again).

value = local-training samples/sec/chip (the throughput half of the
north-star; accuracy parity is tracked in PARITY.md and the test suite).

vs_baseline divides by a MEASURED eager baseline: the same ResNet-56/CIFAR-10
b=64 fp32 local training executed the way the reference's NCCL simulator
executes it — a host loop dispatching one step per batch (per-batch kernel
launches, no cross-batch compilation) — on the SAME chip, measured in this
process right before the main run.  The reference publishes no wall-clock
numbers (BASELINE.md), so hardware-identical architecture-vs-architecture is
the honest comparison; the old hardcoded A100 estimate (2000 samples/s) is
kept as `vs_a100_estimate` for continuity with rounds 1-2.

Read vs_baseline as a CEILING ratio, not an apples-to-apples FL race: the
eager loop is pure back-to-back steps on two resident alternating batches —
no ragged clients, no per-client state resets, no aggregation, no per-step
data gather — i.e. the throughput ceiling of this chip for this model.  The
full in-mesh FL round (v5e, bf16, packed): 24.1k samples/s/chip ≈ 0.43 of
that ceiling; the measured remaining gap is per-step row-gather from the
HBM-resident dataset plus while_loop sequencing, paid in exchange for the
whole FL round (all clients + weighting + aggregation + server update)
compiling into ONE XLA program per round.

Also reported: achieved model TFLOP/s and MFU, from an analytic ResNet-56
cost (0.126 GFLOP forward x3 for training) — model FLOPs, not hardware
FLOPs, so MFU is comparable across implementations.  MFU divides by
PEAK_TFLOPS (bf16 peak of one TPU v5e chip).

The main run uses bf16 compute (fp32 params).  Client-chunk vmap stays OFF:
the v5e ablation showed per-step time grows linearly with chunk size for
this model (bandwidth/lane-padding bound ops), so chunking only loses.

Runs on the real TPU chip (default env). Main thread, single process — the
axon tunnel is not thread-safe (see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

A100_NCCL_SPS = 2000.0  # rounds 1-2 comparison constant (estimated)
PEAK_TFLOPS = 197.0  # TPU v5e bf16 peak per chip
RESNET56_TRAIN_GFLOPS = 0.378  # analytic fallback: 0.126 GFLOP fwd x3

# record format version; tools/perf_gate.py validates stamped records and
# tests/test_perf_gate.py pins the two constants together so they can't
# drift.  Schema 2 = {bench_schema, mode: full|degraded|failed,
# degraded_reason (degraded/failed only), git_rev} on every metric line.
BENCH_SCHEMA = 2


def _git_rev() -> str:
    """Short rev of the measured tree, stamped into every metric line so a
    BENCH artifact is attributable without the driver's wrapper context."""
    import subprocess

    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip()
    except Exception:
        pass
    return "unknown"


_emitted = False


def _emit(out: dict, mode: str) -> None:
    """THE stdout seam: every metric line leaves through here, stamped with
    the schema fields.  ``degraded_reason`` rides in ``out`` when the mode
    needs one."""
    global _emitted
    rec = dict(out)
    rec["bench_schema"] = BENCH_SCHEMA
    rec["mode"] = mode
    rec["git_rev"] = _git_rev()
    print(json.dumps(rec))  # lint_obs: allow — this IS the bench contract
    _emitted = True


def _bench_args(n_chips: int, compute_dtype: str = "bf16"):
    from fedml_tpu.arguments import Arguments

    return Arguments.from_dict(
        {
            "common_args": {"training_type": "simulation", "random_seed": 0, "run_id": "bench"},
            "data_args": {
                "dataset": "cifar10",
                "data_cache_dir": "./fedml_data",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
            },
            "model_args": {"model": "resnet56", "compute_dtype": compute_dtype},
            "train_args": {
                # packed ragged-client round + 32 clients/round: measured on
                # the v5e chip, packed-32 = 24.1k sps/chip vs padded-8 =
                # 10.8k (padding waste eliminated + fixed per-round dispatch
                # cost amortized over 4x the round compute)
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 100,
                "client_num_per_round": min(100, max(32, n_chips * 8)),
                "xla_pack": True,
                "comm_round": 6,  # round 0 compiles, round 1 uploads data; 2-5 are steady state
                "epochs": 1,
                "batch_size": 64,
                "client_optimizer": "sgd",
                "learning_rate": 0.001,
            },
            "validation_args": {"frequency_of_the_test": 0},  # 0 disables eval
            "comm_args": {"backend": "XLA"},
        }
    ).validate()


def _measure_eager_baseline(args, dataset, n_batches: int = 24) -> float:
    """Reference-architecture baseline on the same chip: fp32, one jitted
    step per batch dispatched from a python loop (how a torch/NCCL per-batch
    trainer executes), no cross-batch compilation, batch 64."""
    import jax
    import jax.numpy as jnp
    import optax

    import fedml_tpu
    from fedml_tpu.ml.engine.train import init_variables, softmax_ce_loss

    model = fedml_tpu.models.create(args, 10)  # fp32: args copy has fp32 dtype
    x_glob, y_glob = dataset[2]
    b = int(args.batch_size)
    x = jnp.asarray(x_glob[: b * 2])
    y = jnp.asarray(y_glob[: b * 2])
    variables = init_variables(model, x[:1], seed=0)
    tx = optax.sgd(float(args.learning_rate))
    opt_state = tx.init(variables["params"])

    def step(variables, opt_state, bx, by):
        def loss_fn(params):
            out = model.apply(dict(variables, params=params), bx, train=True,
                              rngs={"dropout": jax.random.PRNGKey(0)})
            loss, _ = softmax_ce_loss(out, by, jnp.ones(by.shape[0]))
            return loss

        grads = jax.grad(loss_fn)(variables["params"])
        updates, opt_state = tx.update(grads, opt_state, variables["params"])
        params = optax.apply_updates(variables["params"], updates)
        return dict(variables, params=params), opt_state

    jstep = jax.jit(step)
    # warmup/compile
    variables, opt_state = jstep(variables, opt_state, x[:b], y[:b])
    jax.block_until_ready(variables)
    t0 = time.time()
    for i in range(n_batches):
        off = (i % 2) * b
        variables, opt_state = jstep(variables, opt_state, x[off:off + b], y[off:off + b])
    jax.block_until_ready(variables)
    dt = time.time() - t0
    return n_batches * b / max(dt, 1e-9)


# the packed round's execution-strategy levers, shared with
# tools/perf_sweep.py so the two grids cannot drift
AUTOTUNE_VARIANTS = (
    {},
    {"xla_pregather": True},
    {"xla_stream": "scan"},
    {"xla_pregather": True, "xla_stream": "scan"},
)


def _autotune(args, dataset, model):
    """Pick the fastest round execution strategy ON THIS CHIP before the
    real measurement: AUTOTUNE_VARIANTS at the bench config, 5 rounds each
    (round 0 compiles; throughput() medians rounds 1-4, riding out the
    round-1 dataset upload).  The levers are equivalence-tested
    (tests/test_packed_round.py) but their win is hardware-dependent —
    self-tuning lands the measured winner in the BENCH artifact even when
    no interactive chip session was possible beforehand.  Disable with
    BENCH_AUTOTUNE=0.  Returns ``(winning override dict, winning simulator
    or None)``.  Only ONE candidate simulator is ever alive (peak HBM stays
    one simulator, exactly as without autotune), so the compiled winner can
    only be handed back when it is the LAST variant trained — which the
    grid orders it to be in the expected case (both levers on); otherwise
    the caller rebuilds it (one compile, the pre-reuse behavior).  ``(None,
    None)`` if every variant (including the baseline) failed."""
    import copy

    from fedml_tpu.simulation.xla.fed_sim import XLASimulator

    best = (0.0, None)
    sim = None
    last_overrides = None
    for overrides in AUTOTUNE_VARIANTS:
        a = copy.deepcopy(args)
        a.comm_round = 5
        for k, v in overrides.items():
            setattr(a, k, v)
        try:
            sim = None  # free the previous candidate BEFORE building the next
            sim = XLASimulator(a, dataset, model)
            sim.train()
            sps = sim.throughput()["samples_per_sec"]
            last_overrides = overrides
            print(f"autotune {overrides}: {sps:.1f} samples/s", file=sys.stderr)
        except Exception as e:
            # a broken lever must not kill the bench, but it must be VISIBLE
            # (an artifact claiming "baseline won" when the lever crashed
            # would mislead the next perf investigation)
            print(f"autotune {overrides}: FAILED ({e})", file=sys.stderr)
            sim = None  # never hand a failed variant's sim to the caller
            continue
        if best[1] is None or sps > best[0]:
            best = (sps, overrides)
    if best[1] is None:
        return None, None
    return best[1], (sim if last_overrides == best[1] else None)


# module-level so tests can substitute a fast fake probe (the real one pays
# a full jax import per attempt — minutes under a flaky tunnel, by design)
_PROBE_CODE = "import jax; print(len(jax.devices()))"


def _wait_for_backend() -> bool:
    """Bounded poll for the TPU tunnel before touching jax in-process.

    BENCH_r03/r04 were both lost to transient axon-tunnel outages because
    the first ``jax.devices()`` throw killed the bench.  Probe in a
    SUBPROCESS (the gentle pattern from tools/tpu_watch.sh — a failed
    in-process backend init is cached by jax and cannot be retried
    cleanly), every BENCH_WAIT_POLL_S seconds for up to BENCH_WAIT_MIN
    minutes, each attempt bounded by BENCH_PROBE_TIMEOUT_S (default 300 —
    tests shrink it so a hung tunnel can't eat the suite's budget).
    Returns True once a probe sees a device, False when the
    window closes (the bench then exits rc=1, as before — but only after
    genuinely riding out a hiccup window the driver run tolerates).
    """
    import subprocess

    wait_min = float(os.environ.get("BENCH_WAIT_MIN", "15"))
    poll_s = float(os.environ.get("BENCH_WAIT_POLL_S", "30"))
    deadline = time.time() + wait_min * 60
    attempt = 0
    while True:
        attempt += 1
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True,
                timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "300")),
            )
            if r.returncode == 0 and r.stdout.strip():
                if attempt > 1:
                    print(f"backend probe ok after {attempt} attempts", file=sys.stderr)
                return True
            tail = (r.stderr or "").strip().splitlines()
            msg = tail[-1] if tail else f"rc={r.returncode}"
        except subprocess.TimeoutExpired:
            msg = "probe subprocess timed out"
        if time.time() >= deadline:
            print(f"backend still unavailable after {wait_min:.0f} min: {msg}",
                  file=sys.stderr)
            return False
        print(f"backend probe {attempt} failed ({msg}); retrying in {poll_s:.0f}s",
              file=sys.stderr)
        time.sleep(poll_s)


def main() -> int | None:
    """Exactly-one-JSON-line wrapper: whatever ``_main`` does — return,
    raise, lose the backend — stdout carries at least (and on the primary
    path exactly) one schema-stamped metric line.  r03-r05 died with EMPTY
    tails; a crash now leaves a ``mode: "failed"`` record naming the
    exception, and the nonzero exit still marks the round dark for
    ``tools/perf_gate.py``."""
    try:
        rc = _main()
    except BaseException as e:
        if not _emitted:
            _emit({"metric": "bench_failed", "value": None, "unit": "none",
                   "degraded_reason": f"unhandled {type(e).__name__}: {e}"},
                  "failed")
        raise
    if rc and not _emitted:
        _emit({"metric": "bench_failed", "value": None, "unit": "none",
               "degraded_reason": f"bench exited rc={rc} without a metric "
                                  "line"}, "failed")
    return rc


def _main() -> int | None:
    degraded_reason = None
    if not _wait_for_backend():
        if os.environ.get("BENCH_REQUIRE_TPU") == "1":
            return 1
        # r03-r05 produced empty BENCH artifacts this way: no backend meant
        # no JSON line at all, and three rounds of perf work went unmeasured.
        # Degrade to a CPU run that still reports the RELATIVE keys (agg
        # step host vs compiled, obs overhead) — trend data, not absolutes.
        os.environ["JAX_PLATFORMS"] = "cpu"
        degraded_reason = "backend probe failed"

    import jax

    import fedml_tpu
    from fedml_tpu.simulation.xla.fed_sim import XLASimulator

    try:
        # persistent XLA compile cache: a re-run on the same chip (or a
        # bench retry after a tunnel hiccup) skips the big compiles
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception as e:  # cache support varies by backend; never fatal
        print(f"compilation cache unavailable: {e}", file=sys.stderr)

    try:
        n_chips = len(jax.devices())
    except Exception as e:
        # the tunnel answered the probe but flapped before our own init; a
        # failed in-process init is cached by jax, so re-exec once (the
        # fresh process gets a full probe window again)
        if os.environ.get("BENCH_REEXECED") != "1":
            print(f"in-process backend init failed after probe ok ({e}); re-exec",
                  file=sys.stderr)
            os.environ["BENCH_REEXECED"] = "1"
            sys.stderr.flush()
            os.execv(sys.executable, [sys.executable] + sys.argv)
        raise
    if (degraded_reason is None and jax.default_backend() == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU_FULL") != "1"):
        # the probe succeeds on a CPU-only box (jax falls back silently);
        # running ResNet-56/CIFAR there would take hours and measure nothing
        # comparable — report the relative keys instead
        degraded_reason = "no accelerator (cpu backend)"
    if degraded_reason is not None:
        return _run_degraded(degraded_reason)
    args = fedml_tpu.init(_bench_args(n_chips), should_init_logs=False)
    from fedml_tpu import data

    dataset, out_dim = data.load(args)

    # measured same-chip eager (reference-architecture) baseline, fp32
    base_args = _bench_args(n_chips, compute_dtype="fp32")
    eager_sps = _measure_eager_baseline(base_args, dataset)

    model = fedml_tpu.models.create(args, out_dim)
    autotune_on = os.environ.get("BENCH_AUTOTUNE", "1") != "0"
    tuned, sim = _autotune(args, dataset, model) if autotune_on else (None, None)
    for k, v in (tuned or {}).items():
        setattr(args, k, v)
    if sim is not None:
        # keep training the autotune winner: its round fn is already
        # compiled, so the extra rounds below are pure steady-state
        # measurement (one big XLA compile saved — matters when the chip
        # window is short).  train() re-runs rounds 0..comm_round-1 and
        # APPENDS to round_times; throughput() medians over all recorded
        # post-warmup rounds.
        sim.args.comm_round = int(args.comm_round)
        sim.train()
    else:
        sim = XLASimulator(args, dataset, model)
        sim.train()

    # median per-round throughput over post-compile rounds: the steady-state
    # rate (compile + one-time dataset upload amortized out; see
    # XLASimulator.throughput for the exact semantics)
    sps = sim.throughput()["samples_per_sec"]
    sps_per_chip = sps / max(n_chips, 1)

    obs_overhead = _measure_obs_overhead(sim)

    gflops_sample = RESNET56_TRAIN_GFLOPS
    achieved_tflops = sps_per_chip * gflops_sample / 1e3
    out = {
        "metric": "fedavg_resnet56_cifar10_100clients_samples_per_sec_per_chip",
        "value": round(sps_per_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(sps_per_chip / max(eager_sps, 1e-9), 4),
        "eager_baseline_sps": round(eager_sps, 2),
        "vs_a100_estimate": round(sps_per_chip / A100_NCCL_SPS, 4),
        "achieved_tflops": round(achieved_tflops, 3),
        "mfu": round(achieved_tflops / PEAK_TFLOPS, 5),
        "compute_dtype": "bf16",
    }
    if autotune_on:
        # {} = baseline won; {...} = winning flags; null = every variant
        # failed (distinct from BENCH_AUTOTUNE=0, where the key is absent)
        out["autotuned"] = tuned
    out.update(obs_overhead)
    out.update(_measure_telemetry_overhead())
    out.update(_measure_agg_step())
    out.update(_measure_round_update())
    out.update(_measure_defended_round())
    out.update(_measure_remesh())
    out.update(_measure_upload_saturation())
    out.update(_measure_fanin())
    out.update(_measure_async_throughput())
    out.update(_measure_chunked())
    out.update(_measure_health_overhead())
    out.update(_measure_round_throughput())
    if os.environ.get("BENCH_SP"):
        out["sp_samples_per_sec"] = round(_measure_sp(args, dataset), 2)
    _emit(out, "full")
    if os.environ.get("BENCH_TRANSFORMER"):
        # second opt-in metric line: the transformer MFU proof-point.
        # PERF.md's analysis says ResNet-56's small convs cap MFU at ~11%
        # regardless of round structure; this line substantiates "high MFU
        # is reachable on the transformer stack" with a measured number.
        _emit(_measure_transformer(), "full")


def _synthetic_updates(n_clients: int, seed: int = 0):
    """Seeded synthetic client deltas shaped like a small MLP — enough
    structure (matrices, vectors, a scalar) to exercise the partition rules
    without making the CPU-degraded run slow."""
    import jax.numpy as jnp
    import numpy as np

    shapes = {
        "layer1/kernel": (256, 256), "layer1/bias": (256,),
        "layer2/kernel": (256, 256), "layer2/bias": (256,),
        "head/kernel": (256, 10), "head/bias": (10,),
        "scale": (),
    }
    rng = np.random.default_rng(seed)
    updates = []
    for _ in range(n_clients):
        tree = {k: jnp.asarray(rng.standard_normal(s), jnp.float32)
                for k, s in shapes.items()}
        updates.append((float(rng.integers(16, 256)), tree))
    return updates


def _measure_agg_step() -> dict:
    """The aggregation-plane relative keys: median host-loop vs compiled
    reduction time over the same seeded synthetic deltas.  Emitted on BOTH
    the full-TPU and CPU-degraded metric lines, so the agg-plane trend
    survives a dark chip window.  Failures degrade to empty keys."""
    import numpy as np

    try:
        import jax

        from fedml_tpu.core.aggregate import weighted_mean
        from fedml_tpu.parallel.agg_plane import CompiledAggPlane

        n = int(os.environ.get("BENCH_AGG_CLIENTS", "32"))
        reps = int(os.environ.get("BENCH_AGG_REPS", "5"))
        updates = _synthetic_updates(n)

        def timed(fn):
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn())
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        host_s = timed(lambda: weighted_mean(updates))
        plane = CompiledAggPlane()
        plane.aggregate(updates)  # pay the compile outside the timing
        comp_s = timed(lambda: plane.aggregate(updates))
        return {
            "agg_step_host_s": round(host_s, 6),
            "agg_step_compiled_s": round(comp_s, 6),
            "agg_speedup": round(host_s / max(comp_s, 1e-9), 4),
            "agg_clients": n,
        }
    except Exception as e:
        print(f"agg step measurement failed: {e}", file=sys.stderr)
        return {}


def _measure_round_update() -> dict:
    """The sharded-server-state relative keys (server_state=sharded): median
    host-oracle round tail (reduce + FedAdam server step) vs the ONE-program
    sharded round update over the same seeded synthetic deltas, plus the
    broadcast wire cost of the full tree vs its largest shard slice.
    Emitted on BOTH the full-TPU and CPU-degraded metric lines.  Failures
    degrade to empty keys."""
    import numpy as np

    try:
        import jax
        import jax.numpy as jnp

        from fedml_tpu.core.aggregate import (host_server_round_update,
                                              make_host_round_step)
        from fedml_tpu.core.distributed.communication.serialization import (
            CachedPayload)
        from fedml_tpu.parallel.agg_plane import (ShardedRoundPlane,
                                                  _policy_tx,
                                                  broadcast_shards)

        n = int(os.environ.get("BENCH_AGG_CLIENTS", "32"))
        reps = int(os.environ.get("BENCH_AGG_REPS", "5"))
        n_shards = int(os.environ.get("BENCH_BCAST_SHARDS", "4"))
        updates = _synthetic_updates(n)
        rng = np.random.default_rng(7)
        params = {k: jnp.asarray(rng.standard_normal(np.shape(v)), jnp.float32)
                  for k, v in updates[0][1].items()}
        policy = ("adam", 0.1, 0.9)  # the FedAdam default server optimizer
        tx = _policy_tx(policy)
        opt_state = tx.init([v for v in jax.tree_util.tree_leaves(params)])
        step = make_host_round_step(tx)
        host_server_round_update(params, updates, tx, opt_state,
                                 step=step)  # pay the jit outside the timing

        def timed(fn):
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn())
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        host_s = timed(lambda: host_server_round_update(
            params, updates, tx, opt_state, step=step))
        plane = ShardedRoundPlane(policy=policy)
        out_tree = plane.round_update(params, updates)  # compile
        state = {"tree": out_tree}

        def compiled_once():
            state["tree"] = plane.round_update(state["tree"], updates)
            return state["tree"]

        comp_s = timed(compiled_once)
        bytes_full = len(CachedPayload(state["tree"]).wire_bytes())
        bytes_sharded = max(
            len(CachedPayload(s).wire_bytes())
            for s in broadcast_shards(state["tree"], n_shards))
        return {
            "round_update_host_s": round(host_s, 6),
            "round_update_compiled_s": round(comp_s, 6),
            "round_update_speedup": round(host_s / max(comp_s, 1e-9), 4),
            "broadcast_bytes_full": bytes_full,
            "broadcast_bytes_sharded": bytes_sharded,
            "broadcast_shrink": round(bytes_full / max(bytes_sharded, 1), 4),
            "round_update_policy": policy[0],
        }
    except Exception as e:
        print(f"round update measurement failed: {e}", file=sys.stderr)
        return {}


def _measure_defended_round() -> dict:
    """The defense/privacy-plane keys (PR 17) over the same seeded
    synthetic deltas —

    * ``defended_round_speedup``: median host-oracle defended round
      (multi-Krum + Gaussian DP via ``host_secure_round_update``) vs the
      ONE staged compiled program (``ShardedRoundPlane`` with the fused
      defense + DP stages).  Higher is better (RELATIVE band).
    * ``dp_overhead_frac``: the compiled round with the DP stage on vs
      the identical round without it — what per-client clip + noise
      costs inside the fused program.  Lower is better (budget cap).
    * ``secagg_mask_s``: one full SecAgg cycle — quantize + pairwise
      mask, submit, finite-field fold, unmask — on the compiled field
      plane.  Lower is better (LATENCY band).

    Emitted on BOTH the full-TPU and CPU-degraded metric lines.
    Failures degrade to empty keys."""
    import numpy as np

    out = {}
    try:
        import jax
        import jax.numpy as jnp

        from fedml_tpu.parallel.agg_plane import ShardedRoundPlane
        from fedml_tpu.parallel.sec_plane import host_secure_round_update

        n = int(os.environ.get("BENCH_AGG_CLIENTS", "32"))
        reps = int(os.environ.get("BENCH_AGG_REPS", "5"))
        updates = _synthetic_updates(n)
        rng = np.random.default_rng(7)
        params = {k: jnp.asarray(rng.standard_normal(np.shape(v)), jnp.float32)
                  for k, v in updates[0][1].items()}
        policy = ("adam", 0.1, 0.9)
        defense = ("krum", 1, max(1, n // 2))  # multi-Krum, half cohort
        dp = ("gaussian", 1.0, 0)
        sigma = 0.5

        def timed(fn):
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn())
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        host_secure_round_update(params, updates, policy=policy,
                                 defense=defense, dp=dp,
                                 dp_sigma=sigma)  # compile outside the timing
        host_s = timed(lambda: host_secure_round_update(
            params, updates, policy=policy, defense=defense, dp=dp,
            dp_sigma=sigma)[0])

        plane = ShardedRoundPlane(policy=policy, defense=defense, dp=dp)
        state = {"tree": plane.round_update(params, updates,
                                            dp_sigma=sigma), "round": 1}

        def staged_once():
            state["tree"] = plane.round_update(
                state["tree"], updates, round_idx=state["round"],
                dp_sigma=sigma)
            state["round"] += 1
            return state["tree"]

        comp_s = timed(staged_once)
        out.update({
            "defended_round_host_s": round(host_s, 6),
            "defended_round_compiled_s": round(comp_s, 6),
            "defended_round_speedup": round(host_s / max(comp_s, 1e-9), 4),
            "defended_round_defense": "multi_krum+gaussian_dp",
        })

        # DP stage overhead inside the fused program: same plane with and
        # without the stage
        plain = ShardedRoundPlane(policy=policy)
        pstate = {"tree": plain.round_update(params, updates)}

        def plain_once():
            pstate["tree"] = plain.round_update(pstate["tree"], updates)
            return pstate["tree"]

        plain_s = timed(plain_once)
        dp_plane = ShardedRoundPlane(policy=policy, dp=dp)
        dstate = {"tree": dp_plane.round_update(params, updates,
                                                dp_sigma=sigma), "round": 1}

        def dp_once():
            dstate["tree"] = dp_plane.round_update(
                dstate["tree"], updates, round_idx=dstate["round"],
                dp_sigma=sigma)
            dstate["round"] += 1
            return dstate["tree"]

        dp_s = timed(dp_once)
        out.update({
            "dp_round_s": round(dp_s, 6),
            "dp_overhead_frac": round(
                max(dp_s - plain_s, 0.0) / max(plain_s, 1e-9), 4),
        })
    except Exception as e:
        print(f"defended round measurement failed: {e}", file=sys.stderr)

    try:
        from fedml_tpu.core.mpc.dropout import SecAggRound

        reps = int(os.environ.get("BENCH_AGG_REPS", "5"))
        k = int(os.environ.get("BENCH_SECAGG_CLIENTS", "8"))
        rng = np.random.default_rng(11)
        vec = rng.standard_normal(int(
            os.environ.get("BENCH_SECAGG_DIM", "65536"))).astype(np.float64)

        def secagg_cycle():
            rnd = SecAggRound(n_clients=k, seed=3, plane="compiled")
            for i in range(k):
                rnd.submit(i, rnd.client_payload(i, vec))
            return rnd.unmask()

        secagg_cycle()  # pay the field-kernel compile outside the timing
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            secagg_cycle()
            ts.append(time.perf_counter() - t0)
        out.update({
            "secagg_mask_s": round(float(np.median(ts)), 6),
            "secagg_clients": k,
        })
    except Exception as e:
        print(f"secagg measurement failed: {e}", file=sys.stderr)
    return out


def _measure_remesh() -> dict:
    """The elastic-resize keys (PR 16): total downtime of an in-place
    ``ShardedRoundPlane.remesh`` — host-gather the resident params +
    optimizer state, re-shard onto a mesh with half the model axis, and
    warm-recompile the round program — plus the recompile slice alone.
    Lower is better (banded as ceilings in tools/perf_gate.py).  Emitted
    on BOTH the full-TPU and CPU-degraded metric lines; failures degrade
    to empty keys."""
    import numpy as np

    try:
        import jax
        import jax.numpy as jnp

        from fedml_tpu.parallel.agg_plane import ShardedRoundPlane
        from fedml_tpu.parallel.mesh import create_round_mesh

        devs = jax.devices()
        model = max(2, 1 << (len(devs).bit_length() - 1))  # largest pow2
        mesh_a = create_round_mesh(clients=1, model=model,
                                   devices=devs[:model])
        mesh_b = create_round_mesh(clients=1, model=max(1, model // 2),
                                   devices=devs[:max(1, model // 2)])
        n = int(os.environ.get("BENCH_AGG_CLIENTS", "32"))
        updates = _synthetic_updates(n)
        rng = np.random.default_rng(7)
        params = {k: jnp.asarray(rng.standard_normal(np.shape(v)), jnp.float32)
                  for k, v in updates[0][1].items()}
        plane = ShardedRoundPlane(policy=("adam", 0.1, 0.9), mesh=mesh_a)
        plane.round_update(params, updates)  # resident state + program
        info = plane.remesh(mesh_b)
        if not (info and info.get("changed")):
            return {}
        return {
            "resize_downtime_s": round(float(info["seconds"]), 6),
            "remesh_recompile_s": round(float(info["recompile_s"]), 6),
            "remesh_reshard_bytes": int(info["reshard_bytes"]),
        }
    except Exception as e:
        print(f"remesh measurement failed: {e}", file=sys.stderr)
        return {}


def _measure_upload_saturation() -> dict:
    """The "heavy traffic" numbers: sustained server ingest rate over the
    accept loop, measured twice (PR 10) —

    * **host leg** (``uploads_per_s_host``, also kept as the legacy
      ``uploads_per_s`` key for band continuity): the serial dispatcher
      path — per-sender dedup, msgpack payload decode, length+crc32-framed
      journal append with a PER-UPLOAD fsync before the ack (the PR 4
      crash-safety contract, paid at full price), ack frame encode.
    * **pipelined leg** (``uploads_per_s_pipelined``): the staged ingest
      path — zero-copy decode into per-sender arenas, zero-copy blob
      append into the group-commit journal (one fsync per batch), acks
      released only once the batch is durable; the clock stops after the
      LAST ack is released, so the contract is identical, only amortized.

    Both legs are driven by the same synthetic firehose (~11% retransmits)
    and both report their ``journal.fsync_seconds`` observation-count delta
    (``journal_fsync_count_*``), making the fsync amortization a first-class
    banded fact.  No sockets: this saturates the server-side loop itself,
    not loopback plumbing.  Pure host work, so it is reported on BOTH the
    full and CPU-degraded lines.  Failures degrade to empty keys."""
    import shutil
    import tempfile

    import numpy as np

    try:
        from flax import serialization

        from fedml_tpu.core import obs
        from fedml_tpu.core.checkpoint import UpdateJournal
        from fedml_tpu.core.ingest import ZeroCopyDecoder

        n_uploads = int(os.environ.get("BENCH_UPLOADS", "240"))
        n_senders = 16
        fsync = os.environ.get("BENCH_JOURNAL_FSYNC", "always")
        gc_ms = float(os.environ.get("BENCH_GROUP_COMMIT_MS", "5"))
        gc_max = int(os.environ.get("BENCH_GROUP_COMMIT_MAX", "32"))
        rng = np.random.default_rng(0)
        deltas = [
            {"w/kernel": rng.standard_normal((64, 64)).astype(np.float32),
             "w/bias": rng.standard_normal(64).astype(np.float32),
             "head/kernel": rng.standard_normal((64, 10)).astype(np.float32)}
            for _ in range(n_senders)
        ]
        # the wire blobs: each sender's upload payload in the exact record
        # layout the journal stores, so the pipelined leg can append the
        # received bytes verbatim (UpdateJournal.append_blob_async)
        blobs = [serialization.msgpack_serialize(
            {"sender": s, "n_samples": 32, "version": 0,
             "model_params": deltas[s]}) for s in range(n_senders)]
        payload_bytes = len(blobs[0])

        def fsync_count() -> int:
            h = obs.registry().get_histogram("journal.fsync_seconds")
            return int(h["count"]) if h else 0

        def firehose():
            """Yield (key, version, is_dup) over the shared upload schedule."""
            seen = set()
            for i in range(n_uploads):
                sender = i % n_senders
                version = i // n_senders
                if i % 9 == 8:  # firehose retransmit: an already-sent key
                    key = ((sender - 1) % n_senders, version)
                else:
                    key = (sender, version)
                dup = key in seen
                seen.add(key)
                yield key, version, dup

        def host_leg():
            tmp = tempfile.mkdtemp(prefix="bench_journal_")
            try:
                journal = UpdateJournal(tmp, fsync=fsync)
                deduped = 0
                t0 = time.perf_counter()
                for key, version, dup in firehose():
                    if dup:
                        deduped += 1  # journaled already: discard, no ack
                        continue
                    if key[0] == 0 and version:
                        journal.prune_before(version)  # flushed-cycle cleanup
                    record = serialization.msgpack_restore(blobs[key[0]])
                    journal.append(version, record)
                    serialization.msgpack_serialize(  # the ack frame
                        {"sender": key[0], "version": version, "ok": True})
                dt = time.perf_counter() - t0
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            return (n_uploads - deduped) / max(dt, 1e-9), deduped

        def pipelined_leg():
            tmp = tempfile.mkdtemp(prefix="bench_journal_")
            try:
                journal = UpdateJournal(tmp, fsync=fsync,
                                        group_commit_ms=gc_ms,
                                        group_commit_max=gc_max)
                decoder = ZeroCopyDecoder()
                for s in range(n_senders):  # learning pass outside the clock
                    decoder.decode(s, blobs[s])
                deduped = 0
                pending = []
                t0 = time.perf_counter()
                for key, version, dup in firehose():
                    if dup:
                        deduped += 1
                        continue
                    if key[0] == 0 and version:
                        journal.prune_before(version)
                    decoder.decode(key[0], blobs[key[0]])  # arena-backed tree
                    pending.append((key[0], version,
                                    journal.append_blob_async(version,
                                                              blobs[key[0]])))
                journal.flush(timeout=60.0)
                for sender, version, ticket in pending:
                    if not ticket.durable:  # ack withheld: leg is invalid
                        raise RuntimeError("journal batch never went durable")
                    serialization.msgpack_serialize(  # the deferred ack frame
                        {"sender": sender, "version": version, "ok": True})
                dt = time.perf_counter() - t0
                journal.close()
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            return (n_uploads - deduped) / max(dt, 1e-9), deduped

        f0 = fsync_count()
        host_rate, deduped = host_leg()
        host_fsyncs = fsync_count() - f0
        f0 = fsync_count()
        pipe_rate, _ = pipelined_leg()
        pipe_fsyncs = fsync_count() - f0
        return {
            "uploads_per_s": round(host_rate, 2),  # legacy band continuity
            "uploads_per_s_host": round(host_rate, 2),
            "uploads_per_s_pipelined": round(pipe_rate, 2),
            "journal_fsync_count_host": host_fsyncs,
            "journal_fsync_count_pipelined": pipe_fsyncs,
            "upload_payload_bytes": payload_bytes,
            "uploads_deduped": deduped,
            "journal_fsync": fsync,
            "group_commit_ms": gc_ms,
            "group_commit_max": gc_max,
        }
    except Exception as e:
        print(f"upload saturation measurement failed: {e}", file=sys.stderr)
        return {}


def _measure_fanin() -> dict:
    """Hierarchical fan-in relative keys (PR 18): the same 512-leaf round
    ingested two ways, both evaluating the SAME
    :class:`~fedml_tpu.core.hierarchy.plan.HierarchyPlan` so the
    arithmetic is identical and only the topology moves —

    * **flat leg** (``fanin_uploads_per_s_flat``): one root serially
      journals every leaf upload (decode + length/crc32-framed append,
      the PR 4 durability contract) then folds the whole plan in-process.
    * **edge leg** (``fanin_uploads_per_s_edge``): the plan's leaf-edge
      blocks run concurrently — each edge thread journals ITS block's
      uploads into its own journal and folds its block partial; the clock
      stops after the root combines the edge partials in block order.

    ``edge_forward_bytes`` is the wire size of one edge's fused forward
    delta (the O(model) payload an edge sends regardless of fanout) —
    the number that makes "edge memory/egress is O(model), not
    O(clients)" a banded fact.  Pure host work (journals + host fold),
    reported on both the full and CPU-degraded lines.  Failures degrade
    to empty keys."""
    import concurrent.futures
    import shutil
    import tempfile

    import numpy as np

    try:
        from flax import serialization

        from fedml_tpu.core.checkpoint import UpdateJournal
        from fedml_tpu.core.compression import wire_bytes
        from fedml_tpu.core.hierarchy.plan import HierarchyPlan

        n_leaves = int(os.environ.get("BENCH_FANIN_LEAVES", "512"))
        fanout = int(os.environ.get("BENCH_FANIN_FANOUT", "64"))
        fsync = os.environ.get("BENCH_JOURNAL_FSYNC", "always")
        plan = HierarchyPlan(n_leaves=n_leaves, levels=2, edge_fanout=fanout)
        rng = np.random.default_rng(7)
        # a handful of distinct payload templates; each leaf's wire blob is
        # pre-encoded so both legs pay decode + journal + fold, nothing
        # else.  ~4KB frames: million-client leaves ship compressed deltas
        # (docs/COMPRESSION.md), and at this size the per-upload cost is the
        # durability round-trip itself — exactly what the edge tier shards.
        templates = [
            {"w/kernel": rng.standard_normal((32, 32)).astype(np.float32),
             "w/bias": rng.standard_normal(32).astype(np.float32),
             "head/kernel": rng.standard_normal((32, 10)).astype(np.float32)}
            for _ in range(16)
        ]
        blobs = [serialization.msgpack_serialize(
            {"sender": i, "n_samples": 16 + (i % 48), "version": 0,
             "model_params": templates[i % len(templates)]})
            for i in range(n_leaves)]

        def ingest(journal, leaf_indices):
            """Decode + journal each upload; return the block's updates in
            leaf-index order (the plan's fold order)."""
            updates = []
            for i in leaf_indices:
                rec = serialization.msgpack_restore(blobs[i])
                journal.append(0, rec)
                updates.append((float(rec["n_samples"]),
                                rec["model_params"]))
            return updates

        def flat_leg():
            tmp = tempfile.mkdtemp(prefix="bench_fanin_flat_")
            try:
                journal = UpdateJournal(tmp, fsync=fsync)
                t0 = time.perf_counter()
                updates = ingest(journal, range(n_leaves))
                plan.aggregate(updates, mode="mean")
                dt = time.perf_counter() - t0
                journal.close()
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            return n_leaves / max(dt, 1e-9)

        def edge_leg():
            tmp = tempfile.mkdtemp(prefix="bench_fanin_edge_")
            total = float(sum(16 + (i % 48) for i in range(n_leaves)))

            def run_edge(e):
                journal = UpdateJournal(os.path.join(tmp, f"edge_{e}"),
                                        fsync=fsync)
                updates = ingest(journal, plan.blocks[e])
                partial = plan.block_partial(updates, total, mode="mean")
                journal.close()
                return partial

            try:
                with concurrent.futures.ThreadPoolExecutor(
                        max_workers=plan.n_edges) as pool:
                    t0 = time.perf_counter()
                    partials = list(pool.map(run_edge,
                                             range(plan.n_edges)))
                    plan.combine(partials)
                    dt = time.perf_counter() - t0
                fwd_bytes = wire_bytes(partials[0])
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            return n_leaves / max(dt, 1e-9), fwd_bytes

        # median of reps: fsync latency on shared storage is noisy and the
        # first rep pays cold-start (page cache, allocator) — the median
        # drops it without a separate warmup pass
        reps = int(os.environ.get("BENCH_FANIN_REPS", "3"))
        flat_rate = float(np.median([flat_leg() for _ in range(reps)]))
        edge_runs = [edge_leg() for _ in range(reps)]
        edge_rate = float(np.median([r for r, _ in edge_runs]))
        fwd_bytes = edge_runs[0][1]
        return {
            "fanin_uploads_per_s_flat": round(flat_rate, 2),
            "fanin_uploads_per_s_edge": round(edge_rate, 2),
            "fanin_edge_speedup": round(edge_rate / max(flat_rate, 1e-9), 3),
            "edge_forward_bytes": fwd_bytes,
            "fanin_leaves": n_leaves,
            "fanin_edges": plan.n_edges,
        }
    except Exception as e:
        print(f"fan-in measurement failed: {e}", file=sys.stderr)
        return {}


def _measure_async_throughput() -> dict:
    """Buffered-async round-throughput keys: a small sp FedBuff run
    (synthetic data, lr model) timed end-to-end — flushes (the async
    'round') and accepted deltas per second.  CPU-cheap on purpose and
    reported on both metric lines, so the async trend survives a dark
    chip window.  Failures degrade to empty keys."""
    try:
        import fedml_tpu
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.simulation.sp.async_fedavg.fedbuff_api import FedBuffAPI

        cfg = {
            "common_args": {"training_type": "simulation", "random_seed": 0,
                            "run_id": "bench_async"},
            "data_args": {"dataset": "mnist", "data_cache_dir": "",
                          "partition_method": "hetero", "partition_alpha": 0.5,
                          "synthetic_train_size": 480},
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 8,
                "client_num_per_round": 4,
                "comm_round": 6,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
                "fl_mode": "async",
                "async_buffer_size": 2,
                "async_max_staleness": 2,
                "async_staleness_policy": "polynomial",
            },
            "validation_args": {"frequency_of_the_test": 100},
            "comm_args": {"backend": "sp"},
        }
        args = fedml_tpu.init(Arguments.from_dict(cfg).validate(),
                              should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        api = FedBuffAPI(args, None, dataset, model)
        t0 = time.perf_counter()
        api.train()
        dt = time.perf_counter() - t0
        flushes = int(args.comm_round)
        # the flush loop drains exactly `capacity` deltas per flush
        deltas = flushes * api.buffer.capacity
        return {
            "async_flushes_per_s": round(flushes / max(dt, 1e-9), 3),
            "async_deltas_per_s": round(deltas / max(dt, 1e-9), 3),
            "async_buffer_size": api.buffer.capacity,
        }
    except Exception as e:
        print(f"async throughput measurement failed: {e}", file=sys.stderr)
        return {}


def _measure_chunked() -> dict:
    """Chunked-upload streaming keys (the resumable-upload plane), pure
    host arithmetic over the REAL framing seam:

    * ``chunk_overhead_frac`` — wire framing cost: the serialized chunk
      frames of a representative 4 MiB upload at 64 KiB chunks, relative
      to the raw payload bytes.  Lower-is-better with an absolute cap —
      headers eating the payload would eat the resumability win too.
    * ``chunked_goodput_frac_lossy`` — payload bytes over total wire
      bytes for an upload whose link dies at 90% of the stream: the
      resumable sender replays only its unacked window (the acked prefix
      survives the cut), where a whole-message sender replays everything.
      Higher-is-better, banded against the trajectory; the whole-message
      figure rides along unbanded for scale.

    Pure host work, reported on BOTH the full and CPU-degraded lines.
    Failures degrade to empty keys."""
    import pickle

    try:
        import numpy as np

        from fedml_tpu.core.distributed.chunking import _KEY_DATA, build_chunks
        from fedml_tpu.core.distributed.communication.message import Message

        chunk_bytes = int(os.environ.get("BENCH_CHUNK_BYTES", str(64 * 1024)))
        window = int(os.environ.get("BENCH_CHUNK_WINDOW", "8"))
        rng = np.random.default_rng(0)
        payload = rng.standard_normal(4 * 1024 * 1024 // 8).tobytes()
        inner = Message("bench_upload", 1, 0)
        inner.add_params("round_idx", 0)
        frames = build_chunks("bench:0:1", inner, payload, chunk_bytes)
        sizes = [len(f.get(_KEY_DATA)) for f in frames]
        assert b"".join(f.get(_KEY_DATA) for f in frames) == payload
        wire = sum(len(pickle.dumps(f.get_params(),
                                    protocol=pickle.HIGHEST_PROTOCOL))
                   for f in frames)
        overhead = wire / len(payload) - 1.0

        # the lossy replay model: the link dies after 90% of the chunks
        # are on the wire; everything acked before the cut stays acked
        # (journal-before-ack), so the resumed stream re-sends only the
        # in-flight window plus the untransmitted tail
        n = len(frames)
        cut = max(1, int(0.9 * n))
        sent_before = sum(sizes[:cut])
        resumed_total = sent_before + sum(sizes[max(0, cut - window):])
        restart_total = sent_before + len(payload)
        return {
            "chunk_overhead_frac": round(overhead, 5),
            "chunked_goodput_frac_lossy": round(
                len(payload) / resumed_total, 4),
            "whole_message_goodput_frac_lossy": round(
                len(payload) / restart_total, 4),
            "chunk_bytes": chunk_bytes,
            "chunk_window": window,
        }
    except Exception as e:
        print(f"chunked streaming measurement failed: {e}", file=sys.stderr)
        return {}


def _run_degraded(reason: str) -> int:
    """No-TPU fallback: ONE JSON line with the relative keys (agg step host
    vs compiled, obs overhead on the agg step) instead of an empty BENCH
    artifact.  Absolute throughput is meaningless on CPU, so the headline
    value is the compiled agg step time — trend data for the agg plane."""
    import numpy as np

    out = {
        "metric": "agg_step_cpu_degraded",
        "unit": "s/agg_step",
        "degraded": True,
        "degraded_reason": reason,
    }
    agg = _measure_agg_step()
    out.update(agg)
    out["value"] = agg.get("agg_step_compiled_s", None)
    out.update(_measure_round_update())
    out.update(_measure_defended_round())
    out.update(_measure_remesh())
    out.update(_measure_upload_saturation())
    out.update(_measure_fanin())
    out.update(_measure_async_throughput())
    out.update(_measure_chunked())
    out.update(_measure_telemetry_overhead())
    out.update(_measure_health_overhead())
    out.update(_measure_round_throughput())

    # obs overhead on the measured path: the same compiled agg step with
    # tracing configured (spans to an in-memory sink, parented under a
    # round span) vs. the tracing-off times just measured
    try:
        import jax

        from fedml_tpu.core import obs
        from fedml_tpu.core.aggregate import weighted_mean
        from fedml_tpu.core.mlops.sinks import InMemorySink
        from fedml_tpu.parallel.agg_plane import CompiledAggPlane

        import shutil
        import tempfile

        # the exporter rides the obs-on leg: snapshot rendering counts as
        # observability cost, so obs_overhead_frac prices the WHOLE plane
        export_dir = tempfile.mkdtemp(prefix="bench_export_")

        class _ObsArgs:
            run_id = "bench_degraded"
            obs_export_path = os.path.join(export_dir, "metrics.prom")

        n = int(agg.get("agg_clients", 8) or 8)
        reps = int(os.environ.get("BENCH_AGG_REPS", "5"))
        updates = _synthetic_updates(n)
        plane = CompiledAggPlane()
        plane.aggregate(updates)  # compile
        mem = InMemorySink()
        obs.configure(_ObsArgs(), mem.emit)
        try:
            ts = []
            for i in range(reps):
                with obs.round_span(i, mode="bench_degraded"):
                    t0 = time.perf_counter()
                    jax.block_until_ready(plane.aggregate(updates))
                    ts.append(time.perf_counter() - t0)
                obs.maybe_export_metrics()
            on_s = float(np.median(ts))
        finally:
            obs.shutdown()
            shutil.rmtree(export_dir, ignore_errors=True)
        off_s = float(agg.get("agg_step_compiled_s", 0.0) or 0.0)
        if off_s > 0:
            out["agg_step_obs_on_s"] = round(on_s, 6)
            out["obs_overhead_frac"] = round(on_s / off_s - 1.0, 4)
    except Exception as e:
        print(f"degraded obs overhead measurement failed: {e}", file=sys.stderr)

    _emit(out, "degraded")
    return 0


def _measure_telemetry_overhead() -> dict:
    """Telemetry-plane relative keys: a synthetic federated round — the
    server's real per-round work (one compiled agg step over N client
    deltas) plus N client report messages — timed with the plane ON
    (every client records its train sub-spans + a resource sample,
    attaches the blob to its upload ``Message``, the server-side merger
    absorbs) vs the IDENTICAL loop with ``obs_telemetry`` off, where the
    facade hands out no capture/merger, so the off leg pays exactly what
    a telemetry-off run pays.  Anchoring both legs on the agg step keeps
    ``telemetry_overhead_frac`` comparable to ``obs_overhead_frac``'s
    budget (telemetry vs real round cost, not vs an empty loop).  Also
    prices the wire: mean blob bytes per round.  Emitted on BOTH the full
    and degraded lines; failures degrade to empty keys."""
    import numpy as np

    from fedml_tpu.core import obs
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.parallel.agg_plane import CompiledAggPlane

    import jax

    n_clients = 8
    rounds = int(os.environ.get("BENCH_TELEMETRY_ROUNDS", "15"))

    def _loop(enabled: bool, plane, updates):
        class _Args:
            run_id = "bench_telemetry"
            obs_telemetry = 1 if enabled else 0

        obs.configure(_Args(), lambda topic, rec: None)
        try:
            caps = [obs.make_client_telemetry(i + 1)
                    for i in range(n_clients)]
            merger = obs.make_telemetry_merger()
            wire_bytes = 0
            ts = []
            for r in range(rounds):
                t0 = time.perf_counter()
                for i, cap in enumerate(caps):
                    msg = Message("send_model_to_server", i + 1, 0)
                    if cap is not None:
                        tctx = cap.record_span(
                            "client.train", 0.01, round_idx=r,
                            client_index=i)
                        cap.record_span("client.train.step", 0.01,
                                        parent=tctx, round_idx=r)
                        cap.record_counter("comm.bytes_sent", 1024.0)
                        cap.sample_resources()
                        wire_bytes += cap.attach(msg)
                    if merger is not None:
                        merger.absorb(msg)
                jax.block_until_ready(plane.aggregate(updates))
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts)), wire_bytes
        finally:
            obs.shutdown()

    try:
        updates = _synthetic_updates(n_clients)
        plane = CompiledAggPlane()
        plane.aggregate(updates)  # compile outside the timed legs
        on_s, wire_bytes = _loop(True, plane, updates)
        off_s, _ = _loop(False, plane, updates)
        if on_s <= 0 or off_s <= 0:
            return {}
        return {
            "telemetry_rounds_per_s": round(1.0 / on_s, 2),
            "telemetry_rounds_per_s_off": round(1.0 / off_s, 2),
            "telemetry_overhead_frac": round(
                max(on_s - off_s, 0.0) / off_s, 4),
            "telemetry_bytes_per_round": round(wire_bytes / rounds, 1),
        }
    except Exception as e:
        print(f"telemetry overhead measurement failed: {e}", file=sys.stderr)
        try:
            obs.shutdown()
        except Exception:
            pass
        return {}


def _measure_health_overhead() -> dict:
    """Health-plane relative key: the telemetry benchmark's synthetic round
    (compiled agg step + round span + ``maybe_export_metrics``, which is
    where the health plane ticks) with ``obs_health`` ON vs the identical
    loop with it off.  The on leg pays the tap (one dict peek per record),
    the per-tick registry pulls, and the window/watchdog checks — i.e. the
    whole liveness plane on the round path.  ``health_overhead_frac``
    rides the shared obs overhead budget.  Emitted on BOTH the full and
    degraded lines; failures degrade to empty keys."""
    import numpy as np

    from fedml_tpu.core import obs
    from fedml_tpu.parallel.agg_plane import CompiledAggPlane

    import jax

    rounds = int(os.environ.get("BENCH_HEALTH_ROUNDS", "15"))

    def _loop(enabled: bool, plane, updates):
        class _Args:
            run_id = "bench_health"
            obs_health = 1 if enabled else 0

        obs.configure(_Args(), lambda topic, rec: None)
        try:
            wd = obs.health_watchdog("bench.round_loop")
            ts = []
            for r in range(rounds):
                t0 = time.perf_counter()
                wd.beat()
                with obs.round_span(r, mode="bench_health"):
                    jax.block_until_ready(plane.aggregate(updates))
                    obs.health_observe("bench.round_seconds",
                                       time.perf_counter() - t0)
                obs.maybe_export_metrics()
                obs.health_tick()
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))
        finally:
            obs.shutdown()

    try:
        updates = _synthetic_updates(8)
        plane = CompiledAggPlane()
        plane.aggregate(updates)  # compile outside the timed legs
        on_s = _loop(True, plane, updates)
        off_s = _loop(False, plane, updates)
        if on_s <= 0 or off_s <= 0:
            return {}
        return {
            "health_round_s_on": round(on_s, 6),
            "health_round_s_off": round(off_s, 6),
            "health_overhead_frac": round(max(on_s - off_s, 0.0) / off_s, 4),
        }
    except Exception as e:
        print(f"health overhead measurement failed: {e}", file=sys.stderr)
        try:
            obs.shutdown()
        except Exception:
            pass
        return {}


def _measure_round_throughput() -> dict:
    """Round-throughput trajectory keys: a small SYNC sp FedAvg run
    (synthetic data, lr model) timed per round — full federated rounds
    per second and clients simulated per second.  Unlike the
    samples/s/chip headline these are CPU-cheap and emitted on BOTH
    metric lines, so the round-orchestration trend (sampling, dispatch,
    aggregate, eval gating) carries signal through a dark chip window.
    Failures degrade to empty keys."""
    try:
        import numpy as np

        import fedml_tpu
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI

        clients_per_round = 4
        cfg = {
            "common_args": {"training_type": "simulation", "random_seed": 0,
                            "run_id": "bench_rounds"},
            "data_args": {"dataset": "mnist", "data_cache_dir": "",
                          "partition_method": "hetero", "partition_alpha": 0.5,
                          "synthetic_train_size": 480},
            "model_args": {"model": "lr"},
            "train_args": {
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 8,
                "client_num_per_round": clients_per_round,
                "comm_round": 6,
                "epochs": 1,
                "batch_size": 32,
                "client_optimizer": "sgd",
                "learning_rate": 0.1,
            },
            "validation_args": {"frequency_of_the_test": 100},
            "comm_args": {"backend": "sp"},
        }
        args = fedml_tpu.init(Arguments.from_dict(cfg).validate(),
                              should_init_logs=False)
        dataset, out_dim = fedml_tpu.data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        api = FedAvgAPI(args, None, dataset, model)
        api.train()
        # median over post-compile rounds: round 0 pays jit + first dispatch
        times = list(api.round_times)
        times = times[1:] or times
        round_s = float(np.median(times))
        rps = 1.0 / max(round_s, 1e-9)
        return {
            "rounds_per_s": round(rps, 3),
            "clients_simulated_per_s": round(rps * clients_per_round, 3),
            "round_clients": clients_per_round,
        }
    except Exception as e:
        print(f"round throughput measurement failed: {e}", file=sys.stderr)
        return {}


def _measure_obs_overhead(sim) -> dict:
    """Round-trace overhead proof: re-run the already-compiled simulator
    with ``core/obs`` tracing enabled (spans emitted to an in-memory sink)
    and compare median round latency against the tracing-off rounds just
    measured.  The acceptance budget is < 2% — the span layer is a handful
    of hash+dict records per round next to an XLA program that trains all
    clients.  Telemetry about telemetry: a failure here degrades to empty
    keys, never a dead bench.

    The obs-on leg also runs the metrics EXPORTER (file-snapshot mode), so
    ``obs_overhead_frac`` prices spans + registry + OpenMetrics rendering
    together — the whole observability plane, not just the span layer."""
    import shutil
    import tempfile

    import numpy as np

    from fedml_tpu.core import obs
    from fedml_tpu.core.mlops.sinks import InMemorySink

    export_dir = tempfile.mkdtemp(prefix="bench_export_")
    try:
        # post-compile tracing-off rounds (round 0 of the final train() run
        # is steady-state too when the autotune winner was reused, but the
        # conservative slice — drop the first recorded round — covers both
        # construction paths)
        mark = len(sim.round_times)
        off = [t for t in sim.round_times[1:mark]]
        mem = InMemorySink()
        sim.args.obs_export_path = os.path.join(export_dir, "metrics.prom")
        obs.configure(sim.args, mem.emit)
        sim.train()  # appends comm_round more rounds, same compiled program
        obs.shutdown()
        sim.args.obs_export_path = None
        shutil.rmtree(export_dir, ignore_errors=True)
        on = sim.round_times[mark:]
        if not off or not on:
            return {}
        off_s = float(np.median(off))
        on_s = float(np.median(on))
        return {
            "round_s_obs_off": round(off_s, 4),
            "round_s_obs_on": round(on_s, 4),
            "obs_overhead_frac": round(on_s / max(off_s, 1e-9) - 1.0, 4),
        }
    except Exception as e:
        print(f"obs overhead measurement failed: {e}", file=sys.stderr)
        try:
            obs.shutdown()
        except Exception:
            pass
        shutil.rmtree(export_dir, ignore_errors=True)
        return {}


def _measure_transformer(
    d_model: int = 1024, n_layers: int = 8, n_heads: int = 16, d_ff: int = 4096,
    vocab: int = 32000, seq_len: int = 1024, batch: int = 8, n_steps: int = 20,
):
    """Opt-in (BENCH_TRANSFORMER=1): single-chip training throughput + MFU of
    the in-repo TransformerLM (models/transformer.py) — bf16 compute, fp32
    params, causal LM loss, back-to-back jitted steps.

    MFU uses the standard analytic cost: 6*N*tokens for the parameter math
    (fwd+bwd) plus 12*L^2*d*layers*batch for attention, over PEAK_TFLOPS.
    Override shapes via BENCH_TF_* env vars (CPU smoke: BENCH_TF_DMODEL=64
    BENCH_TF_LAYERS=2 BENCH_TF_SEQ=128 BENCH_TF_BATCH=2)."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import optax

    from fedml_tpu.models.transformer import TransformerConfig, TransformerLM

    d_model = int(os.environ.get("BENCH_TF_DMODEL", d_model))
    n_layers = int(os.environ.get("BENCH_TF_LAYERS", n_layers))
    n_heads = int(os.environ.get("BENCH_TF_HEADS", n_heads))
    d_ff = int(os.environ.get("BENCH_TF_DFF", d_ff))
    seq_len = int(os.environ.get("BENCH_TF_SEQ", seq_len))
    batch = int(os.environ.get("BENCH_TF_BATCH", batch))
    n_steps = int(os.environ.get("BENCH_TF_STEPS", n_steps))

    cfg = TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        d_ff=d_ff, max_seq_len=seq_len, dtype=jnp.bfloat16,
    )
    model = TransformerLM(cfg)
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq_len), 0, vocab, jnp.int32)
    params = model.init(key, tokens[:, :8])
    n_params = sum(int(p.size) for p in jax.tree_util.tree_leaves(params))
    tx = optax.sgd(1e-3)
    opt_state = tx.init(params)

    def step(params, opt_state, tok):
        def loss_fn(p):
            logits = model.apply(p, tok[:, :-1])
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), tok[:, 1:]
            )
            return jnp.mean(per)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    jstep = jax.jit(step)
    params, opt_state, _ = jstep(params, opt_state, tokens)  # compile
    jax.block_until_ready(params)
    t0 = _time.time()
    for _ in range(n_steps):
        params, opt_state, loss = jstep(params, opt_state, tokens)
    jax.block_until_ready(params)
    dt = _time.time() - t0

    tokens_per_step = batch * (seq_len - 1)
    tok_per_s = n_steps * tokens_per_step / max(dt, 1e-9)
    # analytic training FLOPs: 6*N per token + attention 12*L*d per token-layer
    flops_step = (6.0 * n_params * tokens_per_step
                  + 12.0 * n_layers * d_model * (seq_len - 1) * tokens_per_step)
    achieved_tflops = flops_step * n_steps / max(dt, 1e-9) / 1e12
    # no vs_baseline key on this line: the file-header contract defines
    # vs_baseline as "divided by a MEASURED eager baseline", and this run IS
    # the eager loop — mfu (vs chip peak) is the headline ratio here
    return {
        "metric": "transformer_lm_training_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1),
        "unit": "tokens/s/chip",
        "mfu": round(achieved_tflops / PEAK_TFLOPS, 5),
        "achieved_tflops": round(achieved_tflops, 2),
        "n_params": n_params,
        "config": {"d_model": d_model, "n_layers": n_layers, "n_heads": n_heads,
                   "d_ff": d_ff, "seq_len": seq_len, "batch": batch},
        "compute_dtype": "bf16",
    }


def _measure_sp(args, dataset) -> float:
    """Opt-in (BENCH_SP=1): host-loop sp FedAvg throughput for comparison."""
    import copy

    import fedml_tpu
    from fedml_tpu.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    sp_args = copy.deepcopy(args)
    sp_args.backend = "sp"
    sp_args.comm_round = 3
    sp_args.frequency_of_the_test = 100
    model = fedml_tpu.models.create(sp_args, 10)
    api = FedAvgAPI(sp_args, None, dataset, model)
    api.train()
    import numpy as np

    # pair each round's ACTUAL trained-sample count with its wall time
    # (per-round client sampling varies sizes under the Dirichlet partition)
    pairs = list(zip(api.samples_per_round, api.round_times))
    pairs = pairs[1:] or pairs  # drop the compile round
    return float(np.median([s / max(t, 1e-9) for s, t in pairs]))


if __name__ == "__main__":
    sys.exit(main())
