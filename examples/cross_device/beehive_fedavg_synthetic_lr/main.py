"""Beehive cross-device example: one ServerMNN-role server + two devices.

The devices here are the in-process fake-device harness (the protocol twin
of a phone running the native agent); swap them for real devices by running
`fedml_edge_agent` (native/agent.cpp) against the same model-file plane, or
the Java service over the JNI bridge (native/android/).

    python main.py --cf fedml_config.yaml
"""
import os
import sys

import numpy as np

import fedml_tpu
from fedml_tpu.arguments import Arguments


def _separable(n, d=12, classes=4, seed=0):
    centers = np.random.RandomState(1234).randn(classes, d) * 3
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, d) * 0.5
    return x.astype(np.float32), y.astype(np.int32)


def main(cfg_path: str, workdir: str = "./beehive_run"):
    import yaml

    from fedml_tpu.core.distributed.communication.loopback import LoopbackHub
    from fedml_tpu.cross_device.fake_device import FakeDeviceManager
    from fedml_tpu.cross_device.fedml_aggregator import FedMLAggregator
    from fedml_tpu.cross_device.fedml_server_manager import FedMLServerManager
    from fedml_tpu.models.linear import LogisticRegression

    with open(cfg_path) as f:
        args = Arguments.from_dict(yaml.safe_load(f)).validate()
    LoopbackHub.reset()
    n_dev = int(args.client_num_in_total)
    model = LogisticRegression(output_dim=4)
    aggregator = FedMLAggregator(args, model, _separable(128, seed=9),
                                 worker_num=n_dev,
                                 model_dir=os.path.join(workdir, "models"))
    server = FedMLServerManager(args, aggregator, client_rank=0, client_num=n_dev)
    devices = [
        FakeDeviceManager(args, rank, _separable(96, seed=rank), client_num=n_dev,
                          upload_dir=os.path.join(workdir, f"dev{rank}"))
        for rank in range(1, n_dev + 1)
    ]
    threads = [server.run_async()] + [d.run_async() for d in devices]
    for t in threads:
        t.join(timeout=120)
    print("eval history:", aggregator.eval_history)
    return aggregator.eval_history


if __name__ == "__main__":
    cf = "fedml_config.yaml"
    if "--cf" in sys.argv:
        cf = sys.argv[sys.argv.index("--cf") + 1]
    main(cf)
