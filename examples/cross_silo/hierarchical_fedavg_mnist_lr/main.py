"""Hierarchical cross-silo (Octopus + the Cheetah intra-silo plane): every
client silo runs ``n_proc_in_silo`` processes — proc 0 owns the WAN
connection, slave procs train stride-shards of the silo's data over the
host ProcessGroup plane and join the weighted allreduce.  This main.py is a
self-contained torchrun stand-in: it spawns the silo's slave processes and
places each by env (FEDML_PROC_RANK_IN_SILO / MASTER_PORT — the same env
surface a real torchrun-style launcher would set).

    python main.py --cf fedml_config.yaml --role server --rank 0
    python main.py --cf fedml_config.yaml --role client --rank 1
    python main.py --cf fedml_config.yaml --role client --rank 2
"""
import multiprocessing as mp
import os
import sys

import yaml

import fedml_tpu


def _silo_proc(argv, proc_rank, n_proc, pg_port):
    sys.argv = list(argv)
    os.environ["FEDML_PROC_RANK_IN_SILO"] = str(proc_rank)
    os.environ["FEDML_N_PROC_IN_SILO"] = str(n_proc)
    os.environ["MASTER_PORT"] = str(pg_port)
    fedml_tpu.run_cross_silo_client()


if __name__ == "__main__":
    role = "client"
    if "--role" in sys.argv:
        role = sys.argv[sys.argv.index("--role") + 1]
    if role == "server":
        fedml_tpu.run_cross_silo_server()
    else:
        cf = sys.argv[sys.argv.index("--cf") + 1] if "--cf" in sys.argv else "fedml_config.yaml"
        with open(cf) as f:
            cfg = yaml.safe_load(f)
        n_proc = int(cfg.get("train_args", {}).get("n_proc_in_silo", 1))
        rank = int(sys.argv[sys.argv.index("--rank") + 1]) if "--rank" in sys.argv else 1
        # one pg rendezvous port per silo
        pg_port = int(cfg.get("comm_args", {}).get("pg_base_port", 29420)) + rank
        ctx = mp.get_context("spawn")
        slaves = [
            ctx.Process(target=_silo_proc, args=(sys.argv, k, n_proc, pg_port), daemon=True)
            for k in range(1, n_proc)
        ]
        for p in slaves:
            p.start()
        _silo_proc(sys.argv, 0, n_proc, pg_port)
        for p in slaves:
            p.join()
