"""Cross-silo entry — run one server and N clients as separate processes:
    python main.py --cf fedml_config.yaml --role server --rank 0
    python main.py --cf fedml_config.yaml --role client --rank 1
"""
import sys

import fedml_tpu

if __name__ == "__main__":
    role = "client"
    if "--role" in sys.argv:
        role = sys.argv[sys.argv.index("--role") + 1]
    if role == "server":
        fedml_tpu.run_cross_silo_server()
    else:
        fedml_tpu.run_cross_silo_client()
