"""SecAgg cross-silo example: pairwise-masked aggregation — the server only
ever sees the masked sum (reference Octopus SecAgg scenario).

    python main.py --cf fedml_config.yaml
"""
import sys

import yaml

import fedml_tpu
from fedml_tpu.arguments import Arguments

if __name__ == "__main__":
    cf = "fedml_config.yaml"
    if "--cf" in sys.argv:
        cf = sys.argv[sys.argv.index("--cf") + 1]
    with open(cf) as f:
        args = fedml_tpu.init(Arguments.from_dict(yaml.safe_load(f)).validate(),
                              should_init_logs=False)
    from fedml_tpu.cross_silo.secagg import run_secagg_topology_in_threads

    history = run_secagg_topology_in_threads(
        args, fedml_tpu.data.load,
        lambda a, out_dim: fedml_tpu.models.create(a, out_dim),
    )
    print(history[-1] if history else {})
