"""SecAgg cross-silo example: pairwise-masked aggregation — the server only
ever sees the masked sum (reference Octopus SecAgg scenario).  Runs the
full topology in-process:
    python main.py --cf fedml_config.yaml
"""
import fedml_tpu
from fedml_tpu.arguments import load_arguments
from fedml_tpu.constants import FEDML_TRAINING_PLATFORM_CROSS_SILO
from fedml_tpu.cross_silo.secagg import run_secagg_topology_in_threads

if __name__ == "__main__":
    args = load_arguments(FEDML_TRAINING_PLATFORM_CROSS_SILO)
    args = fedml_tpu.init(args)
    history = run_secagg_topology_in_threads(
        args,
        fedml_tpu.data.load,
        lambda a, out_dim: fedml_tpu.models.create(a, out_dim),
    )
    print("history:", history)
