"""Multi-process simulation (the mpirun -np N workflow, process-real):

    python main.py --cf fedml_config.yaml --np 2

Each rank is an OS process joined over the TCP ProcessGroup; see
fedml_tpu.run_mpi_simulation.  The __main__ guard is REQUIRED: ranks are
spawned multiprocessing children, which re-import this module.
"""
import sys

import yaml

import fedml_tpu

if __name__ == "__main__":
    cf = "fedml_config.yaml"
    world = 2
    if "--cf" in sys.argv:
        cf = sys.argv[sys.argv.index("--cf") + 1]
    if "--np" in sys.argv:
        world = int(sys.argv[sys.argv.index("--np") + 1])
    with open(cf) as f:
        config = yaml.safe_load(f)
    print(fedml_tpu.run_mpi_simulation(config, world_size=world))
