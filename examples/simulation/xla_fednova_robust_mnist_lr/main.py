"""One-liner example entry (reference example dirs run the same way):
    python main.py --cf fedml_config.yaml
"""
import fedml_tpu

if __name__ == "__main__":
    fedml_tpu.run_simulation()
