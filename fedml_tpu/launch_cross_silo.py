"""One-line cross-silo launchers (reference ``launch_cross_silo_horizontal.py``)."""

from __future__ import annotations


def run_cross_silo(role: str = "client"):
    import fedml_tpu
    from fedml_tpu import data as _data, device as _device, models as _models
    from fedml_tpu.arguments import load_arguments
    from fedml_tpu.constants import FEDML_TRAINING_PLATFORM_CROSS_SILO
    from fedml_tpu.runner import FedMLRunner

    args = load_arguments(FEDML_TRAINING_PLATFORM_CROSS_SILO)
    args.training_type = FEDML_TRAINING_PLATFORM_CROSS_SILO
    args.role = role
    args = fedml_tpu.init(args)
    device = _device.get_device(args)
    dataset, output_dim = _data.load(args)
    model = _models.create(args, output_dim)
    runner = FedMLRunner(args, device, dataset, model)
    return runner.run()
