"""One-line launchers for the genuinely-distributed platforms (reference
``launch_cross_silo_horizontal.py``): the shared init → device → data →
model → FedMLRunner sequence, reused by ``launch_cross_device``."""

from __future__ import annotations


def launch(training_type: str, role: str):
    """The common launch sequence behind every one-liner."""
    import fedml_tpu
    from fedml_tpu import data as _data, device as _device, models as _models
    from fedml_tpu.arguments import load_arguments
    from fedml_tpu.runner import FedMLRunner

    args = load_arguments(training_type)
    args.training_type = training_type
    args.role = role
    args = fedml_tpu.init(args)
    device = _device.get_device(args)
    dataset, output_dim = _data.load(args)
    model = _models.create(args, output_dim)
    runner = FedMLRunner(args, device, dataset, model)
    return runner.run()


def run_cross_silo(role: str = "client"):
    from fedml_tpu.constants import FEDML_TRAINING_PLATFORM_CROSS_SILO

    return launch(FEDML_TRAINING_PLATFORM_CROSS_SILO, role)
