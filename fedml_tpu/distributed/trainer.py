"""Sharded trainer over a dp x tp mesh ("Cheetah").

Replaces the reference's DDP-wrap + NCCL allreduce intra-silo acceleration
(``cross_silo/client/fedml_trainer_dist_adapter.py:26``,
``ml/engine/ml_engine_adapter.py:273-281``) with the idiomatic TPU shape:
parameters carry NamedShardings (tensor-parallel where divisible, replicated
otherwise — parallel/sharding.py), batches shard over ``dp``, and jit
compiles the step with XLA inserting all-reduces/all-gathers over ICI.  No
process groups, no wrapper module: sharding is data layout.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from ..ml.engine.train import make_optimizer
from ..parallel.mesh import create_train_mesh
from ..parallel.sharding import batch_sharding, param_shardings, replicated

logger = logging.getLogger(__name__)

Pytree = Any


class DistributedTrainer:
    """Train a flax classifier/LM over a mesh.

    ``loss_fn(logits, y) -> scalar`` defaults to softmax CE over integer
    labels (works for [B] class ids and [B, L] token targets)."""

    def __init__(
        self,
        model,
        args,
        mesh: Optional[Mesh] = None,
        loss_fn: Optional[Callable] = None,
    ):
        self.module = model
        self.args = args
        if mesh is None:
            n = len(jax.devices())
            tp = int(getattr(args, "tp_degree", 1))
            mesh = create_train_mesh(dp=max(n // tp, 1), tp=tp)
        self.mesh = mesh
        self.tx = make_optimizer(args)
        self.loss_fn = loss_fn or (
            lambda logits, y: jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, y)
            )
        )
        self.variables: Optional[Pytree] = None
        self.opt_state = None
        self._step = None

    # -- setup ----------------------------------------------------------------
    def init(self, sample_x: jnp.ndarray, seed: int = 0) -> Pytree:
        variables = self.module.init(jax.random.PRNGKey(seed), sample_x, train=False)
        return self.init_from(dict(variables))

    def init_from(self, variables: Pytree) -> Pytree:
        """Adopt existing variables (e.g. the FL round's incoming global
        model), shard them over the mesh, and (re)build the step."""
        self._var_shardings = param_shardings(variables, self.mesh)
        self.variables = jax.device_put(dict(variables), self._var_shardings)
        self.opt_state = self.tx.init(self.variables["params"])
        if self._step is None:
            self._build_step(self._var_shardings)
        return self.variables

    def get_variables(self) -> Pytree:
        """Host copy of the current variables (for the WAN message plane)."""
        return jax.device_get(self.variables)

    def _build_step(self, var_shardings) -> None:
        module, tx, loss_fn = self.module, self.tx, self.loss_fn
        x_shard = batch_sharding(self.mesh, 2)  # refined per-call by jit
        rep = replicated(self.mesh)

        def step(variables, opt_state, x, y):
            params = variables["params"]
            # sorted: pytree construction inside the traced body must not
            # depend on the caller's dict insertion order
            other = {k: v for k, v in sorted(variables.items()) if k != "params"}

            def compute(p):
                logits = module.apply(dict(other, params=p), x, train=True)
                return loss_fn(logits, y)

            loss, grads = jax.value_and_grad(compute)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return dict(other, params=params), opt_state, loss

        self._step = jax.jit(
            step,
            in_shardings=(var_shardings, None, None, None),
            out_shardings=(var_shardings, None, rep),
            donate_argnums=(0, 1),
        )

    # -- training -------------------------------------------------------------
    def train_step(self, x: jnp.ndarray, y: jnp.ndarray) -> float:
        assert self._step is not None, "call init() first"
        xs = jax.device_put(jnp.asarray(x), batch_sharding(self.mesh, np.ndim(x)))
        ys = jax.device_put(jnp.asarray(y), batch_sharding(self.mesh, np.ndim(y)))
        self.variables, self.opt_state, loss = self._step(
            self.variables, self.opt_state, xs, ys
        )
        return float(loss)

    def fit(self, x, y, epochs: int = 1, batch_size: int = 0, seed: int = 0) -> Dict[str, float]:
        """Simple epoch loop over host arrays; batch must divide by dp."""
        bs = int(batch_size or getattr(self.args, "batch_size", 32))
        dp = int(self.mesh.shape.get("dp", 1))
        bs = max((bs // dp) * dp, dp)
        n = (len(y) // bs) * bs
        rng = np.random.RandomState(seed)
        losses = []
        for _ in range(int(epochs)):
            order = rng.permutation(len(y))[:n]
            for s in range(0, n, bs):
                idx = order[s : s + bs]
                losses.append(self.train_step(np.asarray(x)[idx], np.asarray(y)[idx]))
        return {"final_loss": losses[-1] if losses else float("nan"),
                "mean_loss": float(np.mean(losses)) if losses else float("nan")}

    # -- eval -----------------------------------------------------------------
    def evaluate(self, x, y, batch_size: int = 256) -> Dict[str, float]:
        assert self.variables is not None
        module = self.module
        correct = total = 0
        for s in range(0, len(y), batch_size):
            logits = jax.jit(lambda v, xb: module.apply(v, xb, train=False))(
                self.variables, jnp.asarray(x[s : s + batch_size])
            )
            pred = jnp.argmax(logits, -1)
            correct += int(jnp.sum(pred == jnp.asarray(y[s : s + batch_size])))
            total += len(y[s : s + batch_size])
        return {"accuracy": correct / max(total, 1)}
