"""Distributed training acceleration — the Cheetah pillar.

The reference's ``python/fedml/distributed`` is an empty stub (SURVEY.md §1:
the real intra-silo acceleration is PyTorch DDP in the hierarchical
cross-silo path).  Here the pillar is first-class and TPU-native: a sharded
trainer over a ``dp x tp`` device mesh with XLA collectives over ICI.
"""

from .trainer import DistributedTrainer

__all__ = ["DistributedTrainer"]
