"""Simulator dispatch.

Parity with reference ``simulation/simulator.py`` (SimulatorSingleProcess /
SimulatorMPI / SimulatorNCCL): backend "sp" runs the in-process python round
loop; "XLA" (also accepted: "MPI", "NCCL" — their TPU-native successor) runs
the sharded in-mesh simulator (simulation/xla/) where clients live on a
device mesh and aggregation is a psum over ICI.
"""

from __future__ import annotations

from ..constants import (
    FEDML_SIMULATION_TYPE_MPI,
    FEDML_SIMULATION_TYPE_NCCL,
    FEDML_SIMULATION_TYPE_SP,
    FEDML_SIMULATION_TYPE_XLA,
)


class SimulatorSingleProcess:
    def __init__(self, args, device, dataset, model):
        opt = str(getattr(args, "federated_optimizer", "FedAvg"))
        from .sp import create_sp_algorithm

        self.fl_trainer = create_sp_algorithm(opt, args, device, dataset, model)

    def run(self):
        return self.fl_trainer.train()


class SimulatorXLA:
    def __init__(self, args, device, dataset, model):
        opt = str(getattr(args, "federated_optimizer", "FedAvg")).lower()
        # split-computation algorithms have their own in-mesh programs
        # (communication-shaped structure: feature sharding / activation
        # exchange / knowledge transfer — simulation/xla/split.py)
        if opt == "classical_vertical":
            from .xla.split import VFLInMeshAPI

            self.sim = VFLInMeshAPI(args, device, dataset, model)
        elif opt == "split_nn":
            from .xla.split import SplitNNInMeshAPI

            self.sim = SplitNNInMeshAPI(args, device, dataset, model)
        elif opt == "fedgkt":
            from .xla.split import GKTInMeshAPI

            self.sim = GKTInMeshAPI(args, device, dataset, model)
        elif opt == "fedgan":
            from .xla.gan_nas import GANInMeshAPI

            self.sim = GANInMeshAPI(args, device, dataset, model)
        elif opt == "fednas":
            from .xla.gan_nas import NASInMeshAPI

            self.sim = NASInMeshAPI(args, device, dataset, model)
        elif opt == "decentralized_fl":
            from .xla.decentralized import DecentralizedInMeshAPI

            self.sim = DecentralizedInMeshAPI(args, device, dataset, model)
        elif opt == "spreadgnn":
            from .xla.decentralized import SpreadGNNInMeshAPI

            self.sim = SpreadGNNInMeshAPI(args, device, dataset, model)
        elif opt == "turbo_aggregate":
            from .xla.turbo import TurboAggregateInMeshAPI

            self.sim = TurboAggregateInMeshAPI(args, device, dataset, model)
        elif opt == "hierarchicalfl":
            from .xla.hierarchical import HierarchicalInMeshAPI

            self.sim = HierarchicalInMeshAPI(args, device, dataset, model)
        else:
            from .xla.fed_sim import XLASimulator

            self.sim = XLASimulator(args, dataset, model)

    def run(self):
        return self.sim.train()


def create_simulator(args, device, dataset, model):
    backend = str(getattr(args, "backend", FEDML_SIMULATION_TYPE_SP))
    if backend == FEDML_SIMULATION_TYPE_SP:
        return SimulatorSingleProcess(args, device, dataset, model)
    if backend == "MPI_PROC":
        # process-real MPI rank plane (reference mpirun -np N parity); this
        # constructs ONE rank — fedml_tpu.run_mpi_simulation spawns the set
        from .mpi_proc import MPIProcessSimulator

        return MPIProcessSimulator(args, dataset, model)
    if backend in (
        FEDML_SIMULATION_TYPE_XLA,
        FEDML_SIMULATION_TYPE_MPI,
        FEDML_SIMULATION_TYPE_NCCL,
    ):
        return SimulatorXLA(args, device, dataset, model)
    raise ValueError(f"unknown simulation backend {backend!r}")
