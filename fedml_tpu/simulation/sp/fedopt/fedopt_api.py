"""FedOpt: server-side adaptive optimization (Reddi et al.).

Capability parity with reference ``simulation/sp/fedopt/fedopt_api.py``:
clients run plain local SGD; the server treats the weighted-average client
delta as a pseudo-gradient and applies a server optimizer
(``server_optimizer`` ∈ sgd/adam/yogi/adagrad, ``server_lr``, ``server_momentum``).
Implemented with optax over the params pytree (non-param collections, e.g.
batch_stats, are plainly averaged).
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import optax

from ....core.aggregate import weighted_mean
from ..fedavg.fedavg_api import FedAvgAPI


def make_server_optimizer(args) -> optax.GradientTransformation:
    name = str(getattr(args, "server_optimizer", "adam")).lower()
    lr = float(getattr(args, "server_lr", 1e-1))
    momentum = float(getattr(args, "server_momentum", 0.9))
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum if momentum > 0 else None)
    if name == "adam":
        return optax.adam(lr, b1=0.9, b2=0.99, eps=1e-3)
    if name == "yogi":
        return optax.yogi(lr, b1=0.9, b2=0.99, eps=1e-3)
    if name == "adagrad":
        return optax.adagrad(lr)
    raise ValueError(f"unknown server_optimizer {name!r}")


class FedOptAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        self._server_tx = make_server_optimizer(args)
        self._server_opt_state = self._server_tx.init(self.w_global["params"])

        @jax.jit
        def apply_server_update(params, opt_state, avg_params):
            pseudo_grad = jax.tree_util.tree_map(lambda p, a: p - a, params, avg_params)
            updates, opt_state = self._server_tx.update(pseudo_grad, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._apply_server_update = apply_server_update

    def checkpoint_state(self):
        from flax import serialization

        state = super().checkpoint_state()
        # optax states are namedtuple pytrees; persist as a flax state dict so
        # msgpack round-trips, and rebuild onto the live structure on restore
        state["server_opt_state"] = serialization.to_state_dict(self._server_opt_state)
        return state

    def restore_checkpoint_state(self, state):
        from flax import serialization

        super().restore_checkpoint_state(state)
        self._server_opt_state = serialization.from_state_dict(
            self._server_opt_state, state["server_opt_state"]
        )

    def server_update(self, w_locals: List[Tuple[float, Any]]) -> Any:
        w_locals = self.aggregator.on_before_aggregation(w_locals)
        avg = weighted_mean(w_locals)
        params, self._server_opt_state = self._apply_server_update(
            self.w_global["params"], self._server_opt_state, avg["params"]
        )
        new_global = dict(avg, params=params)
        return self.aggregator.on_after_aggregation(new_global)
