"""Decentralized (gossip) FL over a topology manager.

Parity with reference ``simulation/sp/decentralized`` (573 LoC): no server —
every node trains locally then mixes with its neighbors using the topology's
row-normalized mixing matrix.  TPU-first formulation: all node models are
stacked on a leading axis and one einsum with the mixing matrix performs the
whole gossip exchange (the host-loop equivalent of a ppermute round on an
ICI ring — the XLA simulator path does exactly that in-mesh).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from ....core.aggregate import tree_stack
from ....core.distributed.topology.topology_manager import SymmetricTopologyManager
from ..fedavg.fedavg_api import FedAvgAPI

logger = logging.getLogger(__name__)


class DecentralizedFLAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        n = int(args.client_num_in_total)
        self.topo = SymmetricTopologyManager(
            n, int(getattr(args, "topology_neighbor_num", 2)),
            seed=int(getattr(args, "random_seed", 0)),
        )
        self.topo.generate_topology()
        self.mix = jnp.asarray(self.topo.topology, jnp.float32)  # [n, n]
        self.node_models: List[Any] = [self.w_global for _ in range(n)]

        @jax.jit
        def gossip(stacked, mix):
            # stacked leaf: [n, ...] -> mix @ leaf (einsum over node axis)
            return jax.tree_util.tree_map(
                lambda x: jnp.tensordot(mix, x, axes=(1, 0)), stacked
            )

        self._gossip = gossip

    def train(self) -> Dict[str, Any]:
        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        n = int(self.args.client_num_in_total)
        slot = self.client_list[0]
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            # deterministic per-round RNG stream (same contract as the
            # FedAvgAPI loop): without this every round replays the round-0
            # shuffle/dropout keys
            self.trainer.round_idx = round_idx
            trained: List[Any] = []
            for cid in range(n):
                slot.update_local_dataset(
                    cid,
                    self.train_data_local_dict[cid],
                    self.test_data_local_dict[cid],
                    self.train_data_local_num_dict[cid],
                )
                trained.append(slot.train(self.node_models[cid]))
            stacked = tree_stack(trained)
            mixed = self._gossip(stacked, self.mix)
            self.node_models = [
                jax.tree_util.tree_map(lambda x: x[i], mixed) for i in range(n)
            ]
            # consensus model (plain mean) for evaluation
            self.w_global = jax.tree_util.tree_map(
                lambda x: jnp.mean(x, axis=0), mixed
            )
            self.aggregator.set_model_params(self.w_global)
            if round_idx % freq == 0 or round_idx == comm_round - 1:
                last = self._test_global(round_idx)
        return last
