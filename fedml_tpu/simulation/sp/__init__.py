"""Single-process algorithm registry (reference ``simulation/sp/*`` dirs)."""

from __future__ import annotations


def create_sp_algorithm(optimizer: str, args, device, dataset, model):
    try:
        return _dispatch(optimizer, args, device, dataset, model)
    except ImportError as e:
        raise NotImplementedError(
            f"federated_optimizer {optimizer!r} is registered but its module is "
            f"not available in this build: {e}"
        ) from e


def _dispatch(optimizer: str, args, device, dataset, model):
    opt = optimizer.lower()
    if str(getattr(args, "fl_mode", "sync") or "sync").lower() == "async":
        # buffered-async execution (core/async_fl) replaces the round loop;
        # only the FedAvg aggregation rule has an async counterpart so far
        if opt != "fedavg":
            raise ValueError(
                f"fl_mode=async supports federated_optimizer 'fedavg' only "
                f"in the sp simulator (got {optimizer!r})")
        from .async_fedavg.fedbuff_api import FedBuffAPI

        return FedBuffAPI(args, device, dataset, model)
    if opt == "fedavg":
        from .fedavg.fedavg_api import FedAvgAPI

        return FedAvgAPI(args, device, dataset, model)
    if opt == "fedopt":
        from .fedopt.fedopt_api import FedOptAPI

        return FedOptAPI(args, device, dataset, model)
    if opt == "fedprox":
        from .fedprox.fedprox_api import FedProxAPI

        return FedProxAPI(args, device, dataset, model)
    if opt == "fednova":
        from .fednova.fednova_api import FedNovaAPI

        return FedNovaAPI(args, device, dataset, model)
    if opt == "fedsgd":
        from .fedsgd.fedsgd_api import FedSGDAPI

        return FedSGDAPI(args, device, dataset, model)
    if opt == "scaffold":
        from .scaffold.scaffold_api import ScaffoldAPI

        return ScaffoldAPI(args, device, dataset, model)
    if opt == "feddyn":
        from .feddyn.feddyn_api import FedDynAPI

        return FedDynAPI(args, device, dataset, model)
    if opt == "hierarchicalfl":
        from .hierarchical_fl.hier_api import HierarchicalFLAPI

        return HierarchicalFLAPI(args, device, dataset, model)
    if opt == "decentralized_fl":
        from .decentralized.decentralized_api import DecentralizedFLAPI

        return DecentralizedFLAPI(args, device, dataset, model)
    if opt == "spreadgnn":
        from .spreadgnn.spreadgnn_api import SpreadGNNAPI

        return SpreadGNNAPI(args, device, dataset, model)
    if opt == "turbo_aggregate":
        from .turboaggregate.ta_api import TurboAggregateAPI

        return TurboAggregateAPI(args, device, dataset, model)
    if opt == "classical_vertical":
        from .classical_vertical_fl.vfl_api import VerticalFLAPI

        return VerticalFLAPI(args, device, dataset, model)
    if opt == "split_nn":
        from .split_nn.split_nn_api import SplitNNAPI

        return SplitNNAPI(args, device, dataset, model)
    if opt == "async_fedavg":
        from .async_fedavg.async_fedavg_api import AsyncFedAvgAPI

        return AsyncFedAvgAPI(args, device, dataset, model)
    if opt == "fedgan":
        from .fedgan.fedgan_api import FedGanAPI

        return FedGanAPI(args, device, dataset, model)
    if opt == "fedgkt":
        from .fedgkt.gkt_api import FedGKTAPI

        return FedGKTAPI(args, device, dataset, model)
    if opt == "fednas":
        from .fednas.fednas_api import FedNASAPI

        return FedNASAPI(args, device, dataset, model)
    if opt == "fedseg":
        from .fedseg.fedseg_api import FedSegAPI

        return FedSegAPI(args, device, dataset, model)
    raise ValueError(f"unknown federated_optimizer {optimizer!r}")
