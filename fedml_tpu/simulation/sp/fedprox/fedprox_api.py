"""FedProx: FedAvg + proximal term in the local objective (Li et al.).

Parity with reference ``simulation/mpi/fedprox/``: the client loss gains
mu/2 * ||w - w_global||^2.  Here that is the engine's ``grad_hook``
(g + mu*(w - anchor)), installed automatically when ``args.proximal_mu`` > 0
— see ml/engine/train.build_local_train.
"""

from __future__ import annotations

from ..fedavg.fedavg_api import FedAvgAPI


class FedProxAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        # proximal_mu default injection lives in Arguments.validate (one
        # chokepoint for every backend)
        super().__init__(args, device, dataset, model)
