"""SCAFFOLD: stochastic controlled averaging (Karimireddy et al.).

Beyond-reference algorithm (constant registered in fedml_tpu.constants):
per-client control variates c_i and server control c correct client drift:
the local step uses g - c_i + c (the engine's grad_hook with
extra=(c_i, c)); after K local steps, c_i^+ = c_i - c + (w_g - w_i)/(K*lr),
and the server updates w and c from the aggregated deltas.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax

from ....core.aggregate import tree_sub, tree_sum, tree_zeros_like
from ....ml.trainer.cls_trainer import ModelTrainerCLS
from ..fedavg.fedavg_api import FedAvgAPI


def _scaffold_hook(grads, params, anchor, extra):
    c_i, c = extra
    return jax.tree_util.tree_map(lambda g, ci, cg: g - ci + cg, grads, c_i, c)


class ScaffoldAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        # swap in a grad-hooked trainer and rebind the client slots to it
        self.trainer = ModelTrainerCLS(model, args, grad_hook=_scaffold_hook)
        self.client_list = []
        self._setup_clients()
        self.lr = float(getattr(args, "learning_rate", 0.01))
        self.c_server = tree_zeros_like(self.w_global["params"])
        self.c_clients: Dict[int, Any] = {}

    def _setup_clients(self):
        super()._setup_clients()
        for c in self.client_list:
            c.train = self._client_train(c)

    def _client_train(self, client):
        def run(w_global):
            cid = client.client_idx
            c_i = self.c_clients.get(cid)
            if c_i is None:
                c_i = tree_zeros_like(w_global["params"])
            self.trainer.set_model_params(w_global)
            res = self.trainer.train(
                client.local_training_data, None, self.args, extra=(c_i, self.c_server)
            )
            K = max(float(res.steps), 1.0)
            new_ci = jax.tree_util.tree_map(
                lambda ci, cg, wg, wi: ci - cg + (wg - wi) / (K * self.lr),
                c_i, self.c_server, w_global["params"], res.variables["params"],
            )
            self._round_dc.append(tree_sub(new_ci, c_i))
            self.c_clients[cid] = new_ci
            return res.variables

        return run

    def checkpoint_state(self):
        state = super().checkpoint_state()
        state["c_server"] = self.c_server
        # msgpack keys must be strings
        state["c_clients"] = {str(k): v for k, v in self.c_clients.items()}
        return state

    def restore_checkpoint_state(self, state):
        super().restore_checkpoint_state(state)
        self.c_server = state["c_server"]
        self.c_clients = {int(k): v for k, v in state.get("c_clients", {}).items()}

    def _client_sampling(self, round_idx):
        self._round_dc: List[Any] = []
        return super()._client_sampling(round_idx)

    def server_update(self, w_locals: List[Tuple[float, Any]]) -> Any:
        new_global = super().server_update(w_locals)
        if self._round_dc:  # c <- c + (1/N) * sum_i dc_i
            dc = tree_sum(self._round_dc)
            scale = 1.0 / float(self.args.client_num_in_total)
            self.c_server = jax.tree_util.tree_map(
                lambda c, d: c + scale * d, self.c_server, dc
            )
        return new_global
