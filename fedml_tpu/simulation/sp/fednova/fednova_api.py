"""FedNova: normalized averaging (Wang et al.).

Parity with reference ``simulation/sp/fednova`` / ``mpi/fednova``: each
client's cumulative update is normalized by its effective local step count
tau_i before averaging, removing objective inconsistency under heterogeneous
local work:  w <- w - tau_eff * sum_i p_i * d_i,  d_i = (w - w_i) / tau_i,
tau_eff = sum_i p_i * tau_i.  tau_i comes from the engine
(LocalTrainResult.steps — masked steps actually taken).
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax

from ....core.aggregate import tree_scale, tree_sum
from ..fedavg.fedavg_api import FedAvgAPI


class FedNovaAPI(FedAvgAPI):
    def _collect_tau(self) -> float:
        res = getattr(self.trainer, "last_result", None)
        return float(res.steps) if res is not None else 1.0

    def server_update(self, w_locals: List[Tuple[float, Any]]) -> Any:
        # taus recorded in collection order == w_locals order (shared trainer);
        # pair them BEFORE the defense filter so a filtered subset keeps the
        # right tau for each surviving update
        tau_by_id = {id(w): t for (_, w), t in zip(w_locals, self._round_taus)}
        w_locals = self.aggregator.on_before_aggregation(w_locals)
        taus = [tau_by_id.get(id(w), 1.0) for _, w in w_locals]
        total_n = sum(n for n, _ in w_locals)
        ps = [n / total_n for n, _ in w_locals]
        tau_eff = sum(p * t for p, t in zip(ps, taus))
        normalized = []
        for (n, w_i), p, tau in zip(w_locals, ps, taus):
            d_i = jax.tree_util.tree_map(
                lambda g, wi: (g - wi) / max(tau, 1.0), self.w_global, w_i
            )
            normalized.append(tree_scale(d_i, p))
        d = tree_sum(normalized)
        new_global = jax.tree_util.tree_map(
            lambda g, di: g - tau_eff * di, self.w_global, d
        )
        return self.aggregator.on_after_aggregation(new_global)

    # capture tau right after each client's training by wrapping the slot call
    def _setup_clients(self):
        super()._setup_clients()
        api = self
        for c in self.client_list:
            orig_train = c.train

            def wrapped(w_global, _orig=orig_train, _c=c):
                out = _orig(w_global)
                api._round_taus.append(api._collect_tau())
                return out

            c.train = wrapped

    def _client_sampling(self, round_idx):
        self._round_taus: List[float] = []
        return super()._client_sampling(round_idx)
