"""Turbo-Aggregate: multi-group circular secure aggregation (So et al.).

Parity with reference ``simulation/sp/turboaggregate`` (519 LoC): clients are
partitioned into L groups arranged in a ring; each group masks its models
with additive shares that telescope away as the ring is traversed, so the
server only ever sees group-level partial sums.  Here the masking uses
pairwise-cancelling additive masks drawn from ``jax.random`` (the MPC-grade
finite-field version lives in core/mpc/secagg.py).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ....core.aggregate import tree_scale, tree_sum, tree_zeros_like
from ..fedavg.fedavg_api import FedAvgAPI

logger = logging.getLogger(__name__)


def _mask_like(tree, key, scale=1.0):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef, [scale * jax.random.normal(k, jnp.shape(l)) for l, k in zip(leaves, keys)]
    )


class TurboAggregateAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        self.group_num = int(getattr(args, "ta_group_num", 2))
        self._mask_key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 404)

    def server_update(self, w_locals: List[Tuple[float, Any]]) -> Any:
        # ring of groups; group g adds mask m_g and removes m_{g-1} -> telescoping
        L = min(self.group_num, len(w_locals))
        groups = np.array_split(np.arange(len(w_locals)), L)
        self._mask_key, *gkeys = jax.random.split(self._mask_key, L + 1)
        total_n = sum(n for n, _ in w_locals)
        running = tree_zeros_like(w_locals[0][1])
        prev_mask = None
        for g, members in enumerate(groups):
            group_sum = tree_sum(
                [tree_scale(w_locals[int(i)][1], w_locals[int(i)][0] / total_n) for i in members]
            )
            mask = _mask_like(group_sum, gkeys[g])
            masked = jax.tree_util.tree_map(jnp.add, group_sum, mask)
            if prev_mask is not None:  # remove previous group's mask
                masked = jax.tree_util.tree_map(jnp.subtract, masked, prev_mask)
            running = jax.tree_util.tree_map(jnp.add, running, masked)
            prev_mask = mask
        # final unmask: last group's mask remains
        agg = jax.tree_util.tree_map(jnp.subtract, running, prev_mask)
        return self.aggregator.on_after_aggregation(agg)
