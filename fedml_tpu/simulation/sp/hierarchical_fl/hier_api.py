"""Hierarchical FL (reference ``simulation/sp/hierarchical_fl``, 244 LoC):
two-level averaging — clients -> group aggregation every round, group models
-> global average every ``group_comm_round`` rounds.  Maps onto the
hierarchical cross-silo scenario (silo = group).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Tuple

import numpy as np

from ....core.aggregate import weighted_mean
from ..fedavg.fedavg_api import FedAvgAPI

logger = logging.getLogger(__name__)


class HierarchicalFLAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        self.group_num = int(getattr(args, "group_num", 2))
        self.group_comm_round = int(getattr(args, "group_comm_round", 2))
        rng = np.random.RandomState(int(getattr(args, "random_seed", 0)))
        ids = rng.permutation(int(args.client_num_in_total))
        self.groups = np.array_split(ids, self.group_num)
        # each group's current model starts at global
        self.group_models: List[Any] = [self.w_global for _ in range(self.group_num)]

    def train(self) -> Dict[str, Any]:
        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        per_group = max(1, int(self.args.client_num_per_round) // self.group_num)
        slot = self.client_list[0]
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            # deterministic per-round RNG stream (same contract as the
            # FedAvgAPI loop; without this every round replays round-0 keys)
            self.trainer.round_idx = round_idx
            for g, members in enumerate(self.groups):
                rng = np.random.RandomState(
                    int(getattr(self.args, "random_seed", 0)) * 100003 + round_idx * 131 + g
                )
                chosen = rng.choice(members, min(per_group, len(members)), replace=False)
                w_locals: List[Tuple[float, Any]] = []
                for cid in chosen:
                    cid = int(cid)
                    slot.update_local_dataset(
                        cid,
                        self.train_data_local_dict[cid],
                        self.test_data_local_dict[cid],
                        self.train_data_local_num_dict[cid],
                    )
                    w = slot.train(self.group_models[g])
                    w_locals.append((float(slot.local_sample_number), w))
                self.group_models[g] = weighted_mean(w_locals)
            if (round_idx + 1) % self.group_comm_round == 0:
                sizes = [float(sum(self.train_data_local_num_dict[int(c)] for c in m)) for m in self.groups]
                self.w_global = weighted_mean(list(zip(sizes, self.group_models)))
                self.w_global = self.aggregator.on_after_aggregation(self.w_global)
                self.aggregator.set_model_params(self.w_global)
                self.group_models = [self.w_global for _ in range(self.group_num)]
            if round_idx % freq == 0 or round_idx == comm_round - 1:
                last = self._test_global(round_idx)
        return last
