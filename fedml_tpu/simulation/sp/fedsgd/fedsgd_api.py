"""FedSGD: one full-batch gradient step per round (the FedAvg paper's
baseline; reference constant ``FedML_FEDERATED_OPTIMIZER_FEDSGD``).

Clients compute the gradient of their full local data at the global model;
the server averages gradients (sample-weighted) and takes one SGD step.
Implemented as a single jitted masked-gradient closure per padded shape.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ....core.aggregate import weighted_mean
from ....ml.engine.train import pad_to, softmax_ce_loss
from ..fedavg.fedavg_api import FedAvgAPI


class FedSGDAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        self._grad_fns: Dict[int, Any] = {}
        self.server_lr = float(getattr(args, "learning_rate", 0.01))

        module = self.module

        def make(padded_n):
            def grad_of(variables, x, y, n_valid):
                def loss_fn(params):
                    vs = dict(variables, params=params)
                    logits = module.apply(vs, x, train=False)
                    mask = (jnp.arange(padded_n) < n_valid).astype(jnp.float32)
                    loss, _ = softmax_ce_loss(logits, y, mask)
                    return loss

                return jax.grad(loss_fn)(variables["params"])

            return jax.jit(grad_of)

        self._make = make

    def train(self):
        # monkey-free: replace each slot's train with gradient computation
        for c in self.client_list:
            c.train = self._client_grad(c)
        return super().train()

    def _client_grad(self, client):
        def run(w_global):
            x, y = client.local_training_data
            n = len(y)
            bs = int(getattr(self.args, "batch_size", 32))
            padded_n = self.trainer.padded_size(n, bs)
            if padded_n not in self._grad_fns:
                self._grad_fns[padded_n] = self._make(padded_n)
            g = self._grad_fns[padded_n](
                w_global, pad_to(jnp.asarray(x), padded_n), pad_to(jnp.asarray(y), padded_n), n
            )
            return g  # "model update" slot carries the gradient

        return run

    def server_update(self, grad_locals: List[Tuple[float, Any]]) -> Any:
        grad_locals = self.aggregator.on_before_aggregation(grad_locals)
        avg_grad = weighted_mean(grad_locals)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - self.server_lr * g, self.w_global["params"], avg_grad
        )
        new_global = dict(self.w_global, params=new_params)
        return self.aggregator.on_after_aggregation(new_global)
