"""SplitNN: split learning with activation/gradient exchange.

Parity with reference ``simulation/mpi/split_nn`` (411 LoC): the model is cut
into a client-side front and a server-side back; per batch the client sends
cut-layer activations up, the server computes loss and returns the
activation gradient, each side updates its own half.  The exchange is made
explicit with ``jax.vjp`` (the seam where a real deployment would put the
transport), while both halves still compile to XLA.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ....utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


class _Front(nn.Module):
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.hidden, name="fc1")(x))


class _Back(nn.Module):
    classes: int = 10

    @nn.compact
    def __call__(self, h):
        h = nn.relu(nn.Dense(64, name="fc2")(h))
        return nn.Dense(self.classes, name="head")(h)


class SplitNNAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        (_, _, _tg, (x_te, y_te), self.local_num, self.local_train, _lt, self.class_num) = dataset
        self.x_te = jnp.asarray(np.asarray(x_te, np.float32))
        self.y_te = jnp.asarray(y_te)
        self.front = _Front(int(getattr(args, "split_hidden", 128)))
        self.back = _Back(self.class_num)
        x0 = jnp.asarray(np.asarray(self.local_train[0][0][:1], np.float32))
        # relay protocol (reference split_nn): ONE front model is passed from
        # client to client; each trains it on its own data in turn
        self.front_params = self.front.init(jax.random.PRNGKey(0), x0)
        h0 = self.front.apply(self.front_params, x0)
        self.back_params = self.back.init(jax.random.PRNGKey(999), h0)
        self.lr = float(getattr(args, "learning_rate", 0.1))
        self.metrics = MetricsLogger(args)

        front, back, lr = self.front, self.back, self.lr

        @jax.jit
        def split_step(fp, bp, x, y):
            # client forward to the cut layer
            h, client_vjp = jax.vjp(lambda p: front.apply(p, x), fp)

            # server forward+backward from the cut activations
            def server_loss(bp, h):
                logits = back.apply(bp, h)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

            loss, (gbp, gh) = jax.value_and_grad(server_loss, argnums=(0, 1))(bp, h)
            # gradient of cut activations travels back to the client
            (gfp,) = client_vjp(gh)
            fp = jax.tree_util.tree_map(lambda p, g: p - lr * g, fp, gfp)
            bp = jax.tree_util.tree_map(lambda p, g: p - lr * g, bp, gbp)
            return fp, bp, loss

        self._split_step = split_step

    def train(self) -> Dict[str, Any]:
        rounds = int(self.args.comm_round)
        bs = int(getattr(self.args, "batch_size", 32))
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        n_clients = int(self.args.client_num_in_total)
        last: Dict[str, Any] = {}
        for r in range(rounds):
            for cid in range(n_clients):  # relay: the front passes client->client
                x, y = self.local_train[cid]
                if len(y) == 0:
                    continue
                x = np.asarray(x, np.float32)
                y = np.asarray(y)
                if len(y) < bs:  # tile small clients to one full batch
                    reps = -(-bs // len(y))
                    x = np.tile(x, (reps,) + (1,) * (x.ndim - 1))[:bs]
                    y = np.tile(y, reps)[:bs]
                x, y = jnp.asarray(x), jnp.asarray(y)
                for s in range(max(1, len(y) // bs)):
                    xb, yb = x[s * bs : (s + 1) * bs], y[s * bs : (s + 1) * bs]
                    if len(yb) < bs:
                        break
                    self.front_params, self.back_params, loss = self._split_step(
                        self.front_params, self.back_params, xb, yb
                    )
            if r % freq == 0 or r == rounds - 1:
                last = self._evaluate(r)
        return last

    def _evaluate(self, r) -> Dict[str, Any]:
        h = self.front.apply(self.front_params, self.x_te)
        logits = self.back.apply(self.back_params, h)
        acc = float(jnp.mean(jnp.argmax(logits, 1) == self.y_te))
        out = {"round": r, "test_acc": round(acc, 4)}
        self.metrics.log(out)
        return out
