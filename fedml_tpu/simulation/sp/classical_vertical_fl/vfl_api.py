"""Classical vertical (feature-split) federated learning.

Parity with reference ``simulation/sp/classical_vertical_fl`` (561 LoC): K
parties hold disjoint feature slices of the SAME samples; only the guest
party holds labels.  Each round: every party computes its partial logits
z_k = X_k w_k; the guest sums them, computes dL/dz, and returns it; each
party updates its slice weights from its own features — raw features never
leave a party.  One jitted step covers all parties (party axis = leading
axis of a stacked weight tensor).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ....utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


class VerticalFLAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        (_, _, (x_tr, y_tr), (x_te, y_te), *_rest, self.class_num) = dataset
        self.parties = int(getattr(args, "vfl_party_num", 2))
        x_tr = np.asarray(x_tr, np.float32).reshape(len(y_tr), -1)
        x_te = np.asarray(x_te, np.float32).reshape(len(y_te), -1)
        # multi-hot labels (NUS-WIDE, the reference's canonical VFL dataset:
        # nus_wide_dataset.py maps concepts to a single training label) ->
        # dominant-concept index for the guest's softmax
        y_tr = np.asarray(y_tr)
        y_te = np.asarray(y_te)
        if y_tr.ndim > 1:
            y_tr = y_tr.argmax(axis=-1)
            y_te = y_te.argmax(axis=-1)
        y_tr = y_tr.astype(np.int32)
        y_te = y_te.astype(np.int32)
        self.feature_slices = np.array_split(np.arange(x_tr.shape[1]), self.parties)
        self.x_tr = [jnp.asarray(x_tr[:, s]) for s in self.feature_slices]
        self.x_te = [jnp.asarray(x_te[:, s]) for s in self.feature_slices]
        self.y_tr = jnp.asarray(y_tr)
        self.y_te = jnp.asarray(y_te)
        key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.w = [
            0.01 * jax.random.normal(jax.random.fold_in(key, k), (len(s), self.class_num))
            for k, s in enumerate(self.feature_slices)
        ]
        self.b = jnp.zeros((self.class_num,))
        self.lr = float(getattr(args, "learning_rate", 0.1))
        self.metrics = MetricsLogger(args)

        @jax.jit
        def step(ws, b, xs, y, lr):
            def loss_fn(ws_b):
                ws, b = ws_b
                z = sum(x @ w for x, w in zip(xs, ws)) + b  # guest sums partial logits
                logp = jax.nn.log_softmax(z)
                return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

            loss, grads = jax.value_and_grad(loss_fn)((ws, b))
            gws, gb = grads
            new_ws = [w - lr * g for w, g in zip(ws, gws)]
            return new_ws, b - lr * gb, loss

        self._step = step

    def train(self) -> Dict[str, Any]:
        rounds = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last = {}
        for r in range(rounds):
            self.w, self.b, loss = self._step(self.w, self.b, self.x_tr, self.y_tr, self.lr)
            if r % freq == 0 or r == rounds - 1:
                z = sum(x @ w for x, w in zip(self.x_te, self.w)) + self.b
                acc = float(jnp.mean((jnp.argmax(z, 1) == self.y_te)))
                last = {"round": r, "test_acc": round(acc, 4), "train_loss": round(float(loss), 4)}
                self.metrics.log(last)
        return last
