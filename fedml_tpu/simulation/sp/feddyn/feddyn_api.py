"""FedDyn: dynamic regularization (Acar et al.).

Beyond-reference algorithm: each client keeps a lagrangian-style state h_i;
the local gradient is g - h_i + alpha*(w - w_global) (the engine's grad_hook
with extra=h_i), after training h_i <- h_i - alpha*(w_i - w_global), and the
server average subtracts the population-mean h over alpha.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax

from ....core.aggregate import tree_zeros_like, weighted_mean
from ....ml.trainer.cls_trainer import ModelTrainerCLS
from ..fedavg.fedavg_api import FedAvgAPI


class FedDynAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        self.alpha = float(getattr(args, "feddyn_alpha", 0.01))
        alpha = self.alpha

        def hook(grads, params, anchor, extra):
            return jax.tree_util.tree_map(
                lambda g, h, p, a: g - h + alpha * (p - a), grads, extra, params, anchor
            )

        self.trainer = ModelTrainerCLS(model, args, grad_hook=hook)
        self.client_list = []
        self._setup_clients()
        self.h_clients: Dict[int, Any] = {}
        self.h_mean = tree_zeros_like(self.w_global["params"])

    def _setup_clients(self):
        super()._setup_clients()
        for c in self.client_list:
            c.train = self._client_train(c)

    def _client_train(self, client):
        def run(w_global):
            cid = client.client_idx
            h_i = self.h_clients.get(cid)
            if h_i is None:
                h_i = tree_zeros_like(w_global["params"])
            self.trainer.set_model_params(w_global)
            res = self.trainer.train(client.local_training_data, None, self.args, extra=h_i)
            self.h_clients[cid] = jax.tree_util.tree_map(
                lambda h, wi, wg: h - self.alpha * (wi - wg),
                h_i, res.variables["params"], w_global["params"],
            )
            return res.variables

        return run

    def checkpoint_state(self):
        state = super().checkpoint_state()
        state["h_mean"] = self.h_mean
        state["h_clients"] = {str(k): v for k, v in self.h_clients.items()}
        return state

    def restore_checkpoint_state(self, state):
        super().restore_checkpoint_state(state)
        self.h_mean = state["h_mean"]
        self.h_clients = {int(k): v for k, v in state.get("h_clients", {}).items()}

    def server_update(self, w_locals: List[Tuple[float, Any]]) -> Any:
        w_locals = self.aggregator.on_before_aggregation(w_locals)
        avg = weighted_mean(w_locals)
        if self.h_clients:
            n_total = float(self.args.client_num_in_total)
            # lint_agg: allow — FedDyn's algorithm-internal h-state fold,
            # not a client-update aggregation path
            self.h_mean = jax.tree_util.tree_map(  # lint_agg: allow
                lambda *xs: sum(xs) / n_total, *self.h_clients.values()
            )
        new_params = jax.tree_util.tree_map(
            lambda p, h: p - h / self.alpha, avg["params"], self.h_mean
        )
        return self.aggregator.on_after_aggregation(dict(avg, params=new_params))
