"""FedSeg: federated semantic segmentation (reference ``simulation/mpi/fedseg``,
1168 LoC): FedAvg over a segmentation model with per-pixel CE and mIoU eval.

The round protocol IS FedAvg — what differs is the task head: per-pixel
softmax-CE on [B, H, W, C] logits and mean-IoU as the reported metric."""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ....core.aggregate import weighted_mean
from ....models.unet import UNet
from ....utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


class FedSegAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        (
            _tn, _ten, _tg, self.test_global, self.local_num, self.local_train, _lt, self.class_num,
        ) = dataset
        self.bs = int(getattr(args, "batch_size", 8))
        seed = int(getattr(args, "random_seed", 0))
        lr = float(getattr(args, "learning_rate", 0.01))

        import flax.linen as nn

        # honor any provided flax segmentation module (must map [B,H,W,C] ->
        # [B,H,W,classes]); only build the default UNet when none was given
        self.net = model if isinstance(model, nn.Module) else UNet(num_classes=self.class_num)
        sample = jnp.asarray(next(iter(self.local_train.values()))[0][: 1])
        self.params = self.net.init(jax.random.PRNGKey(seed), sample)
        self.tx = optax.sgd(lr, momentum=0.9)
        self.metrics = MetricsLogger(args)
        self.eval_history: List[Dict[str, Any]] = []

        net, tx = self.net, self.tx

        @jax.jit
        def local_step(params, opt, x, masks):
            def loss_fn(p):
                logits = net.apply(p, x)
                return jnp.mean(
                    optax.softmax_cross_entropy_with_integer_labels(logits, masks)
                )

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt = tx.update(grads, opt, params)
            return optax.apply_updates(params, updates), opt, loss

        @jax.jit
        def infer(params, x):
            return net.apply(params, x)

        self._local_step, self._infer = local_step, infer

    def train(self) -> Dict[str, Any]:
        comm_round = int(self.args.comm_round)
        epochs = int(getattr(self.args, "epochs", 1))
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            from ....core.sampling import client_sampling

            sampled = client_sampling(
                round_idx, int(self.args.client_num_in_total), int(self.args.client_num_per_round)
            )
            locals_: List[Tuple[float, Any]] = []
            for cid in sampled:
                x, masks = self.local_train[int(cid)]
                params = self.params
                opt = self.tx.init(params)
                for _ in range(epochs):
                    for s in range(0, len(masks) - self.bs + 1, self.bs):
                        params, opt, _ = self._local_step(
                            params, opt,
                            jnp.asarray(x[s : s + self.bs]),
                            jnp.asarray(masks[s : s + self.bs]),
                        )
                locals_.append((float(self.local_num[int(cid)]), params))
            self.params = weighted_mean(locals_)
            self.metrics.log({"round": round_idx})
            if round_idx % freq == 0 or round_idx == comm_round - 1:
                last = self._test_global(round_idx)
        return last

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        from ....models.unet import iou_counts

        x, masks = self.test_global
        inter = np.zeros(self.class_num)
        union = np.zeros(self.class_num)
        correct = total = 0
        for s in range(0, len(masks), 64):
            logits = self._infer(self.params, jnp.asarray(x[s : s + 64]))
            m = jnp.asarray(masks[s : s + 64])
            i, u = iou_counts(logits, m, self.class_num)
            inter += np.asarray(i)
            union += np.asarray(u)
            correct += int(jnp.sum(jnp.argmax(logits, -1) == m))
            total += int(m.size)
        present = union > 0
        miou = float(np.mean(inter[present] / union[present])) if present.any() else 0.0
        out = {
            "round": round_idx,
            "test_acc": round(correct / max(total, 1), 4),  # pixel accuracy
            "test_miou": round(miou, 4),  # dataset-level mIoU
        }
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("fedseg eval: %s", out)
        return out
