"""FedGKT: group knowledge transfer (He et al.).

Parity with reference ``simulation/mpi/fedgkt`` (1025 LoC): clients train a
small edge network locally (CE + KL toward the server's per-sample logits),
upload their *feature maps + logits + labels* — never weights — and the
server trains a large tower on the union of client features (CE + KL toward
each client's logits), returning fresh per-sample server logits for the next
round.  Client models stay local; the only aggregated object is knowledge.

TPU shape: client and server training are each ONE jitted step function
scanned over minibatches; the transfer set is a device-resident array stack
(features ride HBM, not a message queue).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ....models.gkt import GKTClientNet, GKTServerNet
from ....utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


def _kl(p_logits, q_logits, temperature: float):
    """KL(softmax(p/T) || softmax(q/T)) averaged over the batch."""
    p = jax.nn.log_softmax(p_logits / temperature)
    q = jax.nn.log_softmax(q_logits / temperature)
    return jnp.mean(jnp.sum(jnp.exp(p) * (p - q), axis=-1)) * temperature**2


def _batched(n: int, bs: int):
    return [(s, min(s + bs, n)) for s in range(0, n, bs)]


class FedGKTAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        (
            _tn, _ten, _tg, self.test_global, self.local_num, self.local_train, _lt, self.class_num,
        ) = dataset
        self.temperature = float(getattr(args, "gkt_temperature", 3.0))
        self.alpha = float(getattr(args, "gkt_alpha", 1.0))  # KD weight
        self.server_epochs = int(getattr(args, "gkt_server_epochs", 1))
        self.bs = int(getattr(args, "batch_size", 32))
        lr = float(getattr(args, "learning_rate", 0.01))
        seed = int(getattr(args, "random_seed", 0))

        # honor a hub-built edge net (model key gkt_client/resnet8_gkt);
        # the server tower is always GKT-internal
        self.client_net = model if isinstance(model, GKTClientNet) else GKTClientNet(
            num_classes=self.class_num
        )
        self.server_net = GKTServerNet(
            num_classes=self.class_num,
            width=int(getattr(args, "gkt_server_width", 64)),
            blocks=int(getattr(args, "gkt_server_blocks", 3)),
        )
        key = jax.random.PRNGKey(seed)
        sample = jnp.asarray(next(iter(self.local_train.values()))[0][: self.bs])
        # per-client edge params (NEVER aggregated — GKT's defining property)
        self.client_params: Dict[int, Any] = {}
        self._proto_client_params = self.client_net.init(key, sample)
        feats, _ = self.client_net.apply(self._proto_client_params, sample)
        self.server_params = self.server_net.init(jax.random.fold_in(key, 1), feats)

        self.client_tx = optax.sgd(lr, momentum=0.9)
        self.server_tx = optax.sgd(lr, momentum=0.9)
        self.metrics = MetricsLogger(args)
        # per-client server logits from the previous round (the downloaded
        # knowledge); empty before round 0
        self.server_logits: Dict[int, np.ndarray] = {}
        self._build_steps()
        self.eval_history: List[Dict[str, Any]] = []

    def _build_steps(self):
        cnet, snet = self.client_net, self.server_net
        ctx, stx = self.client_tx, self.server_tx
        alpha, T = self.alpha, self.temperature

        @jax.jit
        def client_step(params, opt, x, y, s_logits, has_kd):
            def loss_fn(p):
                _, logits = cnet.apply(p, x)
                ce = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, y))
                kd = _kl(s_logits, logits, T)
                return ce + alpha * has_kd * kd

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt = ctx.update(grads, opt, params)
            return optax.apply_updates(params, updates), opt, loss

        @jax.jit
        def client_extract(params, x):
            return cnet.apply(params, x)  # (features, logits)

        @jax.jit
        def server_step(params, opt, feats, y, c_logits):
            def loss_fn(p):
                logits = snet.apply(p, feats)
                ce = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, y))
                kd = _kl(c_logits, logits, T)
                return ce + alpha * kd

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt = stx.update(grads, opt, params)
            return optax.apply_updates(params, updates), opt, loss

        @jax.jit
        def server_infer(params, feats):
            return snet.apply(params, feats)

        self._client_step, self._client_extract = client_step, client_extract
        self._server_step, self._server_infer = server_step, server_infer

    # -- round ----------------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        from ....core.sampling import client_sampling

        comm_round = int(self.args.comm_round)
        epochs = int(getattr(self.args, "epochs", 1))
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            client_ids = [int(c) for c in client_sampling(
                round_idx, int(self.args.client_num_in_total),
                int(self.args.client_num_per_round),
            )]
            transfer = {}  # cid -> (features, logits, labels)
            for cid in client_ids:
                x, y = self.local_train[cid]
                n = len(y) - (len(y) % self.bs) or self.bs
                x = jnp.asarray(x[:n]) if len(y) >= self.bs else jnp.asarray(
                    np.resize(x, (self.bs,) + x.shape[1:]))
                y = jnp.asarray(y[:n]) if len(y) >= self.bs else jnp.asarray(np.resize(y, self.bs))
                params = self.client_params.get(cid, self._proto_client_params)
                opt = self.client_tx.init(params)
                s_log = self.server_logits.get(cid)
                has_kd = jnp.float32(0.0 if s_log is None else 1.0)
                if s_log is None:
                    s_log = np.zeros((len(y), self.class_num), np.float32)
                for _ in range(epochs):
                    for s, e in _batched(len(y), self.bs):
                        if e - s < self.bs:
                            continue
                        params, opt, _ = self._client_step(
                            params, opt, x[s:e], y[s:e], jnp.asarray(s_log[s:e]), has_kd
                        )
                self.client_params[cid] = params
                # extract in fixed-size batches: one compiled shape for every
                # client/dataset size (n is already a multiple of bs here)
                f_parts, l_parts = [], []
                for s, e in _batched(len(y), self.bs):
                    f, l = self._client_extract(params, x[s:e])
                    f_parts.append(np.asarray(f))
                    l_parts.append(np.asarray(l))
                transfer[cid] = (np.concatenate(f_parts), np.concatenate(l_parts), np.asarray(y))

            # server: train tower on the union of client features
            opt = self.server_tx.init(self.server_params)
            loss = 0.0
            for _ in range(self.server_epochs):
                for cid, (feats, c_logits, y) in transfer.items():
                    for s, e in _batched(len(y), self.bs):
                        if e - s < self.bs:
                            continue
                        self.server_params, opt, loss = self._server_step(
                            self.server_params, opt,
                            jnp.asarray(feats[s:e]), jnp.asarray(y[s:e]),
                            jnp.asarray(c_logits[s:e]),
                        )
            # download fresh knowledge (same fixed-batch discipline)
            self.server_logits = {}
            for cid, (feats, _cl, y) in transfer.items():
                parts = [
                    np.asarray(self._server_infer(self.server_params, jnp.asarray(feats[s:e])))
                    for s, e in _batched(len(y), self.bs)
                ]
                self.server_logits[cid] = np.concatenate(parts)
            self.metrics.log({"round": round_idx, "server_loss": float(loss)})
            if round_idx % freq == 0 or round_idx == comm_round - 1:
                last = self._test_global(round_idx, client_ids[0])
        return last

    def _test_global(self, round_idx: int, probe_cid: int) -> Dict[str, Any]:
        """Edge extractor (probe client) + server tower on the global test set."""
        x, y = self.test_global
        correct = total = 0
        params = self.client_params.get(probe_cid, self._proto_client_params)
        for s, e in _batched(len(y), 256):
            feats, _ = self._client_extract(params, jnp.asarray(x[s:e]))
            logits = self._server_infer(self.server_params, feats)
            correct += int(jnp.sum(jnp.argmax(logits, -1) == jnp.asarray(y[s:e])))
            total += e - s
        out = {"round": round_idx, "test_acc": round(correct / max(total, 1), 4)}
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("fedgkt eval: %s", out)
        return out
