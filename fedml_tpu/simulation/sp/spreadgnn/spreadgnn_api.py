"""SpreadGNN: serverless decentralized multi-task GNN FL.

Parity with reference ``research/SpreadGNN`` (``mpi_decentralized_fl_example.py``
driving decentralized periodic averaging over partially-labeled multi-task
molecule sets): no server; nodes train locally on masked multi-task BCE
("mtl_bce" engine loss) and gossip over the topology's mixing matrix — but
ONLY the shared GNN encoder is mixed.  Task heads stay node-local (the
paper's periodic-averaging-with-personalized-heads design), which is the
whole point of multi-task decentralization: every node keeps a head tuned
to its own observed task subset.

TPU-first formulation: node models are stacked on a leading axis and the
gossip is one einsum with the mixing matrix applied ONLY to non-head leaves
(a path-filtered tree_map); head leaves pass through untouched.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..decentralized.decentralized_api import DecentralizedFLAPI

logger = logging.getLogger(__name__)


def _is_local_head(path: Tuple, head_names: Tuple[str, ...]) -> bool:
    """One head-matching rule for BOTH backends (the in-mesh SpreadGNN
    imports this): a leaf is a personalized head iff any path segment is an
    exact head-name match."""
    keys = {getattr(k, "key", getattr(k, "name", None)) for k in path}
    return any(h in keys for h in head_names)


def head_names_from(args) -> Tuple[str, ...]:
    """Shared ``mtl_local_head_names`` parsing (default: 'readout')."""
    heads = getattr(args, "mtl_local_head_names", None) or ("readout",)
    if isinstance(heads, str):
        heads = (heads,)
    return tuple(heads)


class SpreadGNNAPI(DecentralizedFLAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        self.head_names = head_names_from(args)

        @jax.jit
        def gossip(stacked, mix):
            def mix_leaf(path, x):
                if _is_local_head(path, self.head_names):
                    return x  # personalized head: never averaged
                return jnp.tensordot(mix, x, axes=(1, 0))

            return jax.tree_util.tree_map_with_path(mix_leaf, stacked)

        self._gossip = gossip

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        """Personalized eval (SpreadGNN reports mean over nodes, each with
        its own task head) instead of consensus-model eval."""
        corr = loss = tot = 0.0
        for m in self.node_models:
            self.aggregator.set_model_params(m)
            stats = self.aggregator.test(self.test_data_global, self.device, self.args)
            corr += stats["test_correct"]
            loss += stats["test_loss"]
            tot += stats["test_total"]
        out = {
            "round": round_idx,
            "test_acc": round(corr / max(tot, 1.0), 4),
            "test_loss": round(loss / max(tot, 1.0), 4),
        }
        self.metrics.log(out)
        logger.info("eval (per-node mean): %s", out)
        return out
