"""FedGAN: federated generative adversarial training.

Parity with reference ``simulation/mpi/fedgan`` (790 LoC): every client
trains its (G, D) pair locally (alternating D/G steps on local data), the
server FedAvg-aggregates both networks.  One jitted local loop per shape.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ....core.aggregate import weighted_mean
from ....models.gan import MNISTDiscriminator, MNISTGenerator
from ....utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


class FedGanAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        (_, _, _tg, _teg, self.local_num, self.local_train, _lt, _cn) = dataset
        self.latent = int(getattr(args, "gan_latent_dim", 100))
        self.G = MNISTGenerator(self.latent)
        self.D = MNISTDiscriminator()
        key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        z0 = jnp.zeros((1, self.latent))
        self.g_params = self.G.init(key, z0)
        x0 = self.G.apply(self.g_params, z0)
        self.d_params = self.D.init(jax.random.fold_in(key, 1), x0)
        lr = float(getattr(args, "learning_rate", 2e-4))
        self.g_tx, self.d_tx = optax.adam(lr, b1=0.5), optax.adam(lr, b1=0.5)
        self.metrics = MetricsLogger(args)
        self._rng = jax.random.fold_in(key, 2)

        G, D, g_tx, d_tx = self.G, self.D, self.g_tx, self.d_tx
        bs = int(getattr(args, "batch_size", 32))
        latent = self.latent

        def bce(logits, target):
            return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, target))

        @jax.jit
        def local_gan(gp, dp, x, rng, steps):
            g_opt = g_tx.init(gp)
            d_opt = d_tx.init(dp)

            def body(i, carry):
                gp, dp, g_opt, d_opt, rng = carry
                rng, kz1, kz2, kb = jax.random.split(rng, 4)
                start = (i * bs) % jnp.maximum(x.shape[0] - bs, 1)
                real = jax.lax.dynamic_slice_in_dim(x, start, bs)

                def d_loss(dp):
                    fake = G.apply(gp, jax.random.normal(kz1, (bs, latent)))
                    lr_ = D.apply(dp, real)
                    lf = D.apply(dp, fake)
                    return bce(lr_, jnp.ones_like(lr_)) + bce(lf, jnp.zeros_like(lf))

                dl, gd = jax.value_and_grad(d_loss)(dp)
                du, d_opt = d_tx.update(gd, d_opt, dp)
                dp = optax.apply_updates(dp, du)

                def g_loss(gp):
                    fake = G.apply(gp, jax.random.normal(kz2, (bs, latent)))
                    return bce(D.apply(dp, fake), jnp.ones((bs, 1)))

                gl, gg = jax.value_and_grad(g_loss)(gp)
                gu, g_opt = g_tx.update(gg, g_opt, gp)
                gp = optax.apply_updates(gp, gu)
                return (gp, dp, g_opt, d_opt, rng)

            gp, dp, _, _, _ = jax.lax.fori_loop(0, steps, body, (gp, dp, g_opt, d_opt, rng))
            return gp, dp

        self._local_gan = local_gan

    def train(self) -> Dict[str, Any]:
        rounds = int(self.args.comm_round)
        per_round = int(self.args.client_num_per_round)
        steps = int(getattr(self.args, "gan_local_steps", 20))
        last: Dict[str, Any] = {}
        from ....core.sampling import client_sampling

        for r in range(rounds):
            sampled = client_sampling(r, int(self.args.client_num_in_total), per_round)
            g_locals: List[Tuple[float, Any]] = []
            d_locals: List[Tuple[float, Any]] = []
            bs = int(getattr(self.args, "batch_size", 32))
            for cid in sampled:
                x, _y = self.local_train[int(cid)]
                x = np.asarray(x, np.float32)
                if len(x) == 0:
                    continue
                if len(x) < bs:  # tile small clients up to one full batch
                    x = np.tile(x, (-(-bs // len(x)),) + (1,) * (x.ndim - 1))[:bs]
                x = jnp.asarray(x)
                if x.ndim == 3:
                    x = x[..., None]
                x = x * 2.0 - 1.0  # tanh range
                self._rng, sub = jax.random.split(self._rng)
                gp, dp = self._local_gan(self.g_params, self.d_params, x, sub, steps)
                n = float(len(x))
                g_locals.append((n, gp))
                d_locals.append((n, dp))
            self.g_params = weighted_mean(g_locals)
            self.d_params = weighted_mean(d_locals)
            # track D's realism score on generated samples as a health metric
            self._rng, sub = jax.random.split(self._rng)
            fake = self.G.apply(self.g_params, jax.random.normal(sub, (64, self.latent)))
            d_fake = float(jnp.mean(jax.nn.sigmoid(self.D.apply(self.d_params, fake))))
            last = {"round": r, "d_fake_score": round(d_fake, 4)}
            self.metrics.log(last)
        return last
