"""Single-process FedAvg simulator.

Round-protocol parity with reference ``simulation/sp/fedavg/fedavg_api.py``:
per-round seeded client sampling (:125-133), ``client_num_per_round`` client
slots re-bound to sampled data (:86-101), sample-weighted aggregation
(:142-157), periodic test on all clients (:111-118).  The local training loop
itself is the compiled engine (ml/engine/train.py) — one XLA program per
padded shape, shared by all clients.

Server-side hooks (attacker injection, defense, central DP) run exactly where
the reference runs them: between collection and aggregation.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from ....core import obs
from ....core.aggregate import FedMLAggOperator
from ....core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
from ....core.security.fedml_attacker import FedMLAttacker
from ....core.security.fedml_defender import FedMLDefender
from ....ml.aggregator.aggregator_creator import create_server_aggregator
from ....ml.engine.train import init_variables
from ....ml.trainer.trainer_creator import create_model_trainer
from ....utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


class Client:
    """A reusable client slot (reference fedavg_api.py Client)."""

    def __init__(self, client_idx, local_training_data, local_test_data, local_sample_number, args, trainer):
        self.client_idx = client_idx
        self.local_training_data = local_training_data
        self.local_test_data = local_test_data
        self.local_sample_number = local_sample_number
        self.args = args
        self.trainer = trainer

    def update_local_dataset(self, client_idx, local_training_data, local_test_data, local_sample_number):
        self.client_idx = client_idx
        self.local_training_data = local_training_data
        self.local_test_data = local_test_data
        self.local_sample_number = local_sample_number
        self.trainer.set_id(client_idx)

    def train(self, w_global):
        self.trainer.set_model_params(w_global)
        self.trainer.on_before_local_training(self.local_training_data, None, self.args)
        self.trainer.train(self.local_training_data, None, self.args)
        self.trainer.on_after_local_training(self.local_training_data, None, self.args)
        return self.trainer.get_model_params()

    def local_test(self, use_test_set: bool):
        data = self.local_test_data if use_test_set else self.local_training_data
        return self.trainer.test(data, None, self.args)


class FedAvgAPI:
    def __init__(self, args, device, dataset, model):
        self.args = args
        self.device = device
        (
            self.train_global_num,
            self.test_global_num,
            self.train_data_global,
            self.test_data_global,
            self.train_data_local_num_dict,
            self.train_data_local_dict,
            self.test_data_local_dict,
            self.class_num,
        ) = dataset
        self.module = model
        sample = jax.numpy.asarray(self.train_data_global[0][:1])
        self.w_global = init_variables(model, sample, seed=int(getattr(args, "random_seed", 0)))

        self.trainer = create_model_trainer(model, args)
        self.aggregator = create_server_aggregator(model, args)
        self.aggregator.set_model_params(self.w_global)

        self.client_list: List[Client] = []
        self._setup_clients()
        self.metrics = MetricsLogger(args)
        self.round_times: List[float] = []
        self.samples_per_round: List[int] = []
        # population subsystem: registry + selection policy (uniform is
        # bit-identical to the legacy client_sampling schedule)
        from ....core.population import PopulationManager

        n_total = int(self.args.client_num_in_total)
        try:
            samples = [int(self.train_data_local_num_dict[i]) for i in range(n_total)]
        except (KeyError, IndexError, TypeError):
            samples = None
        self.population = PopulationManager.from_args(
            self.args, np.arange(n_total), num_samples=samples,
            rng_style="mt19937",
        )

    def _setup_clients(self):
        for client_idx in range(int(self.args.client_num_per_round)):
            c = Client(
                client_idx,
                self.train_data_local_dict[client_idx],
                self.test_data_local_dict[client_idx],
                self.train_data_local_num_dict[client_idx],
                self.args,
                self.trainer,
            )
            self.client_list.append(c)

    def _client_sampling(self, round_idx: int) -> List[int]:
        return [int(c) for c in self.population.select(
            round_idx, int(self.args.client_num_per_round)
        )]

    def train(self) -> Dict[str, Any]:
        from ....core.checkpoint import checkpoint_frequency, maybe_checkpointer

        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last_metrics: Dict[str, Any] = {}
        ckpt = maybe_checkpointer(self.args)
        start_round = 0
        if ckpt is not None and ckpt.latest_step() is not None:
            step, state = ckpt.restore()
            self.restore_checkpoint_state(state)
            self.aggregator.set_model_params(self.w_global)
            start_round = step + 1
            logger.info("resumed from checkpoint round %d", step)
        # in-process loopback telemetry: the simulator runs the same
        # capture→blob→merge pipeline the distributed managers use, so a
        # simulation's trace_report has the identical cross-host shape
        # (remote train sub-spans, per-client attribution) as a real run
        tele_cap = obs.make_client_telemetry(0)
        tele_merger = obs.make_telemetry_merger()
        for round_idx in range(start_round, comm_round):
            t0 = time.time()
            # one span tree per round; in-process simulation means select/
            # train/aggregate are direct children of the root (no transport,
            # so no invite/upload legs).  annotate=True nests the round under
            # a jax.profiler.TraceAnnotation when a device trace is running.
            rsp = obs.round_span(round_idx, annotate=True, mode="simulation_sp")
            self.trainer.round_idx = round_idx  # deterministic per-round RNG stream
            with obs.span("select", rsp.ctx, round_idx=round_idx,
                          k=int(self.args.client_num_per_round)):
                client_indexes = self._client_sampling(round_idx)
            logger.info("round %d: clients %s", round_idx, client_indexes)
            w_locals: List[Tuple[float, Any]] = []
            attacker = FedMLAttacker.get_instance()
            if attacker.is_attack_enabled():
                # model-side attack corrupts the same population clients the
                # data-side poisoning targets (slots differ under sampling)
                attacker.set_round_clients(client_indexes)
            for slot, idx in enumerate(client_indexes):
                client = self.client_list[slot]
                local_data = self.train_data_local_dict[idx]
                if attacker.is_data_poisoning_attack():
                    local_data = self._poisoned_copy(idx, local_data, attacker)
                client.update_local_dataset(
                    idx,
                    local_data,
                    self.test_data_local_dict[idx],
                    self.train_data_local_num_dict[idx],
                )
                tc0 = time.monotonic()
                cc0 = obs.compile_seconds_total()
                with obs.span("client.train", rsp.ctx, round_idx=round_idx,
                              seq=slot, annotate=True, client=int(idx)):
                    w = client.train(self.w_global)
                if tele_cap is not None:
                    dt_c = time.monotonic() - tc0
                    compile_s = obs.compile_seconds_total() - cc0
                    tctx = tele_cap.record_span(
                        "client.train", dt_c, parent=rsp.ctx,
                        round_idx=round_idx, seq=slot, client=int(idx))
                    if compile_s > 0:
                        tele_cap.record_span(
                            "client.train.compile", compile_s, parent=tctx,
                            round_idx=round_idx, seq=slot)
                    tele_cap.record_span(
                        "client.train.step", max(dt_c - compile_s, 0.0),
                        parent=tctx, round_idx=round_idx, seq=slot)
                w_locals.append((float(client.local_sample_number), w))
            self.samples_per_round.append(
                int(sum(n for n, _ in w_locals)) * int(getattr(self.args, "epochs", 1))
            )

            with obs.span("aggregate", rsp.ctx, round_idx=round_idx,
                          annotate=True, n_uploads=len(w_locals)):
                self.w_global = self.server_update(w_locals)
                self.aggregator.set_model_params(self.w_global)

            dt = time.time() - t0
            if obs.enabled() and len(self.round_times) >= 3:
                med = float(np.median(self.round_times))
                if dt > obs.slow_round_factor() * med:
                    obs.span_event("slow_round", rsp.ctx, round_idx=round_idx,
                                   dt_s=round(dt, 4), median_s=round(med, 4))
            obs.histogram_observe("round.seconds", float(dt))
            rsp.end(reason="closed")
            if tele_cap is not None and tele_merger is not None:
                tele_cap.sample_resources()
                blob = tele_cap.drain()
                if blob:
                    tele_merger.merge(blob)
            obs.maybe_export_metrics()
            self.round_times.append(dt)
            self.metrics.log({"round": round_idx, "round_time_s": round(dt, 4)})
            # population accounting (synchronous round: invited == reported)
            self.population.observe_round(round_idx, client_indexes, seconds=dt)
            if ckpt is not None and (
                round_idx % checkpoint_frequency(self.args) == 0 or round_idx == comm_round - 1
            ):
                ckpt.save(round_idx, self.checkpoint_state())
            if round_idx % freq == 0 or round_idx == comm_round - 1:
                last_metrics = self._test_global(round_idx)
        return last_metrics

    def _poisoned_copy(self, client_idx: int, local_data, attacker) -> Any:
        """Data-poisoning attacks transform a MALICIOUS client's local set
        before training (reference wires this in its data loaders; here it's
        per-round so the clean dict is never mutated).  Edge-case selection
        gets current-model logits."""
        import jax.numpy as jnp

        num_total = int(self.args.client_num_in_total)
        if int(client_idx) not in set(attacker.get_byzantine_idxs(num_total)):
            return local_data  # benign client: skip (and skip the forward pass)
        x, y = local_data
        logits = None
        from ....core.security.constants import ATTACK_METHOD_EDGE_CASE_BACKDOOR

        if attacker.attack_type == ATTACK_METHOD_EDGE_CASE_BACKDOOR:
            logits = self.module.apply(self.w_global, jnp.asarray(x), train=False)
        px, py = attacker.poison_local_data(
            client_idx, num_total, x, y, logits=logits
        )
        return (px, py)

    def checkpoint_state(self) -> Dict[str, Any]:
        """Full server-side state to persist; algorithm subclasses MUST extend
        with their own state (SCAFFOLD control variates, FedOpt moments, ...)
        or a resumed run silently diverges from an uninterrupted one."""
        return {"w_global": self.w_global}

    def restore_checkpoint_state(self, state: Dict[str, Any]) -> None:
        self.w_global = state["w_global"]

    def server_update(self, w_locals: List[Tuple[float, Any]]) -> Any:
        """Aggregation step with hooks at reference positions; the override
        point for the algorithm zoo (FedOpt/FedNova/... subclass this)."""
        w_locals = self.aggregator.on_before_aggregation(w_locals)
        w_global = self.aggregator.aggregate(w_locals)
        return self.aggregator.on_after_aggregation(w_global)

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        stats = self.aggregator.test(self.test_data_global, self.device, self.args)
        acc = stats["test_correct"] / stats["test_total"]
        loss = stats["test_loss"] / stats["test_total"]
        out = {"round": round_idx, "test_acc": round(float(acc), 4), "test_loss": round(float(loss), 4)}
        # task-specific extras (e.g. detection's test_mean_iou) pass through
        for k, v in stats.items():
            if k.startswith("test_") and k not in ("test_correct", "test_total", "test_loss"):
                out[k] = round(float(v), 4)
        self.metrics.log(out)
        logger.info("eval: %s", out)
        return out
