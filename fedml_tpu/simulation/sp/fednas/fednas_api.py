"""FedNAS: federated neural architecture search (reference
``simulation/mpi/fednas``, 890 LoC).

Each round, sampled clients run DARTS search steps on local data — updating
both network weights w and architecture logits alpha (the reference's
single-level MiLeNAS-style joint update) — and the server FedAvg-aggregates
BOTH pytrees.  After the final round the discrete architecture is derived by
per-edge argmax (models/darts.py ``derive_architecture``).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ....core.aggregate import weighted_mean
from ....models.darts import DARTSNetwork, derive_architecture, init_alphas
from ....utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


class FedNASAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        (
            _tn, _ten, _tg, self.test_global, self.local_num, self.local_train, _lt, self.class_num,
        ) = dataset
        self.bs = int(getattr(args, "batch_size", 32))
        seed = int(getattr(args, "random_seed", 0))
        w_lr = float(getattr(args, "learning_rate", 0.025))
        a_lr = float(getattr(args, "arch_learning_rate", 3e-3))

        self.net = model if isinstance(model, DARTSNetwork) else DARTSNetwork(
            num_classes=self.class_num
        )
        self.alphas = init_alphas(seed)
        sample = jnp.asarray(next(iter(self.local_train.values()))[0][: self.bs])
        self.params = self.net.init(jax.random.PRNGKey(seed), sample, self.alphas)
        self.w_tx = optax.sgd(w_lr, momentum=0.9)
        self.a_tx = optax.adam(a_lr)
        self.metrics = MetricsLogger(args)
        self.eval_history: List[Dict[str, Any]] = []

        net, w_tx, a_tx = self.net, self.w_tx, self.a_tx

        @jax.jit
        def search_step(params, alphas, w_opt, a_opt, x, y):
            def loss_fn(p, a):
                logits = net.apply(p, x, a)
                return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, y))

            loss, (gw, ga) = jax.value_and_grad(loss_fn, argnums=(0, 1))(params, alphas)
            wu, w_opt = w_tx.update(gw, w_opt, params)
            au, a_opt = a_tx.update(ga, a_opt, alphas)
            return optax.apply_updates(params, wu), optax.apply_updates(alphas, au), w_opt, a_opt, loss

        @jax.jit
        def infer(params, alphas, x):
            return net.apply(params, x, alphas)

        self._search_step, self._infer = search_step, infer

    def train(self) -> Dict[str, Any]:
        comm_round = int(self.args.comm_round)
        epochs = int(getattr(self.args, "epochs", 1))
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            from ....core.sampling import client_sampling

            sampled = client_sampling(
                round_idx, int(self.args.client_num_in_total), int(self.args.client_num_per_round)
            )
            locals_: List[Tuple[float, Any]] = []
            alpha_locals: List[Tuple[float, Any]] = []
            for cid in sampled:
                x, y = self.local_train[int(cid)]
                params, alphas = self.params, self.alphas
                w_opt, a_opt = self.w_tx.init(params), self.a_tx.init(alphas)
                for _ in range(epochs):
                    for s in range(0, len(y) - self.bs + 1, self.bs):
                        params, alphas, w_opt, a_opt, loss = self._search_step(
                            params, alphas, w_opt, a_opt,
                            jnp.asarray(x[s : s + self.bs]), jnp.asarray(y[s : s + self.bs]),
                        )
                n = float(self.local_num[int(cid)])
                locals_.append((n, params))
                alpha_locals.append((n, alphas))
            self.params = weighted_mean(locals_)
            self.alphas = weighted_mean(alpha_locals)
            self.metrics.log({"round": round_idx})
            if round_idx % freq == 0 or round_idx == comm_round - 1:
                last = self._test_global(round_idx)
        last["genotype"] = derive_architecture(self.alphas)
        logger.info("derived architecture: %s", last["genotype"])
        return last

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        x, y = self.test_global
        correct = total = 0
        for s in range(0, len(y), 256):
            logits = self._infer(self.params, self.alphas, jnp.asarray(x[s : s + 256]))
            correct += int(jnp.sum(jnp.argmax(logits, -1) == jnp.asarray(y[s : s + 256])))
            total += len(y[s : s + 256])
        out = {"round": round_idx, "test_acc": round(correct / max(total, 1), 4)}
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("fednas eval: %s", out)
        return out
