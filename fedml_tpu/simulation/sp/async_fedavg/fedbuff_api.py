"""Single-process FedBuff simulator (``fl_mode=async``).

Buffered-async counterpart of :class:`~..fedavg.fedavg_api.FedAvgAPI`,
sharing the exact execution model of the message-plane servers
(``core/async_fl``): a deterministic virtual-arrival-time queue orders
client report events (per-client simulated durations drawn once from
``random_seed``); the server parks each accepted delta in an
:class:`~....core.async_fl.UpdateBuffer` and flushes through
``server_update`` once ``async_buffer_size`` deltas accrue.  Staleness is
flushes missed (global version - version trained against) and discounts
the aggregation weight via ``async_staleness_policy``.  ``comm_round``
counts flushes.

Unlike :class:`~.async_fedavg_api.AsyncFedAvgAPI` (per-update mixing, its
own alpha/beta knobs), this class trains each client against the PINNED
global it was dispatched (a by-version params ring), so a run is
bit-reproducible from ``random_seed`` alone — and under full
participation (``client_num_per_round == client_num_in_total``, so the
sync loop's per-round draw equals the fixed cohort) with
``async_buffer_size == cohort``, ``async_max_staleness == 0`` and the
``constant`` policy it is bit-identical to the sync FedAvg loop (every
cycle collects the full cohort at staleness 0 with weight ``n * 1.0``,
drained in the same 0..k-1 client order the sync loop folds).

The cohort is the round-0 population draw and stays fixed for the run,
matching the message-plane servers (async cycles re-dispatch the same
participant pool; there is no per-cycle re-selection).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import numpy as np

from ....core import obs
from ....core.async_fl import UpdateBuffer, VirtualArrivalQueue
from ..fedavg.fedavg_api import FedAvgAPI

logger = logging.getLogger(__name__)


class FedBuffAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        per_round = int(args.client_num_per_round)
        cap = int(getattr(args, "async_buffer_size", 0) or 0) or per_round
        if cap > per_round:
            logger.warning("async_buffer_size=%d exceeds the cohort (%d): "
                           "clamping", cap, per_round)
            cap = per_round
        self.buffer = UpdateBuffer(
            capacity=cap,
            policy=str(getattr(args, "async_staleness_policy", "constant")
                       or "constant"),
            alpha=float(getattr(args, "async_staleness_alpha", 0.5) or 0.5),
            hinge_b=int(getattr(args, "async_hinge_b", 4) or 4),
        )
        self.max_staleness = int(getattr(args, "async_max_staleness", 0) or 0)
        rng = np.random.RandomState(int(getattr(args, "random_seed", 0)))
        # heterogeneous simulated round durations per client (same draw
        # idiom as AsyncFedAvgAPI: reproducible from the seed alone)
        self.durations = 0.5 + rng.exponential(
            1.0, size=int(args.client_num_in_total))

    def train(self) -> Dict[str, Any]:
        total_flushes = int(self.args.comm_round)
        # 0 disables periodic eval (final-flush eval still runs)
        freq = int(getattr(self.args, "frequency_of_the_test", 5)) or (1 << 30)
        cohort = self._client_sampling(0)

        version = 0
        # pinned globals by version: a client trains against the exact model
        # it was dispatched, however stale it is by the time it reports
        params_ring: Dict[int, Any] = {0: self.w_global}
        dispatched_version: Dict[int, int] = {}
        queue = VirtualArrivalQueue()
        for cid in cohort:
            dispatched_version[cid] = 0
            queue.push(cid, float(self.durations[cid]))

        slot = self.client_list[0]
        flushes = 0
        dropped_stale = 0
        last: Dict[str, Any] = {}
        # one root span per cycle (version) so a traced async run keeps the
        # round → phases tree shape trace_report asserts on
        rsp = obs.round_span(version, mode="simulation_sp_async")
        while flushes < total_flushes:
            t, cid = queue.pop()
            v_dispatch = dispatched_version[cid]
            staleness = version - v_dispatch
            if staleness > self.max_staleness:
                # too stale to aggregate: fresh work beats idling
                dropped_stale += 1
                obs.counter_inc("async.dropped_stale")
                dispatched_version[cid] = version
                queue.push(cid, t + float(self.durations[cid]))
                continue
            # deterministic per-cycle RNG stream: the version trained
            # against IS the sync loop's round_idx in the equivalence config
            self.trainer.round_idx = v_dispatch
            slot.update_local_dataset(
                cid,
                self.train_data_local_dict[cid],
                self.test_data_local_dict[cid],
                self.train_data_local_num_dict[cid],
            )
            with obs.span("client.train", rsp.ctx, round_idx=version,
                          client=int(cid), staleness=int(staleness)):
                w = slot.train(params_ring[v_dispatch])
            self.buffer.add(cid, w, float(slot.local_sample_number),
                            version=v_dispatch, staleness=staleness)
            obs.histogram_observe("async.staleness", float(staleness))
            obs.gauge_set("async.buffer_occupancy", float(len(self.buffer)))
            if self.max_staleness >= 1 and not self.buffer.ready():
                # FedBuff: the client keeps training while its delta waits
                dispatched_version[cid] = version
                queue.push(cid, t + float(self.durations[cid]))
            if not self.buffer.ready():
                continue

            entries = self.buffer.drain()
            stats = UpdateBuffer.staleness_stats(entries)
            with obs.span("buffer.flush", rsp.ctx, round_idx=version,
                          n_deltas=len(entries), reason="full",
                          capacity=self.buffer.capacity, **stats):
                self.w_global = self.server_update(self.buffer.weighted(entries))
                self.aggregator.set_model_params(self.w_global)
            obs.counter_inc("async.flushes", labels={"reason": "full"})
            obs.gauge_set("async.buffer_occupancy", 0.0)
            version += 1
            params_ring[version] = self.w_global
            for v in [v for v in params_ring
                      if v < version - self.max_staleness]:
                del params_ring[v]
            self.metrics.log({"flush": flushes, "version": version,
                              "n_deltas": len(entries),
                              "dropped_stale": dropped_stale, **stats})
            # re-dispatch every idle contributor on the fresh global
            in_flight = set(queue.clients())
            for c in cohort:
                if c not in in_flight:
                    dispatched_version[c] = version
                    queue.push(c, t + float(self.durations[c]))
            if flushes % freq == 0 or flushes == total_flushes - 1:
                last = self._test_global(flushes)
            flushes += 1
            rsp.end(reason="flush")
            obs.maybe_export_metrics()
            if flushes < total_flushes:
                rsp = obs.round_span(version, mode="simulation_sp_async")
        return last
