"""Asynchronous FedAvg (reference ``simulation/mpi/async_fedavg``, 1235 LoC).

Event-driven simulation in one process: each client has a simulated epoch
duration (heterogeneous); the server applies every arriving update
immediately with staleness-discounted mixing
``w <- (1-a)*w + a*w_i,  a = alpha / (1 + staleness)^beta`` and re-dispatches
the client with the fresh model.  ``comm_round`` counts applied updates.
"""

from __future__ import annotations

import heapq
import logging
from typing import Any, Dict, List, Tuple

import numpy as np

from ..fedavg.fedavg_api import FedAvgAPI

logger = logging.getLogger(__name__)


class AsyncFedAvgAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model):
        super().__init__(args, device, dataset, model)
        self.alpha = float(getattr(args, "async_alpha", 0.6))
        self.beta = float(getattr(args, "async_beta", 0.5))
        rng = np.random.RandomState(int(getattr(args, "random_seed", 0)))
        # heterogeneous simulated round durations per client
        self.durations = 0.5 + rng.exponential(1.0, size=int(args.client_num_in_total))

    def train(self) -> Dict[str, Any]:
        total_updates = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        n_concurrent = int(self.args.client_num_per_round)
        sampled = list(range(min(n_concurrent, int(self.args.client_num_in_total))))

        # priority queue of (finish_time, seq, client_idx, model_version_at_dispatch)
        events: List[Tuple[float, int, int, int]] = []
        seq = 0
        version = 0
        for cid in sampled:
            heapq.heappush(events, (self.durations[cid], seq, cid, version))
            seq += 1

        slot = self.client_list[0]
        applied = 0
        last: Dict[str, Any] = {}
        while applied < total_updates:
            t, _, cid, v_dispatch = heapq.heappop(events)
            # deterministic per-update RNG stream (same contract as the
            # FedAvgAPI loop's per-round round_idx): without this every
            # update replays client cid's round-0 shuffle/dropout keys
            self.trainer.round_idx = applied
            slot.update_local_dataset(
                cid,
                self.train_data_local_dict[cid],
                self.test_data_local_dict[cid],
                self.train_data_local_num_dict[cid],
            )
            w_i = slot.train(self.w_global)
            staleness = version - v_dispatch
            a = self.alpha / ((1.0 + staleness) ** self.beta)
            import jax

            self.w_global = jax.tree_util.tree_map(
                lambda g, wi: (1.0 - a) * g + a * wi, self.w_global, w_i
            )
            self.w_global = self.aggregator.on_after_aggregation(self.w_global)
            self.aggregator.set_model_params(self.w_global)
            version += 1
            applied += 1
            self.metrics.log({"update": applied, "client": cid, "staleness": staleness, "mix": round(a, 4)})
            heapq.heappush(events, (t + self.durations[cid], seq, cid, version))
            seq += 1
            if applied % freq == 0 or applied == total_updates:
                last = self._test_global(applied)
        return last
