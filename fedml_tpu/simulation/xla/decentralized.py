"""In-mesh decentralized (gossip) FL: the whole serverless round — every
node's local training AND the neighbor mixing — compiles into ONE XLA
program over the ``client`` mesh axis.

The reference runs decentralized FL as per-process actors exchanging
neighbor messages (``simulation/sp/decentralized``, topology managers
``core/distributed/topology/symmetric_topology_manager.py:21-56``).  Here
node models live in a stacked HBM table sharded over the mesh; a round is:

* per-device ``lax.scan`` over its node slots — each node trains ITS OWN
  params on its shard via the shared engine (ml/engine/train.py), so the
  local step math is identical to every other backend;
* the gossip exchange: one ``all_gather`` of the freshly-trained node stack
  along the ``client`` axis (XLA lowers it to a ppermute ring over ICI —
  the physical neighbor exchange), then each device applies its rows of the
  row-normalized mixing matrix as a single matmul.  Works for ANY topology
  the managers emit (ring + Watts-Strogatz rewires), not just the ring;
* consensus (plain node mean, the sp twin's evaluation model) comes out of
  the same program via ``psum``.

Equivalence: with a shared topology seed the mix matrix matches the sp
twin's, per-node keys are the same pure function of (seed, round, node id)
as ModelTrainerCLS (cls_trainer.py:70-72), and the engine masks padding, so
the in-mesh round reproduces sp results exactly when padded shapes agree
(tests/test_xla_decentralized.py).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.distributed.topology.topology_manager import SymmetricTopologyManager
from ...ml.engine.train import build_local_train, init_variables
from ...utils.metrics import MetricsLogger
from .fed_sim import shard_map

logger = logging.getLogger(__name__)


class DecentralizedInMeshAPI:
    _needs_consensus = True  # eval reads the consensus mean; SpreadGNN's
    # personalized eval does not — its rounds skip the full-model psum

    def _mix_leaf(self, path) -> bool:
        """Whether a parameter leaf participates in the gossip mix (called at
        trace time, per leaf path).  SpreadGNN overrides to keep task heads
        node-local."""
        return True

    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ...ml.trainer.trainer_creator import loss_kind_for_dataset
        from .split import _pad_clients

        self.args = args
        (_tn, _ten, _tg, self.test_global, local_num, local_train, _lt,
         self.class_num) = dataset
        self.module = model
        self.n_nodes = int(args.client_num_in_total)
        if mesh is None:
            from ...parallel.mesh import create_fl_mesh

            mesh = create_fl_mesh()
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.bs = int(getattr(args, "batch_size", 32))
        seed = int(getattr(args, "random_seed", 0))

        self.x_all, self.y_all, self.idx, self.counts, self.padded_n = _pad_clients(
            local_train, local_num, self.n_nodes, self.bs
        )

        # topology -> row-normalized mixing matrix, padded to the mesh with
        # identity rows/cols (pad nodes mix only with themselves: inert)
        self.topo = SymmetricTopologyManager(
            self.n_nodes, int(getattr(args, "topology_neighbor_num", 2)), seed=seed
        )
        self.topo.generate_topology()
        self.slots = -(-self.n_nodes // self.n_dev)
        n_pad = self.n_dev * self.slots
        mix = np.eye(n_pad, dtype=np.float32)
        mix[: self.n_nodes, : self.n_nodes] = np.asarray(self.topo.topology, np.float32)
        self.n_pad = n_pad

        # stacked node-model table, every node starting from the same init
        proto = init_variables(model, jnp.asarray(self.x_all[:1], jnp.float32), seed=seed)
        shard = NamedSharding(mesh, P("client"))
        self.table = jax.tree_util.tree_map(
            lambda p: jax.device_put(
                jnp.broadcast_to(p, (n_pad,) + p.shape), shard
            ),
            proto,
        )
        self.consensus = proto
        pad_ids = np.concatenate(
            [np.arange(self.n_nodes), np.zeros(n_pad - self.n_nodes, np.int64)]
        )
        self._idx_rows = jnp.asarray(np.asarray(self.idx)[pad_ids])
        self._counts = jnp.asarray(
            np.where(np.arange(n_pad) < self.n_nodes, np.asarray(self.counts)[pad_ids], 0)
        )
        self._mix = jax.device_put(jnp.asarray(mix), shard)  # rows sharded
        self._real = jnp.asarray((np.arange(n_pad) < self.n_nodes).astype(np.float32))

        loss_kind = loss_kind_for_dataset(str(getattr(args, "dataset", "")).lower())
        local_train_fn = build_local_train(
            model, args, self.bs, self.padded_n, loss=loss_kind
        )
        n_real = self.n_nodes

        def per_device(table_l, x_all, y_all, idx_l, counts_l, rngs_l, mix_l, real_l):
            def one_node(carry, inp):
                lsum, wsum = carry
                node_vars, idx_row, n_i, rng, real = inp
                x = jnp.take(x_all, idx_row, axis=0)
                y = jnp.take(y_all, idx_row, axis=0)
                result = local_train_fn(node_vars, x, y, n_i, rng)
                w = n_i.astype(jnp.float32) * real
                return (lsum + result.loss * w, wsum + w), result.variables

            (lsum, wsum), trained_l = jax.lax.scan(
                one_node, (0.0, 0.0),
                (table_l, idx_l, counts_l, rngs_l, real_l),
            )
            # the gossip exchange, leaf by leaf: gather the trained node
            # stack over ICI, then this device's rows of the mixing matrix
            # in one matmul.  Leaves excluded by _mix_leaf (SpreadGNN's
            # personalized task heads) stay node-local and skip the
            # collective entirely.
            def gossip_leaf(path, t):
                if not self._mix_leaf(path):
                    return t.astype(jnp.float32)  # personalized: never averaged
                g = jax.lax.all_gather(t, "client", tiled=True)
                return jnp.tensordot(
                    mix_l, g.astype(jnp.float32).reshape((g.shape[0], -1)), axes=(1, 0)
                ).reshape((mix_l.shape[0],) + g.shape[1:])

            new_l = jax.tree_util.tree_map_with_path(gossip_leaf, trained_l)
            if self._needs_consensus:
                # consensus = plain mean over REAL nodes (sp eval model)
                cons = jax.tree_util.tree_map(
                    lambda nl: jax.lax.psum(
                        jnp.tensordot(real_l, nl.reshape((nl.shape[0], -1)), axes=(0, 0)),
                        "client",
                    ).reshape(nl.shape[1:]) / n_real,
                    new_l,
                )
            else:
                cons = jnp.float32(0)  # structure-stable placeholder
            lsum = jax.lax.psum(lsum, "client")
            wsum = jax.lax.psum(wsum, "client")
            return new_l, cons, lsum / jnp.maximum(wsum, 1e-9)

        self._round_fn = jax.jit(shard_map(
            per_device, mesh=mesh,
            in_specs=(P("client"), P(), P(), P("client"), P("client"),
                      P("client"), P("client"), P("client")),
            out_specs=(P("client"), P(), P()),
            check_vma=False,
        ))
        from ...ml.aggregator.aggregator_creator import create_server_aggregator

        self.aggregator = create_server_aggregator(model, args)
        self.metrics = MetricsLogger(args)
        self.eval_history: List[Dict[str, Any]] = []
        self._base_key = jax.random.PRNGKey(seed)

    def train(self) -> Dict[str, Any]:
        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            # same pure per-(seed, round, node) key function as the sp
            # trainers (cls_trainer.py:70-72) — exact-equivalence seam
            rk = jax.random.fold_in(self._base_key, round_idx)
            rngs = jax.vmap(lambda i: jax.random.fold_in(rk, i))(
                jnp.arange(self.n_pad)
            )
            self.table, self.consensus, mean_loss = self._round_fn(
                self.table, self.x_all, self.y_all, self._idx_rows,
                self._counts, rngs, self._mix, self._real,
            )
            self.metrics.log({"round": round_idx, "train_loss": float(mean_loss)})
            if freq > 0 and (round_idx % freq == 0 or round_idx == comm_round - 1):
                last = self._test_global(round_idx)
        return last

    def node_params(self, node_id: int):
        """One node's current model (host copy) — test/debug surface."""
        return jax.tree_util.tree_map(lambda t: t[node_id], self.table)

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        self.aggregator.set_model_params(self.consensus)
        stats = self.aggregator.test(self.test_global, None, self.args)
        out = {
            "round": round_idx,
            "test_acc": round(stats["test_correct"] / stats["test_total"], 4),
            "test_loss": round(stats["test_loss"] / stats["test_total"], 4),
        }
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("decentralized in-mesh eval: %s", out)
        return out


class SpreadGNNInMeshAPI(DecentralizedInMeshAPI):
    """SpreadGNN on the mesh (reference ``research/SpreadGNN`` serverless
    decentralized multi-task periodic averaging): the same compiled gossip
    round, but task-head leaves (``mtl_local_head_names``, default
    'readout') are EXCLUDED from the mix — they never enter the all_gather
    and stay node-personalized, the paper's defining property.  Eval is the
    per-node mean with each node's own head (sp twin
    ``sp/spreadgnn/spreadgnn_api.py``)."""

    _needs_consensus = False  # personalized eval never reads a consensus

    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ..sp.spreadgnn.spreadgnn_api import head_names_from

        self.head_names = head_names_from(args)
        super().__init__(args, device, dataset, model, mesh=mesh)

    def _mix_leaf(self, path) -> bool:
        from ..sp.spreadgnn.spreadgnn_api import _is_local_head

        return not _is_local_head(path, self.head_names)

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        """Personalized eval: mean over nodes, each with its own head."""
        corr = loss = tot = 0.0
        for nid in range(self.n_nodes):
            self.aggregator.set_model_params(self.node_params(nid))
            stats = self.aggregator.test(self.test_global, None, self.args)
            corr += stats["test_correct"]
            loss += stats["test_loss"]
            tot += stats["test_total"]
        out = {
            "round": round_idx,
            "test_acc": round(corr / max(tot, 1.0), 4),
            "test_loss": round(loss / max(tot, 1.0), 4),
        }
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("spreadgnn in-mesh eval (per-node mean): %s", out)
        return out
