"""In-mesh algorithm strategies for the Parrot-XLA simulator.

The reference ships one MPI directory per algorithm, each re-implementing the
round loop around different server math (``simulation/mpi/{fedopt,fednova,
async_fedavg,...}`` — SURVEY.md §2.5).  Here an algorithm is a STRATEGY
traced into the one compiled round program of
:class:`~fedml_tpu.simulation.xla.fed_sim.XLASimulator`:

* a per-step gradient hook (SCAFFOLD/FedDyn drift correction) compiled into
  the local-SGD scan;
* a per-client contribution pytree, weighted-summed on device and reduced
  with one ``psum`` over the client axis (rides ICI);
* a per-client output (new control variates) returned sharded and scattered
  into an HBM-resident client-state table;
* a server update applied to the psum'd aggregate INSIDE the same XLA
  program — FedOpt's adaptive server step, FedNova's normalized averaging,
  FedDyn's dynamic regularizer all cost zero extra host round-trips.

Each strategy's math mirrors its single-process twin in ``simulation/sp/``
(the equivalence is tested in tests/test_xla_zoo.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


def _weighted_avg(acc: Pytree, wsum: jnp.ndarray, like: Pytree) -> Pytree:
    """acc is the fp32 weighted SUM of client trees; divide and restore dtype."""
    return jax.tree_util.tree_map(
        lambda a, v: (a / jnp.maximum(wsum, 1e-9)).astype(v.dtype), acc, like
    )


class InMeshAlgorithm:
    """FedAvg — also the base contract every in-mesh strategy implements.

    Host-side methods (``init_*``, ``gather_client_extras``,
    ``apply_client_outs``, ``host_round_end``) run in Python between rounds;
    everything else is traced into the compiled round and must be jax-pure.
    """

    needs_client_state = False
    # True when server_update consumes the weighted variables sum ``acc`` —
    # the hook point where the security layer (stacked attack / robust
    # aggregation, fed_sim._build_security_fn) substitutes its own aggregate.
    # Strategies that aggregate through ``ext`` instead (FedNova, async)
    # bypass that substitution and cannot be attacked/defended in-mesh.
    aggregates_via_acc = True

    def __init__(self, args):
        self.args = args

    # -- traced: engine plumbing ------------------------------------------
    def grad_hook(self):
        """Per-step hook for ml.engine.build_local_train (None = plain SGD)."""
        return None

    def engine_extra(self, cex: Pytree, server_state: Pytree) -> Pytree:
        """The ``extra`` handed to the engine's grad hook for one client."""
        return None

    # -- traced: per-client reduction -------------------------------------
    def zero_contrib(self, variables: Pytree) -> Pytree:
        return jnp.zeros(())

    def client_contrib(self, variables, result, w, real, cex, server_state) -> Pytree:
        """Extra per-client contribution, accumulated by plain tree-sum then
        psum'd (the weighted variables sum is always accumulated by the
        simulator itself)."""
        return jnp.zeros(())

    def client_out(self, variables, result, real, cex, server_state) -> Pytree:
        """Per-client output, returned stacked over the client axis (e.g. a
        control-variate delta to scatter back into the client-state table)."""
        return jnp.zeros(())

    def out_template(self, variables) -> Pytree:
        """Shape template for one client's client_out (the packed round
        pre-allocates its per-slot output buffer from this)."""
        return jnp.zeros(())

    # -- traced: server step ----------------------------------------------
    def server_update(self, acc, wsum, ext, variables, server_state) -> Tuple[Pytree, Pytree]:
        return _weighted_avg(acc, wsum, variables), server_state

    # -- traced: security tail (fed_sim._build_security_fn) ----------------
    def ext_from_rows(self, mat, w, w_orig, meta, g_vec, unravel) -> Pytree:
        """Recompute this strategy's psum'd ``ext`` from the security tail's
        (possibly attacked/defended) per-client row space — the substitute
        for the in-round ``client_contrib`` accumulation when the round's
        updates were re-written by a stacked attack or robust aggregation.

        ``mat``: [n, D] defended client rows (``ravel_pytree`` order);
        ``w``: [n] defended weights (selection defenses zero rows here);
        ``w_orig``: [n] the round's real sample weights; ``meta``: [n] the
        strategy's ``security_meta`` vector; ``g_vec``/``unravel``: the
        ravelled fp32 global.  Only strategies with ``aggregates_via_acc``
        False need this (acc strategies take the substituted weighted sum).
        """
        raise NotImplementedError(
            f"{type(self).__name__} aggregates through ext "
            "(aggregates_via_acc=False) and must implement ext_from_rows "
            "to compose with in-mesh attacks/defenses"
        )

    def security_meta(self, taus, cex, real_sel) -> jnp.ndarray:
        """[n_real] per-client metadata for ``ext_from_rows``: sliced from
        the round's captured engine step counts (``taus``, aligned with the
        schedule slots) and the round's client extras (``cex``)."""
        return jnp.zeros((len(real_sel),), jnp.float32)

    # -- host side ---------------------------------------------------------
    def init_server_state(self, variables: Pytree) -> Pytree:
        return ()

    def init_client_state(self, num_clients: int, variables: Pytree) -> Optional[Pytree]:
        return None

    def gather_client_extras(self, client_state, ids: np.ndarray, real: np.ndarray,
                             round_idx: int) -> Pytree:
        """Per-round per-client inputs, leading axis = len(ids), sharded over
        the client mesh axis."""
        if client_state is None:
            return jnp.zeros((len(ids),), jnp.float32)
        return jax.tree_util.tree_map(lambda t: t[jnp.asarray(ids)], client_state)

    def apply_client_outs(self, client_state, ids: np.ndarray, outs: Pytree):
        """Fold the round's stacked client outputs back into the state table.
        Outputs are DELTAS masked to zero for padded slots, so a scatter-add
        is safe even when the padding repeats a real client id."""
        if client_state is None:
            return None
        idx = jnp.asarray(ids)
        return jax.tree_util.tree_map(lambda t, o: t.at[idx].add(o), client_state, outs)

    def host_round_end(self, ids: np.ndarray, real: np.ndarray, round_idx: int) -> None:
        pass

    def host_state(self) -> Dict[str, Any]:
        """Host-side mutable state for checkpointing (msgpack-serializable)."""
        return {}

    def restore_host_state(self, state: Dict[str, Any]) -> None:
        pass


class FedAvgInMesh(InMeshAlgorithm):
    """Weighted averaging; FedProx rides this unchanged (the engine installs
    the proximal grad hook from ``args.proximal_mu`` — sp/fedprox parity)."""


class FedOptInMesh(InMeshAlgorithm):
    """Server-side adaptive optimization (Reddi et al.) — sp/fedopt twin:
    the weighted-average delta is a pseudo-gradient for an optax server
    optimizer whose state is replicated mesh-wide and carried round to round."""

    def __init__(self, args):
        super().__init__(args)
        from ..sp.fedopt.fedopt_api import make_server_optimizer

        self._tx = make_server_optimizer(args)

    def init_server_state(self, variables):
        return self._tx.init(variables["params"])

    def server_update(self, acc, wsum, ext, variables, server_state):
        import optax

        avg = _weighted_avg(acc, wsum, variables)
        pseudo_grad = jax.tree_util.tree_map(
            lambda p, a: p - a, variables["params"], avg["params"]
        )
        updates, new_state = self._tx.update(pseudo_grad, server_state, variables["params"])
        params = optax.apply_updates(variables["params"], updates)
        return dict(avg, params=params), new_state


class FedNovaInMesh(InMeshAlgorithm):
    """Normalized averaging (Wang et al.) — sp/fednova twin:
    w <- w - tau_eff * sum_i p_i d_i with d_i = (w - w_i)/tau_i,
    tau_eff = sum_i p_i tau_i, p_i = n_i / sum n.  tau_i is the engine's
    masked step count (LocalTrainResult.steps)."""

    aggregates_via_acc = False

    def zero_contrib(self, variables):
        return {
            "d": jax.tree_util.tree_map(
                lambda v: jnp.zeros_like(v, jnp.float32), variables
            ),
            "tau": jnp.zeros(()),
        }

    def client_contrib(self, variables, result, w, real, cex, server_state):
        tau = jnp.maximum(result.steps, 1.0)
        d_i = jax.tree_util.tree_map(
            lambda g, wi: (g.astype(jnp.float32) - wi.astype(jnp.float32)) / tau,
            variables, result.variables,
        )
        return {
            "d": jax.tree_util.tree_map(lambda x: w * x, d_i),
            "tau": w * result.steps,
        }

    def server_update(self, acc, wsum, ext, variables, server_state):
        denom = jnp.maximum(wsum, 1e-9)
        tau_eff = ext["tau"] / denom
        new = jax.tree_util.tree_map(
            lambda g, d: (g.astype(jnp.float32) - tau_eff * d / denom).astype(g.dtype),
            variables, ext["d"],
        )
        return new, server_state

    def security_meta(self, taus, cex, real_sel):
        # tau_i = the engine's captured per-client step count, exact by
        # construction (no host re-derivation of masked-step semantics)
        return taus[real_sel]

    def ext_from_rows(self, mat, w, w_orig, meta, g_vec, unravel):
        # client_contrib restated over rows: d = sum_i (w_i/tau_i)(g - m_i),
        # tau = sum_i w_i tau_i — with the DEFENDED weights, so selection
        # defenses drop a client from both the direction and tau_eff (the sp
        # FedNovaAPI.server_update composition: taus follow the surviving
        # updates through the defense filter)
        coef = w / jnp.maximum(meta, 1.0)
        d_vec = jnp.sum(coef) * g_vec - coef @ mat
        return {"d": unravel(d_vec), "tau": jnp.sum(w * meta)}


class ScaffoldInMesh(InMeshAlgorithm):
    """Stochastic controlled averaging (Karimireddy et al.) — sp/scaffold
    twin.  Per-client control variates c_i live in an HBM table sharded over
    rounds by gather/scatter-add; the server control c is replicated state.
    Local steps use g - c_i + c; after K steps
    c_i+ = c_i - c + (w - w_i)/(K lr) and c += (1/N) sum_i (c_i+ - c_i)."""

    needs_client_state = True

    def __init__(self, args):
        super().__init__(args)
        # c_i+ = c_i - c + (w - w_i)/(K lr) assumes each local step is exactly
        # p -= lr*g; with momentum/Adam the relation (and hence the control
        # variates) would silently be wrong.
        opt = str(getattr(args, "client_optimizer", "sgd")).lower()
        momentum = float(getattr(args, "momentum", 0.0) or 0.0)
        if opt != "sgd" or momentum > 0:
            raise NotImplementedError(
                "in-mesh SCAFFOLD requires client_optimizer='sgd' with zero "
                f"momentum (got {opt!r}, momentum={momentum})"
            )
        self.lr = float(getattr(args, "learning_rate", 0.01))
        self.n_total = float(args.client_num_in_total)

    def grad_hook(self):
        def hook(grads, params, anchor, extra):
            c_i, c = extra
            return jax.tree_util.tree_map(
                lambda g, ci, cg: g - ci + cg, grads, c_i, c
            )

        return hook

    def engine_extra(self, cex, server_state):
        return (cex, server_state)

    def init_server_state(self, variables):
        return jax.tree_util.tree_map(
            lambda v: jnp.zeros_like(v, jnp.float32), variables["params"]
        )

    def init_client_state(self, num_clients, variables):
        return jax.tree_util.tree_map(
            lambda v: jnp.zeros((num_clients,) + v.shape, jnp.float32),
            variables["params"],
        )

    def _dc(self, variables, result, real, cex, c):
        K = jnp.maximum(result.steps, 1.0)
        new_ci = jax.tree_util.tree_map(
            lambda ci, cg, wg, wi: ci - cg + (wg.astype(jnp.float32) - wi.astype(jnp.float32)) / (K * self.lr),
            cex, c, variables["params"], result.variables["params"],
        )
        return jax.tree_util.tree_map(lambda n, o: real * (n - o), new_ci, cex)

    def zero_contrib(self, variables):
        return self.init_server_state(variables)

    def out_template(self, variables):
        return self.init_server_state(variables)

    def client_contrib(self, variables, result, w, real, cex, server_state):
        return self._dc(variables, result, real, cex, server_state)

    def client_out(self, variables, result, real, cex, server_state):
        return self._dc(variables, result, real, cex, server_state)

    def server_update(self, acc, wsum, ext, variables, server_state):
        new_c = jax.tree_util.tree_map(
            lambda c, d: c + d / self.n_total, server_state, ext
        )
        return _weighted_avg(acc, wsum, variables), new_c


class FedDynInMesh(InMeshAlgorithm):
    """Dynamic regularization (Acar et al.) — sp/feddyn twin.  Per-client
    h_i table + replicated running mean h; local grads use
    g - h_i + alpha (w - w_t); h_i+ = h_i - alpha (w_i - w_t);
    h <- h + (1/N) sum_i (h_i+ - h_i); w <- avg - h/alpha."""

    needs_client_state = True

    def __init__(self, args):
        super().__init__(args)
        self.alpha = float(getattr(args, "feddyn_alpha", 0.01))
        self.n_total = float(args.client_num_in_total)

    def grad_hook(self):
        alpha = self.alpha

        def hook(grads, params, anchor, extra):
            return jax.tree_util.tree_map(
                lambda g, h, p, a: g - h + alpha * (p - a), grads, extra, params, anchor
            )

        return hook

    def engine_extra(self, cex, server_state):
        return cex

    def init_server_state(self, variables):
        return jax.tree_util.tree_map(
            lambda v: jnp.zeros_like(v, jnp.float32), variables["params"]
        )

    def init_client_state(self, num_clients, variables):
        return jax.tree_util.tree_map(
            lambda v: jnp.zeros((num_clients,) + v.shape, jnp.float32),
            variables["params"],
        )

    def _dh(self, variables, result, real):
        return jax.tree_util.tree_map(
            lambda wi, wg: -self.alpha * real * (wi.astype(jnp.float32) - wg.astype(jnp.float32)),
            result.variables["params"], variables["params"],
        )

    def zero_contrib(self, variables):
        return self.init_server_state(variables)

    def out_template(self, variables):
        return self.init_server_state(variables)

    def client_contrib(self, variables, result, w, real, cex, server_state):
        return self._dh(variables, result, real)

    def client_out(self, variables, result, real, cex, server_state):
        return self._dh(variables, result, real)

    def server_update(self, acc, wsum, ext, variables, server_state):
        avg = _weighted_avg(acc, wsum, variables)
        new_h = jax.tree_util.tree_map(
            lambda h, d: h + d / self.n_total, server_state, ext
        )
        params = jax.tree_util.tree_map(
            lambda p, h: (p.astype(jnp.float32) - h / self.alpha).astype(p.dtype),
            avg["params"], new_h,
        )
        return dict(avg, params=params), new_h


class AsyncFedAvgInMesh(InMeshAlgorithm):
    """Buffered asynchronous FedAvg (FedBuff-style, Nguyen et al.
    arXiv:2106.06639) — the in-mesh counterpart of sp/async_fedavg's
    event-driven loop.  Each round is one buffer flush: the sampled clients'
    deltas are mixed with staleness-discounted weights
    a_i = alpha / (1 + tau_i)^beta where tau_i = rounds since client i last
    participated, and w <- w + (1/K) sum_i a_i (w_i - w).  Unlike the
    event-driven sp path, clients train from the current model (the
    discounting models staleness; the stale-weights effect is not simulated)."""

    aggregates_via_acc = False

    def __init__(self, args):
        super().__init__(args)
        self.alpha = float(getattr(args, "async_alpha", 0.6))
        self.beta = float(getattr(args, "async_beta", 0.5))
        self._last_round: Dict[int, int] = {}

    def gather_client_extras(self, client_state, ids, real, round_idx):
        stale = np.array(
            [round_idx - self._last_round.get(int(c), round_idx) for c in ids],
            np.float32,
        )
        return jnp.asarray(stale)

    def host_round_end(self, ids, real, round_idx):
        for c, r in zip(ids, real):
            if r > 0:
                self._last_round[int(c)] = round_idx

    def host_state(self):
        return {"last_round": {str(k): v for k, v in self._last_round.items()}}

    def restore_host_state(self, state):
        self._last_round = {int(k): int(v) for k, v in state.get("last_round", {}).items()}

    def zero_contrib(self, variables):
        return {
            "d": jax.tree_util.tree_map(
                lambda v: jnp.zeros_like(v, jnp.float32), variables
            ),
            "k": jnp.zeros(()),
        }

    def client_contrib(self, variables, result, w, real, cex, server_state):
        a_i = self.alpha / (1.0 + cex) ** self.beta
        return {
            "d": jax.tree_util.tree_map(
                lambda wi, wg: a_i * real * (wi.astype(jnp.float32) - wg.astype(jnp.float32)),
                result.variables, variables,
            ),
            "k": real,
        }

    def server_update(self, acc, wsum, ext, variables, server_state):
        k = jnp.maximum(ext["k"], 1.0)
        new = jax.tree_util.tree_map(
            lambda g, d: (g.astype(jnp.float32) + d / k).astype(g.dtype),
            variables, ext["d"],
        )
        return new, server_state

    def security_meta(self, taus, cex, real_sel):
        # staleness, already gathered per slot by gather_client_extras
        return cex[real_sel]

    def ext_from_rows(self, mat, w, w_orig, meta, g_vec, unravel):
        # client_contrib ignores sample weights (each arrival mixes with its
        # own staleness discount a_i), so the defense's effect enters as the
        # RELATIVE weight factor r_i = w_i/w_orig_i: 1 for row transforms,
        # 0/1 for selection defenses (krum/3sigma) — exactly the surviving-
        # subset semantics of the sp before-aggregation composition
        r = w / jnp.maximum(w_orig, 1e-9)
        a_i = r * self.alpha / (1.0 + meta) ** self.beta
        d_vec = a_i @ mat - jnp.sum(a_i) * g_vec
        return {"d": unravel(d_vec), "k": jnp.sum(r)}


class FedBuffInMesh(InMeshAlgorithm):
    """Buffered-async FedBuff flush (``fl_mode=async``) — the in-mesh twin
    of ``sp/async_fedavg/fedbuff_api.py`` and the message-plane servers'
    ``core/async_fl`` flush: each compiled round aggregates ONE buffer's
    worth of arrivals with weights ``n_i * staleness_weight(policy, s_i)``
    and the staleness values come from the simulator's host-side virtual
    arrival queue (``fed_sim`` drives ``set_staleness`` before each round).
    Like :class:`AsyncFedAvgInMesh`, clients train from the CURRENT global
    (the discount models staleness; the stale-weights effect is not
    simulated in-mesh — the sp FedBuffAPI pins per-version globals when
    that effect matters).  With ``async_max_staleness == 0`` every arrival
    has staleness 0, so the approximation is exact there."""

    aggregates_via_acc = False

    def __init__(self, args):
        super().__init__(args)
        from ...core.async_fl.staleness import _check_policy

        self.policy = str(getattr(args, "async_staleness_policy", "constant")
                          or "constant")
        _check_policy(self.policy)
        self.s_alpha = float(getattr(args, "async_staleness_alpha", 0.5) or 0.5)
        self.hinge_b = int(getattr(args, "async_hinge_b", 4) or 4)
        self._staleness: Dict[int, float] = {}

    def set_staleness(self, mapping: Dict[int, float]) -> None:
        """Host driver hook: this flush's per-client staleness (flushes the
        delta missed; clients absent from the map get 0)."""
        self._staleness = {int(k): float(v) for k, v in mapping.items()}

    def gather_client_extras(self, client_state, ids, real, round_idx):
        return jnp.asarray(
            [self._staleness.get(int(c), 0.0) for c in ids], jnp.float32)

    def _weight(self, w, cex):
        from ...core.async_fl.staleness import staleness_weights

        return w * staleness_weights(
            self.policy, cex, alpha=self.s_alpha, hinge_b=self.hinge_b)

    def zero_contrib(self, variables):
        return {
            "num": jax.tree_util.tree_map(
                lambda v: jnp.zeros_like(v, jnp.float32), variables
            ),
            "den": jnp.zeros(()),
        }

    def client_contrib(self, variables, result, w, real, cex, server_state):
        wi = self._weight(w, cex) * real
        return {
            "num": jax.tree_util.tree_map(
                lambda p: wi * p.astype(jnp.float32), result.variables
            ),
            "den": wi,
        }

    def server_update(self, acc, wsum, ext, variables, server_state):
        den = jnp.maximum(ext["den"], 1e-9)
        new = jax.tree_util.tree_map(
            lambda g, nm: (nm / den).astype(g.dtype), variables, ext["num"]
        )
        return new, server_state

    def security_meta(self, taus, cex, real_sel):
        # staleness, already gathered per slot by gather_client_extras
        return cex[real_sel]

    def ext_from_rows(self, mat, w, w_orig, meta, g_vec, unravel):
        # the defended weights already carry the sample counts (selection
        # defenses zero dropped rows); apply the staleness discount on top —
        # the sp composition: defenses filter, then the buffer weights
        wi = self._weight(w, meta)
        return {"num": unravel(wi @ mat), "den": jnp.sum(wi)}

    def host_state(self):
        return {"staleness": {str(k): v for k, v in self._staleness.items()}}

    def restore_host_state(self, state):
        self._staleness = {
            int(k): float(v) for k, v in state.get("staleness", {}).items()}


_REGISTRY = {
    "fedavg": FedAvgInMesh,
    "fedprox": FedAvgInMesh,  # engine grad hook from args.proximal_mu
    "fedsgd": FedAvgInMesh,  # E=1, full batch — configured via args
    # FedSeg IS FedAvg round-wise (reference simulation/mpi/fedseg); the seg
    # task head (per-pixel ce + mIoU eval) comes from the dataset family
    "fedseg": FedAvgInMesh,
    "fedopt": FedOptInMesh,
    "fednova": FedNovaInMesh,
    "scaffold": ScaffoldInMesh,
    "feddyn": FedDynInMesh,
    "async_fedavg": AsyncFedAvgInMesh,
}


def create_inmesh_algorithm(args) -> InMeshAlgorithm:
    opt = str(getattr(args, "federated_optimizer", "FedAvg")).lower()
    if str(getattr(args, "fl_mode", "sync") or "sync").lower() == "async":
        # buffered-async execution replaces the round loop (fed_sim's
        # virtual-arrival driver); only FedAvg aggregation has an async twin
        if opt != "fedavg":
            raise ValueError(
                f"fl_mode=async supports federated_optimizer 'fedavg' only "
                f"in the XLA simulator (got {opt!r})")
        return FedBuffInMesh(args)
    cls = _REGISTRY.get(opt)
    if cls is None:
        raise NotImplementedError(
            f"federated_optimizer {opt!r} has no in-mesh strategy; use the 'sp' "
            "backend (its host round loop supports the full zoo)"
        )
    return cls(args)
