"""Parrot-XLA: the in-mesh federated-learning simulator (north-star component).

TPU-native successor of the reference's NCCL simulator
(``simulation/nccl/base_framework/``): there, rank-0 Server broadcasts the
global model over torch.distributed, per-GPU LocalAggregators sequentially
simulate their scheduled clients (``LocalAggregator.py:69-124``) and reduce
into the server (``common.py:196-210``).  Here the whole round collapses into
ONE compiled XLA program over a ``Mesh``:

* broadcast  -> implicit replication of the global variables;
* per-GPU LocalAggregator loop -> per-device ``lax.scan`` over the clients
  assigned to that mesh slot (client axis sharded with shard_map);
* local SGD epochs -> nested compiled scan (ml/engine/train.build_local_train);
* ``fedml_nccl_reduce`` -> weighted on-device accumulation + ``lax.psum``
  over the 'client' axis riding ICI;
* the Server/LocalAggregator role split disappears: no host round-trips
  inside a round, weights never leave HBM.

Client heterogeneity under static shapes: all clients pad to one bucket
(max client size rounded up); padded samples are masked from loss/updates;
rounds whose sampled-client count doesn't fill devices evenly pad with
weight-0 dummy clients.  Static greedy balancing of clients->devices by
sample count (core/schedule) minimizes the padding waste.

The algorithm zoo rides this same compiled round via in-mesh strategies
(algorithms.py): FedAvg/FedProx/FedSGD/FedOpt/FedNova/SCAFFOLD/FedDyn/
buffered-async all compile to ONE XLA program — per-step grad hooks, extra
per-client contributions psum'd alongside the weighted model sum, control
variates in HBM client-state tables, and the server step traced after the
psum (reference ``simulation/mpi/*`` parity, SURVEY.md §2.5).
"""

from __future__ import annotations

import logging
import os
import time
import warnings
from functools import partial
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.7 (check_vma kwarg)
except ImportError:  # pragma: no cover - legacy jax uses check_rep instead
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )

from ...core import obs
from ...core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
from ...core.schedule import RuntimeEstimator, SeqTrainScheduler
from ...core.security.fedml_attacker import FedMLAttacker
from ...core.security.fedml_defender import FedMLDefender
from ...ml.engine.train import build_local_train, init_variables
from ...parallel.mesh import create_fl_mesh, create_round_mesh
from ...utils.metrics import MetricsLogger
from .algorithms import create_inmesh_algorithm

logger = logging.getLogger(__name__)


class XLASimulator:
    def __init__(self, args, dataset, model, mesh: Mesh = None):
        self.args = args
        (
            self.train_num,
            self.test_num,
            self.train_global,
            self.test_global,
            self.local_num_dict,
            self.local_train_dict,
            _local_test_dict,
            self.class_num,
        ) = dataset
        self.module = model
        self.mesh = mesh if mesh is not None else create_fl_mesh()
        self.n_dev = self.mesh.devices.size

        self.num_clients = int(args.client_num_in_total)
        self.clients_per_round = int(args.client_num_per_round)
        self.batch_size = int(getattr(args, "batch_size", 32))

        # Security layer: both rounds can return the per-client update stack
        # (sharded over the client axis); a second jitted program then runs
        # stacked model attacks + robust aggregation + the algorithm's server
        # step on it (core/security/stacked.py) — updates never touch the
        # host, which also keeps the path multi-host safe (P('client') leaves
        # are not fully addressable under jax.distributed).  Data-poisoning
        # attacks stamp at pack time, where each client's shard is assembled.
        attacker = FedMLAttacker.get_instance()
        defender = FedMLDefender.get_instance()
        dp = FedMLDifferentialPrivacy.get_instance()
        self.defended = defender.is_defense_enabled()
        self.model_attacked = attacker.is_model_attack()
        # analysis-primitive attacks (dlg / invert_gradient / revealing
        # labels) read ONE intercepted per-client update off the round's
        # sharded stack — reference fedml_attacker.py:28-30 runs the whole
        # matrix through one simulator path; so does this backend now
        self.analysis_attacked = attacker.is_analysis_attack()
        if (attacker.is_attack_enabled() and not self.model_attacked
                and not self.analysis_attacked
                and not attacker.is_data_poisoning_attack()):
            # fail loud rather than report clean-FedAvg metrics as an
            # attack-experiment result
            raise NotImplementedError(
                f"attack_type {attacker.attack_type!r} has no XLA-backend hook"
            )
        self.needs_stack = (self.defended or self.model_attacked
                            or self.analysis_attacked)
        # every engine loss family runs in-mesh: the loss key is plumbed
        # into the compiled round and eval goes through the task-aware
        # aggregator.  Tag prediction's int->multi-hot conversion happens
        # host-side at pack time (_pack_data), so it rides the bce loss.
        from ...ml.trainer.trainer_creator import _TAG_DATASETS, loss_kind_for_dataset

        ds = str(getattr(args, "dataset", "")).lower()
        self._multihot_labels = ds in _TAG_DATASETS
        self.loss_kind = "bce" if self._multihot_labels else loss_kind_for_dataset(ds)

        self._pack_data()
        sample = jnp.asarray(self.train_global[0][:1])
        self.variables = init_variables(model, sample, seed=int(getattr(args, "random_seed", 0)))
        self.algo = create_inmesh_algorithm(args)
        self.server_state = self.algo.init_server_state(self.variables)
        self.client_state = self.algo.init_client_state(self.num_clients, self.variables)
        self.agg_plane = str(getattr(args, "agg_plane", "host") or "host")
        if self.agg_plane not in ("host", "compiled"):
            raise ValueError(
                f"agg_plane must be host|compiled (got {self.agg_plane!r})")
        from ...core.aggregate import server_state_mode

        self.sharded_state = server_state_mode(args) == "sharded"
        self._model_bytes = int(sum(
            l.size * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(self.variables)))
        self.packed = bool(getattr(args, "xla_pack", False))
        # sharded_state composes with BOTH the packed streamer and the
        # security tail now: each of those programs ends at the psum'd
        # accumulator and the model-sharded GSPMD tail applies the server
        # step — defended + model-sharded rounds run, they don't degrade
        if self.packed:
            self._build_packed_round_fn()
        else:
            self._build_round_fn()
        if self.needs_stack:
            self._build_security_fn()
        if self.sharded_state:
            self._build_server_tail()

        self.runtime_estimator = RuntimeEstimator(self.n_dev, uniform_devices=True)
        self.scheduler = SeqTrainScheduler(self.n_dev, estimator=self.runtime_estimator)
        # population subsystem: fleet registry + selection policy; the
        # uniform policy is bit-identical to the legacy client_sampling
        # schedule (mt19937), so default configs are unchanged
        from ...core.population import PopulationManager, stacked_cohorts

        try:
            samples = [int(self.local_num_dict[i]) for i in range(self.num_clients)]
        except (KeyError, IndexError, TypeError):
            samples = None
        self.population = PopulationManager.from_args(
            self.args, np.arange(self.num_clients), num_samples=samples,
            rng_style="mt19937",
        )
        # opt-in Parrot-scale path: the whole run's cohorts in ONE vectorized
        # draw (10^5-10^6 virtual clients with no per-round host choice) —
        # a different schedule from the per-round seeded draw, hence gated
        self._stacked_schedule = None
        if bool(getattr(args, "population_stacked", False)):
            self._stacked_schedule = stacked_cohorts(
                self.num_clients, self.clients_per_round,
                int(getattr(args, "comm_round", 1)),
                seed=int(getattr(args, "random_seed", 0)),
            )
        # buffered-async execution (fl_mode=async): a host-side virtual
        # arrival queue decides each flush's cohort + staleness; the
        # FedBuffInMesh strategy turns them into discounted weights in-mesh
        self.async_mode = str(
            getattr(args, "fl_mode", "sync") or "sync").lower() == "async"
        if self.async_mode:
            self._async_init()
        from ...ml.aggregator.aggregator_creator import create_server_aggregator

        self.aggregator = create_server_aggregator(model, args)
        self.metrics = MetricsLogger(args)
        self.round_times: List[float] = []
        self.samples_per_round: List[int] = []
        self.samples_trained = 0
        self._rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 11)

    # ------------------------------------------------------------------
    # data packing: one global HBM-resident array + per-client index table
    # ------------------------------------------------------------------
    def _pack_data(self):
        """Concatenate client shards into one HBM-resident array pair and
        record each client's contiguous row range in an index table — so a
        round's client data is a pure on-device gather (no host transfers)."""
        b = self.batch_size
        counts = np.array([self.local_num_dict[i] for i in range(self.num_clients)], np.int32)
        self.max_client_n = int(counts.max())
        self.padded_n = max(b, -(-self.max_client_n // b) * b)
        xs, ys = [], []
        idx = np.zeros((self.num_clients, self.padded_n), np.int32)
        cursor = 0
        attacker = FedMLAttacker.get_instance()
        poisoning = attacker.is_data_poisoning_attack()
        for i in range(self.num_clients):
            xi, yi = self.local_train_dict[i]
            if poisoning:
                # data side of the attack matrix stamps HERE, where each
                # malicious client's shard is assembled (the XLA round then
                # trains on poisoned HBM rows with zero extra hooks) —
                # reference fedml_attacker.poison_data called per client
                xi, yi = attacker.poison_local_data(i, self.num_clients, xi, yi)
                xi, yi = np.asarray(xi), np.asarray(yi)
            if self._multihot_labels and np.asarray(yi).ndim == 1:
                # tag prediction with int class ids: one-hot for the bce
                # loss (mounted multi-label sets already arrive multi-hot)
                yi = np.eye(self.class_num, dtype=np.float32)[np.asarray(yi)]
            n = len(yi)
            xs.append(np.asarray(xi))
            ys.append(np.asarray(yi))
            if n > 0:
                idx[i, :n] = np.arange(cursor, cursor + n, dtype=np.int32)
                idx[i, n:] = cursor  # padding rows (masked out by counts)
            cursor += n
        self._client_rows = idx  # host copy (packed-round schedule builder)
        self.client_idx = jnp.asarray(idx)
        self.client_counts = jnp.asarray(counts)
        from ...models.hub import data_storage_dtype

        # bf16 storage halves the per-step gather traffic (the measured #1
        # round cost) whenever the model casts its input to bf16 anyway —
        # the gathered batch is then bitwise-identical to the fp32 path.
        # Only FLOAT data participates: integer inputs are token/class ids
        # (transformer Embed requires integers) and keep their dtype.
        x_np = np.concatenate(xs, 0)
        if np.issubdtype(x_np.dtype, np.floating):
            self.x_all = jnp.asarray(x_np, dtype=data_storage_dtype(self.args, self.module))
        else:
            self.x_all = jnp.asarray(x_np)
        self.y_all = jnp.asarray(np.concatenate(ys, 0))
        logger.info(
            "packed %d clients (max_n=%d padded_n=%d) data %s (%s) into HBM",
            self.num_clients, self.max_client_n, self.padded_n, self.x_all.shape,
            self.x_all.dtype,
        )

    # ------------------------------------------------------------------
    # the compiled round
    # ------------------------------------------------------------------
    def _resolve_chunk(self, per_dev: int) -> int:
        """Clients vmapped together per scan step (effective batch k*B, scan
        runs per_dev/k steps).  Default is 1: measured on TPU v5e with the
        bench model (ResNet-56/CIFAR, batch 64), vmapping clients did NOT
        help — per-step time grew linearly with k (the ops are bandwidth/
        lane-padding bound, not launch-bound), and fp32 chunk=8 was 1.6x
        SLOWER than unchunked.  The knob stays for models where per-step cost
        is launch-dominated (tiny dense models).  Must divide per_dev."""
        req = int(getattr(self.args, "xla_client_chunk", 0) or 0)
        if req <= 0:
            return 1
        k = max(d for d in range(1, min(req, per_dev) + 1) if per_dev % d == 0)
        if k != req:
            logger.warning(
                "xla_client_chunk=%d does not divide clients/device=%d; using %d",
                req, per_dev, k,
            )
        return k

    def _ldp_hook(self):
        """Pure per-client noise fn when local DP is enabled (the mechanism's
        add_noise is jax-traceable), else None."""
        dp = FedMLDifferentialPrivacy.get_instance()
        if not dp.is_local_dp_enabled():
            return None
        mechanism = dp.mechanism
        return lambda tree, key: mechanism.add_noise(tree, key)

    def _build_round_fn(self):
        mesh = self.mesh
        algo = self.algo
        stacked = self.needs_stack
        sharded = self.sharded_state
        post_train = self._ldp_hook()
        local_train = build_local_train(
            self.module, self.args, self.batch_size, self.padded_n,
            grad_hook=algo.grad_hook(), loss=self.loss_kind,
        )

        def per_device(variables, server_state, x_all, y_all, idx_l, counts_l, rngs_l, cex_l):
            # idx_l: [C/n_dev, padded_n]; counts_l: [C/n_dev]; rngs_l: [C/n_dev, 2]
            # cex_l: per-client algorithm inputs (leading axis C/n_dev)
            per_dev = idx_l.shape[0]
            k = self._resolve_chunk(per_dev)
            zeros = jax.tree_util.tree_map(
                lambda v: jnp.zeros_like(v, dtype=jnp.float32), variables
            )

            def one_client(idx_row, n_i, rng, cex):
                x = jnp.take(x_all, idx_row, axis=0)
                y = jnp.take(y_all, idx_row, axis=0)
                result = local_train(
                    variables, x, y, n_i, rng,
                    extra=algo.engine_extra(cex, server_state),
                )
                if post_train is not None:
                    # in-mesh local DP: per-client noise before aggregation
                    result = result._replace(variables=post_train(
                        result.variables, jax.random.fold_in(rng, 104729)
                    ))
                w = n_i.astype(jnp.float32)
                real = (n_i > 0).astype(jnp.float32)
                wv = jax.tree_util.tree_map(
                    lambda p: w * p.astype(jnp.float32), result.variables
                )
                contrib = algo.client_contrib(variables, result, w, real, cex, server_state)
                out = algo.client_out(variables, result, real, cex, server_state)
                if stacked:
                    # per-client update stack for the security program (the
                    # weights are the host-known sample counts); "tau" = the
                    # engine's step count so the security tail can recompute
                    # ext contributions (FedNova) from the defended stack
                    out = {"algo": out,
                           "update": jax.tree_util.tree_map(
                               lambda p: p.astype(jnp.float32), result.variables),
                           "tau": result.steps}
                return wv, w, result.loss * w, contrib, out

            vclients = jax.vmap(one_client)

            def train_chunk(carry, inp):
                acc, wsum, lsum, ext = carry
                wv, w, wl, contrib, out = vclients(*inp)  # leading axis k
                acc = jax.tree_util.tree_map(lambda a, p: a + p.sum(0), acc, wv)
                ext = jax.tree_util.tree_map(lambda e, c: e + c.sum(0), ext, contrib)
                return (acc, wsum + w.sum(), lsum + wl.sum(), ext), out

            chunked = jax.tree_util.tree_map(
                lambda t: t.reshape((per_dev // k, k) + t.shape[1:]),
                (idx_l, counts_l, rngs_l, cex_l),
            )
            (acc, wsum, lsum, ext), outs = jax.lax.scan(
                train_chunk,
                (zeros, 0.0, 0.0, algo.zero_contrib(variables)),
                chunked,
            )
            # un-chunk the stacked per-client outputs: [per_dev/k, k, ...] -> [per_dev, ...]
            outs = jax.tree_util.tree_map(
                lambda o: o.reshape((per_dev,) + o.shape[2:]), outs
            )
            # the "fedml_nccl_reduce": one psum over ICI
            wsum = jax.lax.psum(wsum, "client")
            lsum = jax.lax.psum(lsum, "client")
            ext = jax.lax.psum(ext, "client")
            mean_loss = lsum / jnp.maximum(wsum, 1e-9)
            if stacked:
                # aggregation + server step move to the security program,
                # which consumes the sharded update stack (XLA drops the
                # unused acc accumulator — no wasted model-size psum)
                return mean_loss, outs, ext
            acc = jax.lax.psum(acc, "client")
            if sharded:
                # server_state=sharded: the algorithm's server step moves to
                # the separate model-sharded GSPMD tail program — this
                # program ends at the reduced accumulator
                return acc, wsum, ext, mean_loss, outs
            # algorithm server step, replicated — still inside the XLA program
            new_global, new_state = algo.server_update(
                acc, wsum, ext, variables, server_state
            )
            return new_global, new_state, mean_loss, outs

        if stacked:
            out_specs = (P(), P("client"), P())
        elif sharded:
            out_specs = (P(), P(), P(), P(), P("client"))
        else:
            out_specs = (P(), P(), P(), P("client"))
        self._round_fn = jax.jit(
            shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(), P(), P(), P(), P("client"), P("client"), P("client"), P("client")),
                out_specs=out_specs,
                check_vma=False,
            )
        )

    def _build_server_tail(self):
        """server_state=sharded: the algorithm's server step as its own
        GSPMD jit program on a ``(client=1, model)`` round mesh.  Global
        variables and server-optimizer state live between rounds as
        ``NamedSharding`` arrays partitioned along the ``model`` axis (the
        :func:`~fedml_tpu.parallel.sharding.param_spec` heuristic picks the
        largest divisible dim per leaf); the psum'd accumulator is resharded
        onto the same layout and variables/state/acc buffers are DONATED, so
        the tail updates the globals in place with no replicated copy.  The
        training round itself is untouched (client-axis shard_map) — only
        the memory-bound round tail is model-sharded."""
        from ...parallel.sharding import param_spec

        devices = list(np.asarray(self.mesh.devices).flat)
        smp = int(getattr(self.args, "server_model_parallel", 0) or 0)
        if smp:
            if smp > len(devices):
                # degrade-to-replicate, mirroring the message plane's
                # round_mesh_for: a request the surviving mesh can't satisfy
                # runs the tail replicated instead of refusing the round
                logger.warning(
                    "server_model_parallel=%d exceeds the %d mesh devices; "
                    "degrading to a replicated (model=1) server tail",
                    smp, len(devices))
                obs.counter_inc("mesh.degraded_total")
                smp = 1
            devices = devices[:smp]
        rmesh = create_round_mesh(clients=1, model=len(devices),
                                  devices=devices)
        model = int(rmesh.shape["model"])
        repl = NamedSharding(rmesh, P())

        def shard_of(tree):
            return jax.tree_util.tree_map(
                lambda l: NamedSharding(
                    rmesh, param_spec(tuple(np.shape(l)), model, axis="model")),
                tree)

        var_sh = shard_of(self.variables)
        state_sh = shard_of(self.server_state)
        # the round fn replicates its inputs; when the tail runs on a device
        # subset its outputs must hop back to the full mesh between rounds
        self._tail_subset = len(devices) != self.n_dev
        self._tail_shardings = (var_sh, state_sh, repl)
        algo = self.algo

        def tail(variables, server_state, acc, wsum, ext):
            return algo.server_update(acc, wsum, ext, variables, server_state)

        self._server_tail = jax.jit(
            tail, donate_argnums=(0, 1, 2),
            in_shardings=(var_sh, state_sh, var_sh, repl, repl),
            out_shardings=(var_sh, state_sh))

    def _build_packed_round_fn(self):
        """Packed ragged round (ml/engine/packed.py): no per-client padding
        to the global max — each client contributes exactly ceil(n_i/B)*E
        batches, streamed through one while_loop per device.  Enabled by
        ``args.xla_pack``."""
        from ...ml.engine.packed import build_packed_device_fn, s_max_for

        mesh = self.mesh
        algo = self.algo
        self.slots = -(-self.clients_per_round // self.n_dev)
        self.s_max = s_max_for(
            self.max_client_n, self.slots, self.batch_size,
            int(getattr(self.args, "epochs", 1)),
        )
        stacked = self.needs_stack
        sharded = self.sharded_state
        device_fn = build_packed_device_fn(
            self.module, self.args, algo, self.batch_size, self.slots,
            loss=self.loss_kind,
            pregather=bool(getattr(self.args, "xla_pregather", False)),
            stream=str(getattr(self.args, "xla_stream", "while")),
            post_train=self._ldp_hook(),
            capture_updates=stacked,
        )

        def per_device(variables, server_state, x_all, y_all, idx, mask, boundary,
                       weight, slot, n_steps, rngs, cex):
            # arrays with a [n_dev, ...] leading axis arrive as [1, ...]
            acc, wsum, lsum, cnt, ext, outs = device_fn(
                variables, server_state, x_all, y_all, idx[0], mask[0],
                boundary[0], weight[0], slot[0], n_steps[0], rngs[0], cex,
            )
            lsum = jax.lax.psum(lsum, "client")
            cnt = jax.lax.psum(cnt, "client")
            ext = jax.lax.psum(ext, "client")
            mean_loss = lsum / jnp.maximum(cnt, 1.0)
            if stacked:
                return mean_loss, outs, ext
            acc = jax.lax.psum(acc, "client")
            wsum = jax.lax.psum(wsum, "client")
            if sharded:
                # program ends at the reduced accumulator; the model-sharded
                # tail applies the server step (same split as _build_round_fn)
                return acc, wsum, ext, mean_loss, outs
            new_global, new_state = algo.server_update(
                acc, wsum, ext, variables, server_state
            )
            return new_global, new_state, mean_loss, outs

        if stacked:
            out_specs = (P(), P("client"), P())
        elif sharded:
            out_specs = (P(), P(), P(), P(), P("client"))
        else:
            out_specs = (P(), P(), P(), P("client"))
        self._round_fn = jax.jit(
            shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(), P(), P(), P(), P("client"), P("client"), P("client"),
                          P("client"), P("client"), P("client"), P("client"), P("client")),
                out_specs=out_specs,
                check_vma=False,
            )
        )

    def _build_security_fn(self):
        """ONE jitted program for the round's security tail: stacked model
        attacks -> robust aggregation -> the algorithm's server step, consuming
        the round's sharded per-client update stack directly (no host
        materialization; multi-host safe under jax.distributed because jit
        handles the non-addressable P('client') leaves with global semantics).
        Mirrors ServerAggregator.on_before_aggregation/aggregate/
        defend_after_aggregation (reference fedml_attacker.py:28-30 +
        fedml_defender.py hook order)."""
        from jax.flatten_util import ravel_pytree

        from ...core.security import defense_funcs as DF
        from ...core.security.stacked import (
            build_stacked_attack,
            build_stacked_defense,
            stack_to_mat,
        )

        algo = self.algo
        via_acc = algo.aggregates_via_acc
        sharded = self.sharded_state
        use_plane = self.agg_plane == "compiled"
        attacker = FedMLAttacker.get_instance()
        defender = FedMLDefender.get_instance()
        attack_fn = (build_stacked_attack(self.args, attacker.attack_type)
                     if self.model_attacked else None)
        defend_fn = None
        if self.defended:
            probe_mask = None
            probe = getattr(defender, "_soteria_probe", None)
            if probe is not None:
                feature_fn, xs = probe
                probe_mask = DF.soteria_mask(
                    DF.soteria_scores(feature_fn, xs),
                    float(getattr(self.args, "soteria_percentile", 10.0)),
                )
            defend_fn = build_stacked_defense(
                self.args, defender.defense_type, probe_mask=probe_mask,
                rows=not via_acc,
            )
        self._defense_type = defender.defense_type if self.defended else None
        self._defense_state = None
        self._defense_n = -1

        def security_round(stack, weights, real_idx, mal_mask, meta, prev_global,
                           server_state, ext, key, dstate):
            sub = jax.tree_util.tree_map(lambda t: t[real_idx], stack)
            w = weights
            ka, kd = jax.random.split(key)
            g32 = jax.tree_util.tree_map(
                lambda v: v.astype(jnp.float32), prev_global
            )
            if via_acc:
                if attack_fn is not None:
                    g_vec, unravel = ravel_pytree(g32)
                    mat = attack_fn(stack_to_mat(sub), w, g_vec, mal_mask, ka)
                    sub = jax.vmap(unravel)(mat)
                if defend_fn is not None:
                    agg, dstate = defend_fn(sub, w, g32, kd, dstate)
                elif use_plane:
                    # the plane's sequential fold — same left-to-right order
                    # as the host weighted_mean, so the simulator's compiled
                    # security tail matches the server paths bit-for-bit
                    from ...parallel.agg_plane import stacked_reduce

                    agg = stacked_reduce(
                        sub, w / jnp.maximum(jnp.sum(w), 1e-9))
                else:
                    agg = jax.tree_util.tree_map(
                        lambda s: jnp.tensordot(w, s.astype(jnp.float32), axes=1)
                        / jnp.maximum(jnp.sum(w), 1e-9),
                        sub,
                    )
                # hand the robust aggregate to the algorithm's server step as
                # a weighted sum (every acc strategy divides by wsum)
                wsum = jnp.sum(w)
                acc = jax.tree_util.tree_map(lambda t: t * wsum, agg)
                if sharded:
                    # model-sharded state: the defended reduce stops at the
                    # accumulator and the GSPMD server tail applies the step
                    # (same two-program split as the undefended sharded round)
                    return acc, wsum, ext, dstate
                new_global, new_server_state = algo.server_update(
                    acc, wsum, ext, prev_global, server_state
                )
                return new_global, new_server_state, dstate
            # ext-aggregating strategies (FedNova, async): the attacked/
            # defended row space replaces the round's in-stream contribution
            # accumulation — ext is recomputed from the defended rows via the
            # strategy's own per-client math (sp composition: defenses filter
            # the update list, THEN the aggregator runs on the survivors)
            g_vec, unravel = ravel_pytree(g32)
            mat = stack_to_mat(sub)
            if attack_fn is not None:
                mat = attack_fn(mat, w, g_vec, mal_mask, ka)
            w2 = w
            if defend_fn is not None:
                sub2 = jax.vmap(unravel)(mat) if attack_fn is not None else sub
                mat, w2, dstate = defend_fn(sub2, w, g32, kd, dstate)
            ext2 = algo.ext_from_rows(mat, w2, w, meta, g_vec, unravel)
            # contract-complete acc (the defended weighted sum); strategies
            # that only read ext leave it to XLA's dead-code elimination
            acc = unravel(w2 @ mat)
            if sharded:
                return acc, jnp.sum(w2), ext2, dstate
            new_global, new_server_state = algo.server_update(
                acc, jnp.sum(w2), ext2, prev_global, server_state
            )
            return new_global, new_server_state, dstate

        self._security_fn = jax.jit(security_round)

    def _ensure_defense_state(self, n_real: int):
        if not self.defended:
            return {}
        if self._defense_state is None or self._defense_n != n_real:
            from ...core.security.stacked import flat_dim, init_defense_state

            # cross-round per-slot state (foolsgold history, wbc prev) is
            # positional; a changed participant count resets it, matching the
            # host dispatcher's shape-mismatch reset
            self._defense_state = init_defense_state(
                self._defense_type, n_real, flat_dim(self.variables)
            )
            self._defense_n = n_real
        return self._defense_state

    def _packed_inputs(self, ids: np.ndarray, counts: np.ndarray, round_idx: int):
        from ...ml.engine.packed import pack_round

        ids2d = ids.reshape(self.n_dev, self.slots)
        counts2d = counts.reshape(self.n_dev, self.slots)
        sched = pack_round(
            ids2d, counts2d,
            lambda cid: self._client_rows[cid],
            self.batch_size, int(getattr(self.args, "epochs", 1)),
            int(getattr(self.args, "random_seed", 0)), round_idx, self.s_max,
        )
        # trim the stream buffers to a quantized bucket of the round's real
        # max steps: uploads, the scan-stream tail, and (with xla_pregather)
        # the round's data gather all scale with the bucket, not the global
        # worst case.  Quantum = s_max/8 -> at most 8 distinct shapes per
        # run (each compiles once, then caches — flip-flopping between
        # already-compiled levels costs nothing) and <= one quantum of
        # overshoot, vs up to 2x for the old monotone power-of-two ladder.
        s_used = max(int(sched.n_steps.max()), 1)
        quantum = max(1, -(-self.s_max // 8))
        s_bucket = min(-(-s_used // quantum) * quantum, self.s_max)
        seen = getattr(self, "_seen_buckets", None)
        if seen is None:
            seen = self._seen_buckets = set()
        # first round at a new bucket shape pays an XLA recompile: flag it so
        # train() keeps that wall time out of the runtime model's fit
        self._bucket_compiling = s_bucket not in seen
        seen.add(s_bucket)
        self._s_bucket = s_bucket
        sched = sched._replace(
            idx=sched.idx[:, :s_bucket], mask=sched.mask[:, :s_bucket],
            boundary=sched.boundary[:, :s_bucket], weight=sched.weight[:, :s_bucket],
            slot=sched.slot[:, :s_bucket],
        )
        return tuple(jnp.asarray(a) for a in sched)

    def _client_steps(self, n: int) -> int:
        """A client's cost in the packed round's native unit: compiled steps
        (ceil(n/B) per epoch) — the quantity the while_loop actually runs."""
        if n <= 0:
            return 0
        return -(-int(n) // self.batch_size) * int(getattr(self.args, "epochs", 1))

    def _schedule(self, sampled: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Balance sampled clients across mesh slots via core/schedule
        (SeqTrainScheduler; runtime-model-aware once rounds have been
        observed).  Returns (client_ids [C_pad], is_real [C_pad]) laid out so
        that reshape(n_dev, -1) gives each device its contiguous schedule.

        Cost units match what each round variant executes: the packed stream
        runs ceil(n/B)*E steps per client (a 1-sample client costs a whole
        batch step), the padded round always runs padded_n/B steps, so LPT
        balances packed rounds on STEP counts and the runtime model is fed
        the same unit (see the record() call in train())."""
        if self.packed:
            sizes = [self._client_steps(self.local_num_dict[int(c)]) for c in sampled]
        else:
            sizes = [self.local_num_dict[int(c)] for c in sampled]
        ids2d, mask2d, _ = self.scheduler.schedule(sampled, sizes)
        return ids2d.reshape(-1), mask2d.reshape(-1)

    def _client_sampling(self, round_idx: int) -> np.ndarray:
        if self._stacked_schedule is not None:
            return self._stacked_schedule[round_idx % len(self._stacked_schedule)]
        return np.asarray(
            self.population.select(round_idx, self.clients_per_round), np.int64
        )

    # ------------------------------------------------------------------
    # buffered-async virtual-arrival driver (fl_mode=async)
    # ------------------------------------------------------------------
    def _async_init(self):
        """Deterministic virtual-time schedule: per-client durations drawn
        once from ``random_seed`` (the sp FedBuffAPI idiom), a fixed cohort
        (the round-0 population draw — async cycles re-dispatch the same
        pool, matching the message-plane servers), and a flush size of
        ``async_buffer_size`` arrivals.  Each XLA round is one flush."""
        from ...core.async_fl import VirtualArrivalQueue
        from ...core.checkpoint import maybe_checkpointer

        if maybe_checkpointer(self.args) is not None:
            raise NotImplementedError(
                "fl_mode=async does not checkpoint mid-run in the XLA "
                "simulator (the virtual arrival queue is not persisted)")
        cap = int(getattr(self.args, "async_buffer_size", 0) or 0) \
            or self.clients_per_round
        if cap > self.clients_per_round:
            logger.warning("async_buffer_size=%d exceeds the cohort (%d): "
                           "clamping", cap, self.clients_per_round)
            cap = self.clients_per_round
        self._async_cap = cap
        self._async_max_staleness = int(
            getattr(self.args, "async_max_staleness", 0) or 0)
        rng = np.random.RandomState(int(getattr(self.args, "random_seed", 0)))
        self._async_durations = 0.5 + rng.exponential(
            1.0, size=self.num_clients)
        self._async_cohort = [int(c) for c in self._client_sampling(0)]
        self._async_version = 0
        self._async_dispatched = {c: 0 for c in self._async_cohort}
        self._async_queue = VirtualArrivalQueue()
        for c in self._async_cohort:
            self._async_queue.push(c, float(self._async_durations[c]))
        self._async_t = 0.0
        self._async_dropped_stale = 0

    def _async_next_flush(self) -> Tuple[np.ndarray, Dict[int, int]]:
        """Pop arrivals off the virtual queue until one buffer's worth
        accrues; returns (cohort sorted by id, staleness by id).  Sorting
        keeps the mesh layout id-deterministic — and makes the
        full-participation constant-weight config schedule-identical to the
        sync loop (the arrival ORDER carries no weight information; the
        staleness map does)."""
        picked: List[int] = []
        stal: Dict[int, int] = {}
        v = self._async_version
        while len(picked) < self._async_cap:
            t, cid = self._async_queue.pop()
            self._async_t = t
            s = v - self._async_dispatched[cid]
            if s > self._async_max_staleness:
                # too stale to aggregate: fresh work beats idling
                self._async_dropped_stale += 1
                obs.counter_inc("async.dropped_stale")
                self._async_dispatched[cid] = v
                self._async_queue.push(cid, t + float(self._async_durations[cid]))
                continue
            picked.append(cid)
            stal[cid] = int(s)
            obs.histogram_observe("async.staleness", float(s))
            if self._async_max_staleness >= 1 and len(picked) < self._async_cap:
                # FedBuff: the client keeps training while its delta waits
                self._async_dispatched[cid] = v
                self._async_queue.push(cid, t + float(self._async_durations[cid]))
        return np.asarray(sorted(picked), np.int64), stal

    def _async_round_end(self):
        """The flush applied: bump the version and re-dispatch every idle
        cohort member on the fresh global at the flush's virtual time."""
        self._async_version += 1
        obs.counter_inc("async.flushes", labels={"reason": "full"})
        in_flight = set(self._async_queue.clients())
        for c in self._async_cohort:
            if c not in in_flight:
                self._async_dispatched[c] = self._async_version
                self._async_queue.push(
                    c, self._async_t + float(self._async_durations[c]))

    def train(self) -> Dict[str, Any]:
        from ...core.checkpoint import checkpoint_frequency, maybe_checkpointer

        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 10))
        eval_enabled = freq > 0  # freq <= 0 disables eval (throughput benches)
        last: Dict[str, Any] = {}
        ckpt = maybe_checkpointer(self.args)
        start_round = 0
        if ckpt is not None and ckpt.latest_step() is not None:
            from flax import serialization

            step, state = ckpt.restore()
            self.variables = state["variables"]
            self._rng = jnp.asarray(state["rng"])
            if "server_state" in state:
                self.server_state = serialization.from_state_dict(
                    self.server_state, state["server_state"]
                )
            if self.client_state is not None and "client_state" in state:
                self.client_state = serialization.from_state_dict(
                    self.client_state, state["client_state"]
                )
            if "algo_host_state" in state:
                self.algo.restore_host_state(state["algo_host_state"])
            if self.defended and state.get("defense_state"):
                # cross-round defense state (foolsgold history, wbc prev):
                # without it a resumed run silently re-pardons attenuated
                # sybils / loses the perturbation baseline
                self._defense_state = {
                    k: jnp.asarray(v) for k, v in state["defense_state"].items()
                }
                self._defense_n = int(state.get("defense_n", -1))
            start_round = step + 1
            logger.info("resumed from checkpoint round %d", step)
        profiling = bool(getattr(self.args, "enable_profiler", False))
        if profiling:
            # whole-run XLA trace (TensorBoard-viewable; the reference's
            # profiler posts wall-clock events — on TPU the on-device
            # timeline is the thing worth capturing)
            prof_dir = str(getattr(self.args, "profiler_dir", "")
                           or os.path.join(
                               str(getattr(self.args, "log_file_dir", ".") or "."),
                               "xla_trace"))
            jax.profiler.start_trace(prof_dir)
            logger.info("jax profiler trace -> %s", prof_dir)
        # in-process loopback telemetry (cohort-level: the in-mesh round has
        # no per-client wall times, so the remote "client.train" leg covers
        # the whole cohort's execute time) — keeps the trace_report shape
        # identical between simulation and distributed runs
        tele_cap = obs.make_client_telemetry(0)
        tele_merger = obs.make_telemetry_merger()
        for round_idx in range(start_round, comm_round):
            t0 = time.time()
            compile_s0 = obs.compile_seconds_total()
            # the whole round is one (or two) compiled XLA programs, so the
            # round root is the only meaningful span here; annotate=True nests
            # it inside the device trace when enable_profiler is on
            rsp = obs.round_span(
                round_idx, annotate=True,
                mode="simulation_xla_async" if self.async_mode
                else "simulation_xla")
            if self.async_mode:
                sampled, stal_map = self._async_next_flush()
                self.algo.set_staleness(stal_map)
            else:
                sampled = self._client_sampling(round_idx)
            ids, real = self._schedule(sampled)
            counts = np.where(real > 0, np.asarray(self.client_counts)[ids], 0)
            # participation mask as the compiled round sees it: a sampled
            # client with zero local samples contributes nothing in-mesh
            participated = (counts > 0).astype(np.float32)
            self._rng, sub = jax.random.split(self._rng)
            cex = self.algo.gather_client_extras(
                self.client_state, ids, participated, round_idx
            )
            prev_global = self.variables  # defense reference (pre-round global)
            dp = FedMLDifferentialPrivacy.get_instance()
            if dp.is_local_dp_enabled():
                # account BEFORE the round releases anything (matching the sp
                # path, where add_noise spends before producing the noised
                # update): budget exhaustion must abort the round, not trail it
                dp.spend_budget(int(participated.sum()))
            if self.packed:
                packed = self._packed_inputs(np.asarray(ids), counts, round_idx)
                dev_rngs = jax.random.split(
                    jax.random.fold_in(sub, round_idx), self.n_dev
                )
                round_inputs = (self.variables, self.server_state, self.x_all,
                                self.y_all, *packed, dev_rngs, cex)
            else:
                rngs = jax.random.split(jax.random.fold_in(sub, round_idx), len(ids))
                idx_rows = self.client_idx[jnp.asarray(ids)]
                round_inputs = (self.variables, self.server_state, self.x_all,
                                self.y_all, idx_rows, jnp.asarray(counts), rngs, cex)
            if self.needs_stack:
                # security path: the round returns the sharded per-client
                # update stack; the second jitted program runs stacked model
                # attacks + robust aggregation + the server step on device
                mean_loss, outs, ext = self._round_fn(*round_inputs)
                stack = outs["update"]
                taus = outs["tau"]
                outs = outs["algo"]
                real_sel = np.where(counts > 0)[0]
                if real_sel.size > 0:
                    attacker = FedMLAttacker.get_instance()
                    mal = np.zeros(real_sel.size, np.float32)
                    if self.model_attacked:
                        bad = set(attacker.get_byzantine_idxs(self.num_clients))
                        mal = np.array(
                            [1.0 if int(ids[i]) in bad else 0.0 for i in real_sel],
                            np.float32,
                        )
                    dstate = self._ensure_defense_state(int(real_sel.size))
                    # derive the security key from the round's sub-key, NOT by
                    # splitting the main stream: the round-r data/rng layout
                    # must be identical with and without the security tail
                    # (one split per round is the replayable invariant)
                    skey = jax.random.fold_in(sub, 999331)
                    meta = self.algo.security_meta(taus, cex, jnp.asarray(real_sel))
                    sec_inputs = (
                        stack,
                        jnp.asarray(counts[real_sel], jnp.float32),
                        jnp.asarray(real_sel),
                        jnp.asarray(mal),
                        meta,
                        self.variables,
                        self.server_state,
                        ext,
                        skey,
                        dstate,
                    )
                    with obs.span("aggregate.reduce", rsp.ctx,
                                  round_idx=round_idx,
                                  n_clients=int(real_sel.size),
                                  mode="inmesh"):
                        if self.sharded_state:
                            # defended + model-sharded: the security program
                            # stops at the robust accumulator; the GSPMD
                            # server tail applies the step on donated
                            # resident buffers (the same two-program split
                            # the undefended sharded round uses)
                            acc_d, wsum_d, ext_d, self._defense_state = (
                                self._security_fn(*sec_inputs))
                            var_sh, state_sh, repl = self._tail_shardings
                            t_tail = time.time()
                            with warnings.catch_warnings():
                                warnings.filterwarnings(
                                    "ignore",
                                    message="Some donated buffers were not usable")
                                self.variables, self.server_state = self._server_tail(
                                    jax.device_put(self.variables, var_sh),
                                    jax.device_put(self.server_state, state_sh),
                                    jax.device_put(acc_d, var_sh),
                                    jax.device_put(wsum_d, repl),
                                    jax.device_put(ext_d, repl),
                                )
                            jax.block_until_ready(self.variables)
                            obs.histogram_observe(
                                "server_opt.step_seconds", time.time() - t_tail,
                                labels={"policy": type(self.algo).__name__,
                                        "mode": "inmesh"})
                            if self._tail_subset:
                                full = NamedSharding(self.mesh, P())
                                self.variables = jax.device_put(
                                    self.variables, full)
                                self.server_state = jax.device_put(
                                    self.server_state, full)
                        else:
                            self.variables, self.server_state, self._defense_state = (
                                self._security_fn(*sec_inputs))
                            jax.block_until_ready(self.variables)
                    if self.analysis_attacked and round_idx % max(
                        1, int(getattr(self.args, "dlg_frequency", 1))
                    ) == 0:
                        # privacy/analysis attack (dlg, invert_gradient,
                        # revealing_labels): run on ONE intercepted update (a
                        # single model-size host pull; dlg_frequency gates the
                        # per-round gradient-matching cost)
                        bad = set(attacker.get_byzantine_idxs(self.num_clients))
                        victims = [int(i) for i in real_sel
                                   if int(ids[i]) in bad] or [int(real_sel[0])]
                        row = jax.tree_util.tree_map(
                            lambda t: t[victims[0]], stack
                        )
                        attacker.analyze_update(
                            self.module, prev_global, row,
                            (int(getattr(self.args, "dlg_batch_size", 1)),)
                            + tuple(self.x_all.shape[1:]),
                            self.class_num,
                        )
            elif self.sharded_state:
                # two programs: the client-axis training round ends at the
                # psum'd accumulator; the model-sharded GSPMD tail applies
                # the algorithm's server step on donated resident buffers
                acc, wsum, ext, mean_loss, outs = self._round_fn(*round_inputs)
                var_sh, state_sh, repl = self._tail_shardings
                t_tail = time.time()
                with obs.span("round.server_update", rsp.ctx,
                              round_idx=round_idx,
                              n_clients=int(participated.sum()),
                              mode="inmesh", policy=type(self.algo).__name__):
                    with warnings.catch_warnings():
                        # donation is a no-op on CPU backends; expected there
                        warnings.filterwarnings(
                            "ignore",
                            message="Some donated buffers were not usable")
                        self.variables, self.server_state = self._server_tail(
                            jax.device_put(self.variables, var_sh),
                            jax.device_put(self.server_state, state_sh),
                            jax.device_put(acc, var_sh),
                            jax.device_put(wsum, repl),
                            jax.device_put(ext, repl),
                        )
                    jax.block_until_ready(self.variables)
                obs.histogram_observe(
                    "server_opt.step_seconds", time.time() - t_tail,
                    labels={"policy": type(self.algo).__name__,
                            "mode": "inmesh"})
                if self._tail_subset:
                    full = NamedSharding(self.mesh, P())
                    self.variables = jax.device_put(self.variables, full)
                    self.server_state = jax.device_put(self.server_state, full)
            else:
                self.variables, self.server_state, mean_loss, outs = self._round_fn(
                    *round_inputs
                )
            self.client_state = self.algo.apply_client_outs(self.client_state, ids, outs)
            self.algo.host_round_end(ids, participated, round_idx)
            if self.async_mode:
                # the flush's record span (the aggregation itself ran inside
                # the compiled round): staleness distribution + buffer shape
                # for trace_report's async columns
                svals = list(stal_map.values()) or [0]
                with obs.span("buffer.flush", rsp.ctx, round_idx=round_idx,
                              n_deltas=len(sampled), reason="full",
                              capacity=self._async_cap,
                              staleness_min=int(min(svals)),
                              staleness_mean=round(
                                  float(np.mean(svals)), 4),
                              staleness_max=int(max(svals))):
                    pass
                self._async_round_end()
            # host-side hooks (attack/defense need per-client updates and run
            # in the host path; central DP applies here)
            if dp.is_global_dp_enabled():
                self.variables = dp.add_global_noise(self.variables)
            jax.block_until_ready(self.variables)
            dt = time.time() - t0
            if obs.enabled() and len(self.round_times) >= 3:
                med = float(np.median(self.round_times))
                if dt > obs.slow_round_factor() * med:
                    obs.span_event("slow_round", rsp.ctx, round_idx=round_idx,
                                   dt_s=round(dt, 4), median_s=round(med, 4))
            obs.histogram_observe("round.seconds", float(dt))
            obs.counter_inc("agg.bytes_reduced",
                            int(participated.sum()) * self._model_bytes,
                            labels={"path": "inmesh"})
            # compile-vs-execute attribution: the jax.monitoring listener
            # accumulated every backend compile this round triggered (round
            # fn, security fn, eval fn); the rest of the wall time is
            # execute + host orchestration
            compile_s = max(0.0, obs.compile_seconds_total() - compile_s0)
            if compile_s > 0.0:
                obs.histogram_observe("round.compile_seconds", compile_s)
            rsp.end(reason="closed", loss=float(mean_loss),
                    compile_s=round(compile_s, 6),
                    execute_s=round(max(0.0, dt - compile_s), 6))
            if tele_cap is not None and tele_merger is not None:
                tctx = tele_cap.record_span(
                    "client.train", max(0.0, dt - compile_s), parent=rsp.ctx,
                    round_idx=round_idx, cohort=int(participated.sum()))
                if compile_s > 0.0:
                    tele_cap.record_span(
                        "client.train.compile", compile_s, parent=tctx,
                        round_idx=round_idx)
                tele_cap.record_span(
                    "client.train.step", max(0.0, dt - compile_s),
                    parent=tctx, round_idx=round_idx)
                tele_cap.sample_resources()
                tele_blob = tele_cap.drain()
                if tele_blob:
                    tele_merger.merge(tele_blob)
            obs.maybe_export_metrics()
            self.round_times.append(dt)
            if round_idx > 0:  # round 0 is dominated by XLA compile
                # The round's wall time is set by the heaviest mesh slot.
                # Packed: record max device STEPS — the while_loop's actual
                # trip count, so round time is genuinely load-dependent and
                # the fitted slope drives next rounds' LPT balancing (in the
                # same step units _schedule passes as costs).  Padded: the
                # round is shape-static (every client pays padded_n), so the
                # model degenerates to count-balancing there by design.
                if self.packed:
                    if getattr(self, "_bucket_compiling", False):
                        pass  # compile-dominated round: would poison the fit
                    else:
                        epochs_ = int(getattr(self.args, "epochs", 1))
                        steps2d = -(-counts.reshape(self.n_dev, -1)
                                    // self.batch_size) * epochs_
                        self.runtime_estimator.record(
                            0, int(steps2d.sum(axis=1).max()), dt
                        )
                else:
                    dev_loads = counts.reshape(self.n_dev, -1).sum(axis=1)
                    self.runtime_estimator.record(0, int(dev_loads.max()), dt)
            epochs = int(getattr(self.args, "epochs", 1))
            self.samples_per_round.append(int(counts.sum()) * epochs)
            self.samples_trained += int(counts.sum()) * epochs
            self.metrics.log(
                {"round": round_idx, "round_time_s": round(dt, 4), "train_loss": float(mean_loss)}
            )
            from ...core import mlops

            mlops.log_round_info(comm_round, round_idx)
            # population accounting for the synchronous round: everyone
            # sampled was invited and reported; emits cohort_stats
            self.population.observe_round(round_idx, sampled, seconds=dt)
            if ckpt is not None and (
                round_idx % checkpoint_frequency(self.args) == 0 or round_idx == comm_round - 1
            ):
                from flax import serialization

                state = {"variables": self.variables, "rng": self._rng,
                         "server_state": serialization.to_state_dict(self.server_state)}
                if self.client_state is not None:
                    state["client_state"] = serialization.to_state_dict(self.client_state)
                host = self.algo.host_state()
                if host:
                    state["algo_host_state"] = host
                if self.defended and self._defense_state:
                    state["defense_state"] = {
                        k: np.asarray(v) for k, v in self._defense_state.items()
                    }
                    state["defense_n"] = self._defense_n
                ckpt.save(round_idx, state)
            if eval_enabled and (round_idx % freq == 0 or round_idx == comm_round - 1):
                last = self._test_global(round_idx)
        if profiling:
            jax.profiler.stop_trace()
        return last

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        self.aggregator.set_model_params(self.variables)
        stats = self.aggregator.test(self.test_global, None, self.args)
        out = {
            "round": round_idx,
            "test_acc": round(stats["test_correct"] / stats["test_total"], 4),
            "test_loss": round(stats["test_loss"] / stats["test_total"], 4),
        }
        # task-specific extras (mean IoU, exact match, RMSE, ...) pass through
        for k, v in stats.items():
            if k.startswith("test_") and k not in ("test_correct", "test_total", "test_loss"):
                out[k] = round(float(v), 4)
        self.metrics.log(out)
        logger.info("eval: %s", out)
        return out

    # exposed for benchmarking
    def throughput(self) -> Dict[str, float]:
        """Steady-state throughput.  Round 0 is XLA compile and the first
        executed round pays the one-time host->HBM dataset upload, so the
        representative per-round cost is the MEDIAN over post-compile rounds
        (one-time costs amortize to nothing over a real run's hundreds of
        rounds).  NOTE: the median only isolates steady state when >= 3
        post-compile rounds ran (bench.py uses comm_round=6); with fewer,
        the upload round still weighs in.  mean_round_s keeps the
        warmup-inclusive average for comparison.  All zeros if no round ran.
        """
        import numpy as _np

        times = self.round_times[1:] if len(self.round_times) > 1 else self.round_times
        samples = (
            self.samples_per_round[1:]
            if len(self.samples_per_round) > 1
            else self.samples_per_round
        )
        if not times:
            return {"rounds_per_sec": 0.0, "mean_round_s": 0.0,
                    "median_round_s": 0.0, "samples_per_sec": 0.0}
        med = float(_np.median(times))
        # per-round pairing preserved: median of the per-round ratios
        sps = float(_np.median([s / max(t, 1e-9) for s, t in zip(samples, times)]))
        return {
            "rounds_per_sec": 1.0 / max(med, 1e-9),
            "mean_round_s": sum(times) / len(times),
            "median_round_s": med,
            "samples_per_sec": sps,
        }
