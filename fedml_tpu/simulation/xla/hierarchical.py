"""In-mesh hierarchical FL: the two-level (client -> group -> global)
round compiles into one XLA program over the ``client`` mesh axis.

The reference's hierarchical simulator (``simulation/sp/hierarchical_fl``,
244 LoC; mirrored by our sp twin ``sp/hierarchical_fl/hier_api.py``) runs
group-local FedAvg rounds and periodically averages group models into a
global.  Here the sampled clients of ALL groups train in one shard_mapped
pass — each slot gathers ITS group's current model from a replicated
``[G, ...]`` group stack — and the group-level aggregation is a one-hot
(group-id) contraction accumulated through the per-device scan and psum'd
over ICI: the two reduce levels of the hierarchy collapse into a single
collective.  On global-sync rounds (every ``group_comm_round``-th) the same
program also folds the size-weighted global average and resets the group
stack — a second traced variant, selected host-side (the schedule is
static per round).

Equivalence: group membership, per-group sampling, and per-(round, client)
keys reproduce the sp twin bit-for-bit (tests/test_xla_hierarchical.py).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ...ml.engine.train import build_local_train, init_variables
from ...utils.metrics import MetricsLogger
from .fed_sim import shard_map
from jax.sharding import PartitionSpec as P

logger = logging.getLogger(__name__)


class HierarchicalInMeshAPI:
    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ...ml.trainer.trainer_creator import loss_kind_for_dataset
        from .split import _pad_clients

        self.args = args
        (_tn, _ten, _tg, self.test_global, local_num, local_train, _lt,
         self.class_num) = dataset
        self.module = model
        self.num_clients = int(args.client_num_in_total)
        if mesh is None:
            from ...parallel.mesh import create_fl_mesh

            mesh = create_fl_mesh()
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.bs = int(getattr(args, "batch_size", 32))
        self.seed = int(getattr(args, "random_seed", 0))
        self.group_num = int(getattr(args, "group_num", 2))
        self.group_comm_round = int(getattr(args, "group_comm_round", 2))

        self.x_all, self.y_all, self.idx, self.counts, self.padded_n = _pad_clients(
            local_train, local_num, self.num_clients, self.bs
        )
        # same membership draw as the sp twin (exact-equivalence seam)
        rng = np.random.RandomState(self.seed)
        ids = rng.permutation(self.num_clients)
        self.groups = np.array_split(ids, self.group_num)
        self.group_sizes = jnp.asarray(
            [float(sum(int(local_num[int(c)]) for c in m)) for m in self.groups]
        )
        self.client_group = np.zeros(self.num_clients, np.int32)
        for g, members in enumerate(self.groups):
            self.client_group[members] = g

        proto = init_variables(model, jnp.asarray(self.x_all[:1], jnp.float32),
                               seed=self.seed)
        self.group_stack = jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(p, (self.group_num,) + p.shape), proto
        )
        self.w_global = proto

        loss_kind = loss_kind_for_dataset(str(getattr(args, "dataset", "")).lower())
        local_train_fn = build_local_train(
            model, args, self.bs, self.padded_n, loss=loss_kind
        )
        G = self.group_num
        group_sizes = self.group_sizes

        def make_per_device(sync: bool):
            def per_device(group_stack, x_all, y_all, idx_l, counts_l, gids_l, rngs_l):
                def one_slot(carry, inp):
                    gacc, gw, lsum = carry
                    idx_row, n_i, gid, rng = inp
                    start = jax.tree_util.tree_map(
                        lambda t: t[gid], group_stack
                    )
                    x = jnp.take(x_all, idx_row, axis=0)
                    y = jnp.take(y_all, idx_row, axis=0)
                    result = local_train_fn(start, x, y, n_i, rng)
                    w = n_i.astype(jnp.float32)
                    hot = jax.nn.one_hot(gid, G) * w  # [G]
                    # the client->group reduce level: one-hot(group) outer
                    # product accumulates each group's weighted param sum
                    gacc = jax.tree_util.tree_map(
                        lambda a, p: a + hot.reshape((G,) + (1,) * p.ndim)
                        * p.astype(jnp.float32)[None, ...],
                        gacc, result.variables,
                    )
                    return (gacc, gw + hot, lsum + result.loss * w), 0.0

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((G,) + p.shape[1:], jnp.float32), group_stack
                )
                (gacc, gw, lsum), _ = jax.lax.scan(
                    one_slot, (zeros, jnp.zeros(G), 0.0),
                    (idx_l, counts_l, gids_l, rngs_l),
                )
                gacc = jax.lax.psum(gacc, "client")
                gw = jax.lax.psum(gw, "client")
                lsum = jax.lax.psum(lsum, "client")
                # group models: weighted mean where the group trained, else kept
                new_stack = jax.tree_util.tree_map(
                    lambda a, old: jnp.where(
                        (gw > 0).reshape((G,) + (1,) * (a.ndim - 1)),
                        a / jnp.maximum(gw, 1e-9).reshape((G,) + (1,) * (a.ndim - 1)),
                        old.astype(jnp.float32),
                    ),
                    gacc, group_stack,
                )
                mean_loss = lsum / jnp.maximum(jnp.sum(gw), 1e-9)
                if not sync:
                    return new_stack, new_stack, mean_loss  # global slot unused
                # global sync: size-weighted mean of group models, reset stack
                wsum = jnp.sum(group_sizes)
                glob = jax.tree_util.tree_map(
                    lambda s: jnp.tensordot(group_sizes, s, axes=(0, 0)) / wsum,
                    new_stack,
                )
                reset = jax.tree_util.tree_map(
                    lambda g_, s: jnp.broadcast_to(g_, s.shape), glob, new_stack
                )
                return reset, glob, mean_loss

            return per_device

        specs = dict(
            mesh=mesh,
            in_specs=(P(), P(), P(), P("client"), P("client"), P("client"), P("client")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        self._round_fn = jax.jit(shard_map(make_per_device(False), **specs))
        self._sync_round_fn = jax.jit(shard_map(make_per_device(True), **specs))

        from ...core.schedule import SeqTrainScheduler

        self._scheduler = SeqTrainScheduler(self.n_dev)
        from ...ml.aggregator.aggregator_creator import create_server_aggregator

        self.aggregator = create_server_aggregator(model, args)
        self.aggregator.set_model_params(self.w_global)
        self.metrics = MetricsLogger(args)
        self.eval_history: List[Dict[str, Any]] = []
        self._base_key = jax.random.PRNGKey(self.seed)

    def _sample_round(self, round_idx: int) -> np.ndarray:
        """Per-group draws with the sp twin's exact RandomState streams."""
        per_group = max(1, int(self.args.client_num_per_round) // self.group_num)
        chosen: List[int] = []
        for g, members in enumerate(self.groups):
            rng = np.random.RandomState(self.seed * 100003 + round_idx * 131 + g)
            chosen.extend(int(c) for c in rng.choice(
                members, min(per_group, len(members)), replace=False
            ))
        return np.asarray(chosen, np.int64)

    def train(self) -> Dict[str, Any]:
        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        counts_all = np.asarray(self.counts)
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            sampled = self._sample_round(round_idx)
            sizes = [int(counts_all[c]) for c in sampled]
            ids2d, mask2d, _ = self._scheduler.schedule(sampled, sizes)
            ids = ids2d.reshape(-1).astype(np.int64)
            cnt = np.where(mask2d.reshape(-1) > 0, counts_all[ids], 0).astype(np.int32)
            gids = self.client_group[ids]
            rk = jax.random.fold_in(self._base_key, round_idx)
            rngs = jax.vmap(lambda c: jax.random.fold_in(rk, c))(jnp.asarray(ids))
            sync = (round_idx + 1) % self.group_comm_round == 0
            fn = self._sync_round_fn if sync else self._round_fn
            self.group_stack, glob, mean_loss = fn(
                self.group_stack, self.x_all, self.y_all,
                self.idx[jnp.asarray(ids)], jnp.asarray(cnt),
                jnp.asarray(gids), rngs,
            )
            if sync:
                # sp twin applies on_after_aggregation at sync (central DP);
                # if the hook transformed the global, the group reset must
                # carry the post-hook model too
                hooked = self.aggregator.on_after_aggregation(glob)
                if hooked is not glob:
                    self.group_stack = jax.tree_util.tree_map(
                        lambda g_, s: jnp.broadcast_to(g_, s.shape),
                        hooked, self.group_stack,
                    )
                self.w_global = hooked
                self.aggregator.set_model_params(self.w_global)
            self.metrics.log({"round": round_idx, "train_loss": float(mean_loss)})
            if freq > 0 and (round_idx % freq == 0 or round_idx == comm_round - 1):
                last = self._test_global(round_idx)
        return last

    def group_model(self, g: int):
        """One group's current model (host copy) — test/debug surface."""
        return jax.tree_util.tree_map(lambda t: t[g], self.group_stack)

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        stats = self.aggregator.test(self.test_global, None, self.args)
        out = {
            "round": round_idx,
            "test_acc": round(stats["test_correct"] / stats["test_total"], 4),
            "test_loss": round(stats["test_loss"] / stats["test_total"], 4),
        }
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("hierarchical in-mesh eval: %s", out)
        return out
