"""In-mesh FedGAN and FedNAS: the generative/search zoo members compiled
onto the client mesh.

The reference runs both through per-process MPI programs
(``simulation/mpi/fedgan`` 790 LoC — every client trains its (G, D) pair
locally, the server FedAvg-aggregates both nets;  ``simulation/mpi/fednas``
890 LoC — DARTS search steps update weights w AND architecture logits alpha,
the server averages both).  Here each round is ONE XLA program over the
``client`` mesh axis, the same shape as the main simulator's round
(fed_sim.py): sampled clients are sharded over devices, each device scans
its slots sequentially, local training is a compiled ``fori_loop``, and the
server aggregate is a weighted ``psum`` riding ICI — for FedGAN the psum
carries BOTH parameter pytrees (G and D), for FedNAS it carries (w, alpha).

Dispatched from :class:`fedml_tpu.simulation.simulator.SimulatorXLA` for
``federated_optimizer`` in {fedgan, fednas} — the same configs that pick the
sp twins (simulation/sp/{fedgan,fednas}) pick these on ``backend: XLA``.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from ...utils.metrics import MetricsLogger
from .fed_sim import shard_map
from jax.sharding import PartitionSpec as P

logger = logging.getLogger(__name__)


def _client_mesh(mesh: Mesh = None) -> Mesh:
    if mesh is not None:
        return mesh
    from ...parallel.mesh import create_fl_mesh

    return create_fl_mesh()


def _schedule_round(sampled: np.ndarray, counts_all: np.ndarray, n_dev: int):
    """Balance sampled clients over devices via the shared core/schedule
    scheduler (the same one the main simulator uses — one balancing
    implementation to maintain); dummy slots get count 0.  Returns
    (ids [n_dev*slots], counts [n_dev*slots]) laid out so that
    reshape(n_dev, slots) gives each device its contiguous schedule."""
    from ...core.schedule import SeqTrainScheduler

    sizes = [int(counts_all[int(c)]) for c in sampled]
    ids2d, mask2d, _ = SeqTrainScheduler(n_dev).schedule(sampled, sizes)
    ids = ids2d.reshape(-1).astype(np.int32)
    cnt = np.where(mask2d.reshape(-1) > 0, counts_all[ids], 0).astype(np.int32)
    return ids, cnt


class GANInMeshAPI:
    """Federated GAN with the client axis on the mesh: each slot runs the
    alternating D/G local loop on its HBM-gathered shard, the weighted psum
    averages BOTH networks (reference ``simulation/mpi/fedgan`` server)."""

    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ...models.gan import MNISTDiscriminator, MNISTGenerator
        from .split import _pad_clients

        self.args = args
        (_, _, _tg, _teg, local_num, local_train, _lt, _cn) = dataset
        self.num_clients = int(args.client_num_in_total)
        self.mesh = _client_mesh(mesh)
        self.n_dev = self.mesh.devices.size
        self.bs = int(getattr(args, "batch_size", 32))
        self.latent = int(getattr(args, "gan_latent_dim", 100))
        self.steps = int(getattr(args, "gan_local_steps", 20))
        seed = int(getattr(args, "random_seed", 0))

        x_all, _y, self.idx, self.counts, self.padded_n = _pad_clients(
            local_train, local_num, self.num_clients, self.bs
        )
        # tanh range + channel axis, once, on device
        if x_all.ndim == 3:
            x_all = x_all[..., None]
        self.x_all = x_all * 2.0 - 1.0

        self.G, self.D = MNISTGenerator(self.latent), MNISTDiscriminator()
        key = jax.random.PRNGKey(seed)
        z0 = jnp.zeros((1, self.latent))
        self.g_params = self.G.init(key, z0)
        self.d_params = self.D.init(jax.random.fold_in(key, 1), self.G.apply(self.g_params, z0))
        lr = float(getattr(args, "learning_rate", 2e-4))
        g_tx, d_tx = optax.adam(lr, b1=0.5), optax.adam(lr, b1=0.5)
        self.metrics = MetricsLogger(args)
        self._rng = jax.random.fold_in(key, 2)

        G, D, bs, latent, steps = self.G, self.D, self.bs, self.latent, self.steps

        def bce(logits, target):
            return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, target))

        def local_gan(gp, dp, x, n, rng):
            """Alternating D/G steps on one client's gathered rows; batch i
            slides over the client's REAL rows only (start mod n-bs)."""
            g_opt, d_opt = g_tx.init(gp), d_tx.init(dp)
            span = jnp.maximum(jnp.minimum(n, x.shape[0]) - bs, 1)

            def body(i, carry):
                gp, dp, g_opt, d_opt, rng = carry
                rng, kz1, kz2 = jax.random.split(rng, 3)
                real = jax.lax.dynamic_slice_in_dim(x, (i * bs) % span, bs)

                def d_loss(dp):
                    fake = G.apply(gp, jax.random.normal(kz1, (bs, latent)))
                    lr_ = D.apply(dp, real)
                    lf = D.apply(dp, fake)
                    return bce(lr_, jnp.ones_like(lr_)) + bce(lf, jnp.zeros_like(lf))

                gd = jax.grad(d_loss)(dp)
                du, d_opt = d_tx.update(gd, d_opt, dp)
                dp = optax.apply_updates(dp, du)

                def g_loss(gp):
                    fake = G.apply(gp, jax.random.normal(kz2, (bs, latent)))
                    return bce(D.apply(dp, fake), jnp.ones((bs, 1)))

                gg = jax.grad(g_loss)(gp)
                gu, g_opt = g_tx.update(gg, g_opt, gp)
                return optax.apply_updates(gp, gu), dp, g_opt, d_opt, rng

            gp, dp, _, _, _ = jax.lax.fori_loop(0, steps, body, (gp, dp, g_opt, d_opt, rng))
            return gp, dp

        def per_device(gp, dp, x_all, idx_l, counts_l, rngs_l):
            def one_slot(carry, inp):
                g_acc, d_acc, wsum = carry
                idx_row, n, rng = inp
                x = jnp.take(x_all, idx_row, axis=0)
                gp2, dp2 = local_gan(gp, dp, x, n, rng)
                w = n.astype(jnp.float32)
                g_acc = jax.tree_util.tree_map(lambda a, p: a + w * p, g_acc, gp2)
                d_acc = jax.tree_util.tree_map(lambda a, p: a + w * p, d_acc, dp2)
                return (g_acc, d_acc, wsum + w), 0.0

            zeros_g = jax.tree_util.tree_map(jnp.zeros_like, gp)
            zeros_d = jax.tree_util.tree_map(jnp.zeros_like, dp)
            (g_acc, d_acc, wsum), _ = jax.lax.scan(
                one_slot, (zeros_g, zeros_d, 0.0), (idx_l, counts_l, rngs_l)
            )
            g_acc = jax.lax.psum(g_acc, "client")
            d_acc = jax.lax.psum(d_acc, "client")
            wsum = jnp.maximum(jax.lax.psum(wsum, "client"), 1e-9)
            new_g = jax.tree_util.tree_map(lambda a: a / wsum, g_acc)
            new_d = jax.tree_util.tree_map(lambda a: a / wsum, d_acc)
            return new_g, new_d

        self._round_fn = jax.jit(shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(), P(), P(), P("client"), P("client"), P("client")),
            out_specs=(P(), P()),
            check_vma=False,
        ))

    def train(self) -> Dict[str, Any]:
        from ...core.sampling import client_sampling

        rounds = int(self.args.comm_round)
        per_round = int(self.args.client_num_per_round)
        counts_all = np.asarray(self.counts)
        last: Dict[str, Any] = {}
        for r in range(rounds):
            sampled = client_sampling(r, self.num_clients, per_round)
            ids, counts = _schedule_round(sampled, counts_all, self.n_dev)
            self._rng, sub = jax.random.split(self._rng)
            rngs = jax.random.split(jax.random.fold_in(sub, r), len(ids))
            self.g_params, self.d_params = self._round_fn(
                self.g_params, self.d_params, self.x_all,
                self.idx[jnp.asarray(ids)], jnp.asarray(counts), rngs,
            )
            self._rng, sub = jax.random.split(self._rng)
            fake = self.G.apply(self.g_params, jax.random.normal(sub, (64, self.latent)))
            d_fake = float(jnp.mean(jax.nn.sigmoid(self.D.apply(self.d_params, fake))))
            last = {"round": r, "d_fake_score": round(d_fake, 4)}
            self.metrics.log(last)
        return last


class NASInMeshAPI:
    """Federated DARTS search on the mesh: each slot runs joint (w, alpha)
    search steps on its shard (MiLeNAS-style single-level, matching the sp
    twin), the weighted psum averages BOTH pytrees, and the final genotype is
    derived host-side (reference ``simulation/mpi/fednas`` round protocol)."""

    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ...models.darts import DARTSNetwork, init_alphas
        from .split import _pad_clients

        self.args = args
        (_tn, _ten, _tg, self.test_global, local_num, local_train, _lt,
         self.class_num) = dataset
        self.num_clients = int(args.client_num_in_total)
        self.mesh = _client_mesh(mesh)
        self.n_dev = self.mesh.devices.size
        self.bs = int(getattr(args, "batch_size", 32))
        self.epochs = int(getattr(args, "epochs", 1))
        seed = int(getattr(args, "random_seed", 0))

        self.x_all, self.y_all, self.idx, self.counts, self.padded_n = _pad_clients(
            local_train, local_num, self.num_clients, self.bs
        )
        self.y_all = self.y_all.astype(jnp.int32)

        self.net = model if isinstance(model, DARTSNetwork) else DARTSNetwork(
            num_classes=self.class_num
        )
        self.alphas = init_alphas(seed)
        sample = self.x_all[: self.bs]
        self.params = self.net.init(jax.random.PRNGKey(seed), sample, self.alphas)
        w_tx = optax.sgd(float(getattr(args, "learning_rate", 0.025)), momentum=0.9)
        a_tx = optax.adam(float(getattr(args, "arch_learning_rate", 3e-3)))
        self.metrics = MetricsLogger(args)
        self.eval_history: List[Dict[str, Any]] = []

        net, bs, epochs = self.net, self.bs, self.epochs
        steps_per_epoch = self.padded_n // bs

        def local_search(params, alphas, x, y, n):
            """sp semantics: floor(n/bs) full batches per epoch; steps past a
            client's real batches leave (w, alpha, opts) untouched."""
            w_opt, a_opt = w_tx.init(params), a_tx.init(alphas)
            real_batches = jnp.minimum(n, x.shape[0]) // bs

            def body(i, carry):
                params, alphas, w_opt, a_opt = carry
                s = i % steps_per_epoch
                valid = s < real_batches
                bx = jax.lax.dynamic_slice_in_dim(x, s * bs, bs)
                by = jax.lax.dynamic_slice_in_dim(y, s * bs, bs)

                def loss_fn(p, a):
                    logits = net.apply(p, bx, a)
                    return jnp.mean(
                        optax.softmax_cross_entropy_with_integer_labels(logits, by)
                    )

                gw, ga = jax.grad(loss_fn, argnums=(0, 1))(params, alphas)
                wu, w_opt2 = w_tx.update(gw, w_opt, params)
                au, a_opt2 = a_tx.update(ga, a_opt, alphas)
                sel = lambda new, old: jax.tree_util.tree_map(
                    lambda a_, b_: jnp.where(valid, a_, b_), new, old
                )
                return (sel(optax.apply_updates(params, wu), params),
                        jnp.where(valid, optax.apply_updates(alphas, au), alphas),
                        sel(w_opt2, w_opt), sel(a_opt2, a_opt))

            params, alphas, _, _ = jax.lax.fori_loop(
                0, steps_per_epoch * epochs, body, (params, alphas, w_opt, a_opt)
            )
            return params, alphas

        def per_device(params, alphas, x_all, y_all, idx_l, counts_l):
            def one_slot(carry, inp):
                p_acc, a_acc, wsum = carry
                idx_row, n = inp
                x = jnp.take(x_all, idx_row, axis=0)
                y = jnp.take(y_all, idx_row, axis=0)
                p2, a2 = local_search(params, alphas, x, y, n)
                w = n.astype(jnp.float32)
                p_acc = jax.tree_util.tree_map(lambda a, p: a + w * p, p_acc, p2)
                a_acc = a_acc + w * a2
                return (p_acc, a_acc, wsum + w), 0.0

            zeros_p = jax.tree_util.tree_map(jnp.zeros_like, params)
            (p_acc, a_acc, wsum), _ = jax.lax.scan(
                one_slot, (zeros_p, jnp.zeros_like(alphas), 0.0), (idx_l, counts_l)
            )
            p_acc = jax.lax.psum(p_acc, "client")
            a_acc = jax.lax.psum(a_acc, "client")
            wsum = jnp.maximum(jax.lax.psum(wsum, "client"), 1e-9)
            return (jax.tree_util.tree_map(lambda a: a / wsum, p_acc), a_acc / wsum)

        self._round_fn = jax.jit(shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(), P(), P(), P(), P("client"), P("client")),
            out_specs=(P(), P()),
            check_vma=False,
        ))
        self._infer = jax.jit(lambda p, a, x: net.apply(p, x, a))

    def train(self) -> Dict[str, Any]:
        from ...core.sampling import client_sampling
        from ...models.darts import derive_architecture

        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        counts_all = np.asarray(self.counts)
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            sampled = client_sampling(
                round_idx, self.num_clients, int(self.args.client_num_per_round)
            )
            ids, counts = _schedule_round(sampled, counts_all, self.n_dev)
            self.params, self.alphas = self._round_fn(
                self.params, self.alphas, self.x_all, self.y_all,
                self.idx[jnp.asarray(ids)], jnp.asarray(counts),
            )
            self.metrics.log({"round": round_idx})
            if freq > 0 and (round_idx % freq == 0 or round_idx == comm_round - 1):
                last = self._test_global(round_idx)
        last["genotype"] = derive_architecture(self.alphas)
        logger.info("derived architecture: %s", last["genotype"])
        return last

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        x, y = self.test_global
        correct = total = 0
        for s in range(0, len(y), 256):
            logits = self._infer(self.params, self.alphas, jnp.asarray(x[s:s + 256]))
            correct += int(jnp.sum(jnp.argmax(logits, -1) == jnp.asarray(y[s:s + 256])))
            total += len(y[s:s + 256])
        out = {"round": round_idx, "test_acc": round(correct / max(total, 1), 4)}
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("fednas in-mesh eval: %s", out)
        return out
