"""In-mesh split-computation algorithms: VFL, SplitNN, FedGKT.

The reference runs these three through dedicated MPI programs whose structure
IS communication (``simulation/mpi/classical_vertical_fl/`` partial-logit
exchange, ``mpi/split_nn/SplitNN_api.py`` activation/grad relay,
``mpi/fedgkt/`` feature/logit knowledge transfer, ~2k LoC of rank
choreography).  Here each one compiles into XLA programs over a device mesh,
with the algorithm's defining exchange realized as a mesh collective:

* **VFL** (:class:`VFLInMeshAPI`) — the feature axis is sharded over a
  ``party`` mesh axis; each party's partial logits ``x_k @ w_k`` meet in ONE
  ``psum`` (the guest's logit sum riding ICI), the guest's ``dL/dz`` is
  computed replicated, and each party forms its own weight gradient from its
  local feature shard.  Raw features never cross the party boundary — the
  only tensor on the interconnect is ``[batch, classes]`` logits, the privacy
  property of classical VFL made physical.
* **SplitNN** (:class:`SplitNNInMeshAPI`) — clients are sharded over the
  mesh; each device runs the client-side front and the server-side back with
  the cut-layer activation/gradient exchange expressed as ``jax.vjp`` INSIDE
  the compiled round (the seam a real deployment replaces with transport).
  The reference's strictly sequential client relay becomes parallel relay
  chains (one per device, sequential within) whose halves are
  weight-averaged by a ``psum`` at the round boundary — the split-learning
  analogue of parallel FedAvg over relay groups.
* **FedGKT** (:class:`GKTInMeshAPI`) — per-client edge networks live in an
  HBM-resident stacked parameter table (gather participants / scatter back,
  never aggregated — GKT's defining property); the client phase (edge
  training + feature/logit extraction) is shard_mapped over the client axis,
  and the transfer set arrives at the replicated server tower as sharded
  arrays, not a message queue.

Dispatched from :class:`fedml_tpu.simulation.simulator.SimulatorXLA` for
``federated_optimizer`` in {classical_vertical, split_nn, fedgkt} — the
same config that picks the sp twin picks these on ``backend: XLA``.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...utils.metrics import MetricsLogger
from .fed_sim import shard_map

logger = logging.getLogger(__name__)


def _pad_clients(local_train, local_num, num_clients: int, batch_size: int):
    """Concatenate client shards into HBM arrays + per-client padded index
    rows (the fed_sim._pack_data layout, standalone)."""
    counts = np.array([local_num[i] for i in range(num_clients)], np.int32)
    padded_n = max(batch_size, -(-int(counts.max()) // batch_size) * batch_size)
    xs, ys = [], []
    idx = np.zeros((num_clients, padded_n), np.int32)
    cursor = 0
    for i in range(num_clients):
        xi, yi = local_train[i]
        n = len(yi)
        xs.append(np.asarray(xi, np.float32))
        ys.append(np.asarray(yi))
        if n > 0:
            idx[i, :n] = np.arange(cursor, cursor + n, dtype=np.int32)
            idx[i, n:] = cursor
        cursor += n
    return (jnp.asarray(np.concatenate(xs, 0)), jnp.asarray(np.concatenate(ys, 0)),
            jnp.asarray(idx), counts, padded_n)


# ---------------------------------------------------------------------------
# Vertical FL: feature-sharded party mesh
# ---------------------------------------------------------------------------
class VFLInMeshAPI:
    """Classical vertical FL with the feature axis sharded over the mesh.

    ``vfl_party_num`` stays the LOGICAL party count (who owns which feature
    slice — API parity with the sp twin / reference
    ``simulation/sp/classical_vertical_fl``); physically every logical slice
    is sub-sharded over the mesh's ``party`` axis, which only strengthens
    the isolation: no device ever holds another shard's raw features, and
    the single cross-shard tensor is the psum'd ``[batch, classes]`` logits.
    """

    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        self.args = args
        (_, _, (x_tr, y_tr), (x_te, y_te), *_rest, self.class_num) = dataset
        self.mesh = mesh if mesh is not None else Mesh(np.array(jax.devices()), ("party",))
        n_dev = self.mesh.devices.size
        x_tr = np.asarray(x_tr, np.float32).reshape(len(y_tr), -1)
        x_te = np.asarray(x_te, np.float32).reshape(len(y_te), -1)
        y_tr, y_te = np.asarray(y_tr), np.asarray(y_te)
        if y_tr.ndim > 1:  # multi-hot (NUS-WIDE style) -> dominant concept
            y_tr, y_te = y_tr.argmax(-1), y_te.argmax(-1)
        self.parties = int(getattr(args, "vfl_party_num", 2))
        # pad the feature axis to the mesh size (zero features are inert:
        # their weights receive zero gradient forever)
        f = x_tr.shape[1]
        f_pad = -(-f // n_dev) * n_dev
        if f_pad != f:
            x_tr = np.pad(x_tr, ((0, 0), (0, f_pad - f)))
            x_te = np.pad(x_te, ((0, 0), (0, f_pad - f)))
        shard_x = NamedSharding(self.mesh, P(None, "party"))
        shard_w = NamedSharding(self.mesh, P("party", None))
        self.x_tr = jax.device_put(jnp.asarray(x_tr), shard_x)
        self.x_te = jax.device_put(jnp.asarray(x_te), shard_x)
        self.y_tr = jnp.asarray(y_tr.astype(np.int32))
        self.y_te = jnp.asarray(y_te.astype(np.int32))
        key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.w = jax.device_put(
            0.01 * jax.random.normal(key, (f_pad, self.class_num)), shard_w
        )
        self.b = jnp.zeros((self.class_num,))
        lr = float(getattr(args, "learning_rate", 0.1))
        classes = self.class_num
        self.metrics = MetricsLogger(args)

        def step(w_l, b, x_l, y):
            # each party's partial logits meet in one psum (the guest's sum)
            z = jax.lax.psum(x_l @ w_l, "party") + b
            logp = jax.nn.log_softmax(z)
            loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
            # guest computes dL/dz once, replicated — the gradient message of
            # the reference protocol; each party forms dw from ITS shard only
            dz = (jnp.exp(logp) - jax.nn.one_hot(y, classes)) / y.shape[0]
            dw = x_l.T @ dz
            db = jnp.sum(dz, axis=0)
            return w_l - lr * dw, b - lr * db, loss

        self._step = jax.jit(shard_map(
            step, mesh=self.mesh,
            in_specs=(P("party", None), P(), P(None, "party"), P()),
            out_specs=(P("party", None), P(), P()),
            check_vma=False,
        ))

        def infer(w_l, b, x_l):
            return jax.lax.psum(x_l @ w_l, "party") + b

        self._infer = jax.jit(shard_map(
            infer, mesh=self.mesh,
            in_specs=(P("party", None), P(), P(None, "party")),
            out_specs=P(),
            check_vma=False,
        ))

    def train(self) -> Dict[str, Any]:
        rounds = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last: Dict[str, Any] = {}
        for r in range(rounds):
            self.w, self.b, loss = self._step(self.w, self.b, self.x_tr, self.y_tr)
            if r % freq == 0 or r == rounds - 1:
                z = self._infer(self.w, self.b, self.x_te)
                acc = float(jnp.mean(jnp.argmax(z, 1) == self.y_te))
                last = {"round": r, "test_acc": round(acc, 4),
                        "train_loss": round(float(loss), 4)}
                self.metrics.log(last)
        return last


# ---------------------------------------------------------------------------
# SplitNN: compiled activation/gradient exchange, clients over the mesh
# ---------------------------------------------------------------------------
class SplitNNInMeshAPI:
    """Split learning with the cut-layer exchange compiled into the round.

    Front/back topology and hyperparameters match the sp twin
    (``simulation/sp/split_nn/split_nn_api.py``, reference
    ``simulation/mpi/split_nn/SplitNN_api.py``).  Parallelization: the
    reference relays ONE front sequentially through all clients; here each
    mesh slot runs that relay over ITS scheduled clients inside one compiled
    program (activation up / cut-gradient down via ``jax.vjp`` per batch),
    and the relay chains' (front, back) pairs are sample-weight psum-averaged
    at the round boundary."""

    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ..sp.split_nn.split_nn_api import _Back, _Front

        self.args = args
        (_, _, _tg, (x_te, y_te), local_num, local_train, _lt, self.class_num) = dataset
        self.mesh = mesh if mesh is not None else Mesh(np.array(jax.devices()), ("client",))
        self.n_dev = self.mesh.devices.size
        self.num_clients = int(args.client_num_in_total)
        self.bs = int(getattr(args, "batch_size", 32))
        self.x_te = jnp.asarray(np.asarray(x_te, np.float32))
        self.y_te = jnp.asarray(y_te)
        (self.x_all, self.y_all, self.client_idx, self.counts, self.padded_n
         ) = _pad_clients(local_train, local_num, self.num_clients, self.bs)
        self.front = _Front(int(getattr(args, "split_hidden", 128)))
        self.back = _Back(self.class_num)
        x0 = self.x_all[:1]
        self.front_params = self.front.init(jax.random.PRNGKey(0), x0)
        h0 = self.front.apply(self.front_params, x0)
        self.back_params = self.back.init(jax.random.PRNGKey(999), h0)
        lr = float(getattr(args, "learning_rate", 0.1))
        front, back = self.front, self.back
        bs, padded_n = self.bs, self.padded_n
        n_batches = padded_n // bs
        self.metrics = MetricsLogger(args)

        def split_batch(fp, bp, x, y, m):
            # client forward to the cut layer; vjp IS the exchange seam
            h, client_vjp = jax.vjp(lambda p: front.apply(p, x), fp)

            def server_loss(bp, h):
                logits = back.apply(bp, h)
                logp = jax.nn.log_softmax(logits)
                per = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
                return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)

            loss, (gbp, gh) = jax.value_and_grad(server_loss, argnums=(0, 1))(bp, h)
            (gfp,) = client_vjp(gh)  # cut-layer gradient travels down
            fp = jax.tree_util.tree_map(lambda p, g: p - lr * g, fp, gfp)
            bp = jax.tree_util.tree_map(lambda p, g: p - lr * g, bp, gbp)
            return fp, bp, loss

        def per_device(fp, bp, x_all, y_all, idx_l, counts_l):
            w_dev = jnp.sum(counts_l.astype(jnp.float32))

            def one_client(carry, inp):
                fp, bp = carry
                idx_row, n_i = inp
                x = jnp.take(x_all, idx_row, axis=0)
                y = jnp.take(y_all, idx_row, axis=0)
                mask = (jnp.arange(padded_n) < n_i).astype(jnp.float32)

                def one_batch(c, b_i):
                    fp, bp = c
                    sl = b_i * bs
                    xb = jax.lax.dynamic_slice_in_dim(x, sl, bs)
                    yb = jax.lax.dynamic_slice_in_dim(y, sl, bs)
                    mb = jax.lax.dynamic_slice_in_dim(mask, sl, bs)
                    fp, bp, loss = split_batch(fp, bp, xb, yb, mb)
                    return (fp, bp), loss * jnp.sum(mb)

                (fp, bp), wl = jax.lax.scan(
                    one_batch, (fp, bp), jnp.arange(n_batches, dtype=jnp.int32)
                )
                return (fp, bp), jnp.sum(wl)

            (fp, bp), wl = jax.lax.scan(one_client, (fp, bp), (idx_l, counts_l))
            # weight-averaged merge of the relay chains (weight-0 devices
            # contribute nothing; their unchanged params are masked out)
            wsum = jax.lax.psum(w_dev, "client")
            merge = lambda t: jax.lax.psum(w_dev * t, "client") / jnp.maximum(wsum, 1e-9)
            fp = jax.tree_util.tree_map(merge, fp)
            bp = jax.tree_util.tree_map(merge, bp)
            lsum = jax.lax.psum(jnp.sum(wl), "client")
            return fp, bp, lsum / jnp.maximum(wsum, 1e-9)

        self._round_fn = jax.jit(shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(), P(), P(), P(), P("client"), P("client")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))

    def train(self) -> Dict[str, Any]:
        rounds = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last: Dict[str, Any] = {}
        # all clients participate each round (the reference relay walks the
        # full population), padded to fill the mesh
        c_pad = -(-self.num_clients // self.n_dev) * self.n_dev
        ids = np.resize(np.arange(self.num_clients), c_pad)
        counts = np.where(np.arange(c_pad) < self.num_clients,
                          self.counts[ids], 0).astype(np.int32)
        idx_rows = self.client_idx[jnp.asarray(ids)]
        counts_j = jnp.asarray(counts)
        for r in range(rounds):
            self.front_params, self.back_params, loss = self._round_fn(
                self.front_params, self.back_params, self.x_all, self.y_all,
                idx_rows, counts_j,
            )
            if r % freq == 0 or r == rounds - 1:
                last = self._evaluate(r, float(loss))
        return last

    def _evaluate(self, r: int, loss: float) -> Dict[str, Any]:
        h = self.front.apply(self.front_params, self.x_te)
        logits = self.back.apply(self.back_params, h)
        acc = float(jnp.mean(jnp.argmax(logits, 1) == self.y_te))
        out = {"round": r, "test_acc": round(acc, 4), "train_loss": round(loss, 4)}
        self.metrics.log(out)
        return out


# ---------------------------------------------------------------------------
# FedGKT: sharded edge phase + replicated server tower
# ---------------------------------------------------------------------------
class GKTInMeshAPI:
    """Group knowledge transfer with the client phase shard_mapped over the
    mesh.  Per-client edge params live in a stacked HBM table (gathered for
    the round's participants, scattered back after — never averaged), the
    transfer set (features/logits/labels) is produced sharded over the
    client axis, and the server tower trains replicated on the union.
    Hyperparameters and loss structure match the sp twin
    (``simulation/sp/fedgkt/gkt_api.py``, reference ``simulation/mpi/fedgkt``)."""

    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ...models.gkt import GKTClientNet, GKTServerNet

        self.args = args
        (_tn, _ten, _tg, self.test_global, local_num, local_train, _lt,
         self.class_num) = dataset
        self.mesh = mesh if mesh is not None else Mesh(np.array(jax.devices()), ("client",))
        self.n_dev = self.mesh.devices.size
        self.num_clients = int(args.client_num_in_total)
        self.cpr = int(args.client_num_per_round)
        self.bs = int(getattr(args, "batch_size", 32))
        self.temperature = float(getattr(args, "gkt_temperature", 3.0))
        self.alpha = float(getattr(args, "gkt_alpha", 1.0))
        self.server_epochs = int(getattr(args, "gkt_server_epochs", 1))
        self.epochs = int(getattr(args, "epochs", 1))
        lr = float(getattr(args, "learning_rate", 0.01))
        seed = int(getattr(args, "random_seed", 0))
        (self.x_all, self.y_all, self.client_idx, self.counts, self.padded_n
         ) = _pad_clients(local_train, local_num, self.num_clients, self.bs)

        self.client_net = model if isinstance(model, GKTClientNet) else GKTClientNet(
            num_classes=self.class_num
        )
        self.server_net = GKTServerNet(
            num_classes=self.class_num,
            width=int(getattr(args, "gkt_server_width", 64)),
            blocks=int(getattr(args, "gkt_server_blocks", 3)),
        )
        key = jax.random.PRNGKey(seed)
        sample = self.x_all[: self.bs]
        proto = self.client_net.init(key, sample)
        # stacked per-client edge table: every client starts from the proto
        # (reference model_hub ResNet-8 init), diverges privately forever
        self.edge_table = jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(p, (self.num_clients,) + p.shape), proto
        )
        feats, _ = self.client_net.apply(proto, sample)
        self.feat_shape = feats.shape[1:]
        self.server_params = self.server_net.init(jax.random.fold_in(key, 1), feats)
        # downloaded knowledge: per-client per-row server logits + validity
        self.logit_table = jnp.zeros(
            (self.num_clients, self.padded_n, self.class_num), jnp.float32
        )
        self.has_kd = jnp.zeros((self.num_clients,), jnp.float32)
        self.client_tx = optax.sgd(lr, momentum=0.9)
        self.server_tx = optax.sgd(lr, momentum=0.9)
        self.metrics = MetricsLogger(args)
        self.eval_history = []
        self._build_fns(proto)
        # canonical placements: tables + data mesh-replicated (the client
        # phase shards them per its in_specs); scatter results from mixed
        # dev0/sharded sources are re-placed here every round to keep jit
        # from seeing conflicting committed devices
        self._rep_mesh = lambda t: jax.device_put(
            t, NamedSharding(self.mesh, P())
        )
        self.x_all = self._rep_mesh(self.x_all)
        self.y_all = self._rep_mesh(self.y_all)
        self.client_idx = self._rep_mesh(self.client_idx)
        self.edge_table = self._rep_mesh(self.edge_table)
        self.logit_table = self._rep_mesh(self.logit_table)
        self.has_kd = self._rep_mesh(self.has_kd)

    def _build_fns(self, proto):
        cnet, snet = self.client_net, self.server_net
        ctx, stx = self.client_tx, self.server_tx
        alpha, T = self.alpha, self.temperature
        bs, padded_n = self.bs, self.padded_n
        n_batches = padded_n // bs
        epochs, server_epochs = self.epochs, self.server_epochs

        def _kl(p_logits, q_logits, m):
            p = jax.nn.log_softmax(p_logits / T)
            q = jax.nn.log_softmax(q_logits / T)
            per = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
            return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0) * T**2

        def _ce(logits, y, m):
            per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)

        def client_phase(edge_l, x_all, y_all, idx_l, counts_l, slog_l, haskd_l):
            """Per device: train each of its clients' edge nets, then extract
            the transfer set.  edge_l leaves: [slots, ...]."""

            def one_client(_, inp):
                params, idx_row, n_i, s_log, has_kd = inp
                x = jnp.take(x_all, idx_row, axis=0)
                y = jnp.take(y_all, idx_row, axis=0)
                mask = (jnp.arange(padded_n) < n_i).astype(jnp.float32)
                opt = ctx.init(params)

                def one_batch(c, b_i):
                    params, opt = c
                    sl = (b_i % n_batches) * bs
                    xb = jax.lax.dynamic_slice_in_dim(x, sl, bs)
                    yb = jax.lax.dynamic_slice_in_dim(y, sl, bs)
                    mb = jax.lax.dynamic_slice_in_dim(mask, sl, bs)
                    sb = jax.lax.dynamic_slice_in_dim(s_log, sl, bs)

                    def loss_fn(p):
                        _, logits = cnet.apply(p, xb)
                        return _ce(logits, yb, mb) + alpha * has_kd * _kl(sb, logits, mb)

                    grads = jax.grad(loss_fn)(params)
                    updates, opt = ctx.update(grads, opt, params)
                    return (optax.apply_updates(params, updates), opt), 0.0

                (params, _), _ = jax.lax.scan(
                    one_batch, (params, opt),
                    jnp.arange(n_batches * epochs, dtype=jnp.int32),
                )
                feats, logits = cnet.apply(params, x)  # transfer extraction
                return None, (params, feats, logits, y, mask)

            _, (new_edge, feats, logits, ys, masks) = jax.lax.scan(
                one_client, None, (edge_l, idx_l, counts_l, slog_l, haskd_l)
            )
            return new_edge, feats, logits, ys, masks

        self._client_phase = jax.jit(shard_map(
            client_phase, mesh=self.mesh,
            in_specs=(P("client"), P(), P(), P("client"), P("client"),
                      P("client"), P("client")),
            out_specs=(P("client"), P("client"), P("client"), P("client"),
                       P("client")),
            check_vma=False,
        ))

        def server_phase(sp, feats, c_logits, ys, masks):
            """Replicated tower training on the union of the transfer set
            (client-by-client, batch-by-batch — the sp ordering), then fresh
            knowledge inference for every transfer row."""
            c_pad = feats.shape[0]
            f_flat = feats.reshape((c_pad * n_batches, bs) + feats.shape[2:])
            l_flat = c_logits.reshape((c_pad * n_batches, bs, -1))
            y_flat = ys.reshape((c_pad * n_batches, bs))
            m_flat = masks.reshape((c_pad * n_batches, bs))
            opt = stx.init(sp)

            def one_batch(c, inp):
                sp, opt = c
                fb, lb, yb, mb = inp

                def loss_fn(p):
                    logits = snet.apply(p, fb)
                    return _ce(logits, yb, mb) + alpha * _kl(lb, logits, mb)

                loss, grads = jax.value_and_grad(loss_fn)(sp)
                updates, opt = stx.update(grads, opt, sp)
                return (optax.apply_updates(sp, updates), opt), loss

            def one_epoch(c, _):
                c, losses = jax.lax.scan(one_batch, c, (f_flat, l_flat, y_flat, m_flat))
                return c, losses[-1]

            (sp, _), losses = jax.lax.scan(one_epoch, (sp, opt), None,
                                           length=server_epochs)
            fresh = jax.vmap(lambda f: snet.apply(sp, f))(f_flat)
            fresh = fresh.reshape((c_pad, padded_n, -1))
            return sp, fresh, losses[-1]

        # the transfer set arrives client-sharded; the server tower trains on
        # ONE device (GKT's server is a separate machine — and replicating
        # the tower across the mesh would just run the same sequential-SGD
        # work redundantly on every device).  device_put here IS the
        # "clients upload knowledge" hop; features are small by design.
        dev0 = self.mesh.devices.reshape(-1)[0]
        self._replicate = lambda t: jax.device_put(t, dev0)
        self._server_phase = jax.jit(server_phase)

        def probe_eval(edge_params, sp, x, y):
            feats, _ = cnet.apply(edge_params, x)
            logits = snet.apply(sp, feats)
            return jnp.sum(jnp.argmax(logits, -1) == y)

        self._probe_eval = jax.jit(probe_eval)

    def train(self) -> Dict[str, Any]:
        from ...core.sampling import client_sampling

        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            sampled = np.asarray(client_sampling(
                round_idx, self.num_clients, self.cpr
            ))
            c_pad = -(-len(sampled) // self.n_dev) * self.n_dev
            ids = np.resize(sampled, c_pad)
            real = np.arange(c_pad) < len(sampled)
            counts = np.where(real, self.counts[ids], 0).astype(np.int32)
            idsj = jnp.asarray(ids)
            edge_l = jax.tree_util.tree_map(lambda t: t[idsj], self.edge_table)
            new_edge, feats, logits, ys, masks = self._client_phase(
                edge_l, self.x_all, self.y_all, self.client_idx[idsj],
                jnp.asarray(counts), self.logit_table[idsj],
                self.has_kd[idsj],
            )
            self.server_params, fresh, loss = self._server_phase(
                self.server_params, self._replicate(feats),
                self._replicate(logits), self._replicate(ys),
                self._replicate(masks),
            )
            # scatter: edge params + downloaded knowledge back to the tables
            # (real slots only — a padding dup must not clobber its original)
            upd = jnp.asarray(ids[real])
            sel = jnp.asarray(np.where(real)[0])
            self.edge_table = self._rep_mesh(jax.tree_util.tree_map(
                lambda t, n: t.at[upd].set(n[sel]), self.edge_table, new_edge
            ))
            self.logit_table = self._rep_mesh(
                self.logit_table.at[upd].set(self._rep_mesh(fresh)[sel])
            )
            self.has_kd = self._rep_mesh(self.has_kd.at[upd].set(1.0))
            self.metrics.log({"round": round_idx, "server_loss": float(loss)})
            if round_idx % freq == 0 or round_idx == comm_round - 1:
                last = self._test_global(round_idx, int(sampled[0]))
        return last

    def _test_global(self, round_idx: int, probe_cid: int) -> Dict[str, Any]:
        x, y = self.test_global
        # probe edge params join the server tower on its device
        probe = self._replicate(
            jax.tree_util.tree_map(lambda t: t[probe_cid], self.edge_table)
        )
        correct = total = 0
        for s in range(0, len(y), 256):
            e = min(s + 256, len(y))
            correct += int(self._probe_eval(
                probe, self.server_params,
                jnp.asarray(np.asarray(x[s:e], np.float32)), jnp.asarray(y[s:e]),
            ))
            total += e - s
        out = {"round": round_idx, "test_acc": round(correct / max(total, 1), 4)}
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("gkt in-mesh eval: %s", out)
        return out
