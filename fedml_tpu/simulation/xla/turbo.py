"""In-mesh Turbo-Aggregate: the multi-group circular secure aggregation
(So et al.; reference ``simulation/sp/turboaggregate``, 519 LoC) compiled
into the round program.

Clients train the global model exactly as FedAvg; the AGGREGATION walks a
ring of L client groups — group g's weighted partial sum is masked with an
additive mask m_g and the previous group's m_{g-1} is removed, so every
intermediate the "server" sees is masked and the masks telescope away only
once the full ring has been traversed.  On the mesh this becomes: per-slot
training (scan), a one-hot(group) contraction + psum producing the L group
sums, and a trace-time ring walk adding/removing the per-group masks — the
whole protocol, training included, is ONE XLA program.  The masks cancel
exactly by construction, so the round output equals weighted FedAvg (the
equivalence test pins it against the sp twin); the MPC-grade finite-field
variant of the same masking lives in core/mpc/secagg.py.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ...ml.engine.train import build_local_train, init_variables
from ...utils.metrics import MetricsLogger
from .fed_sim import shard_map

logger = logging.getLogger(__name__)


class TurboAggregateInMeshAPI:
    def __init__(self, args, device, dataset, model=None, mesh: Mesh = None):
        from ...ml.trainer.trainer_creator import loss_kind_for_dataset
        from .split import _pad_clients

        self.args = args
        (_tn, _ten, _tg, self.test_global, local_num, local_train, _lt,
         self.class_num) = dataset
        self.module = model
        self.num_clients = int(args.client_num_in_total)
        self.cpr = int(args.client_num_per_round)
        if mesh is None:
            from ...parallel.mesh import create_fl_mesh

            mesh = create_fl_mesh()
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.bs = int(getattr(args, "batch_size", 32))
        seed = int(getattr(args, "random_seed", 0))
        # effective group count is capped by the cohort size — this also
        # keeps the per-round mask-key chain identical to the sp twin's
        # (ta_api.py splits L+1 keys with L = min(group_num, cohort))
        self.group_num = min(int(getattr(args, "ta_group_num", 2)), self.cpr)

        self.x_all, self.y_all, self.idx, self.counts, self.padded_n = _pad_clients(
            local_train, local_num, self.num_clients, self.bs
        )
        self.variables = init_variables(
            model, jnp.asarray(self.x_all[:1], jnp.float32), seed=seed
        )
        # same mask-key chain as the sp twin (ta_api.py): the masks cancel,
        # but sharing the chain keeps the wire-visible intermediates
        # reproducible across backends
        self._mask_key = jax.random.PRNGKey(seed + 404)

        loss_kind = loss_kind_for_dataset(str(getattr(args, "dataset", "")).lower())
        local_train_fn = build_local_train(
            model, args, self.bs, self.padded_n, loss=loss_kind
        )
        G = self.group_num

        def per_device(variables, x_all, y_all, idx_l, counts_l, gids_l, rngs_l,
                       mask_keys):
            def one_slot(carry, inp):
                gacc, gw, lsum = carry
                idx_row, n_i, gid, rng = inp
                x = jnp.take(x_all, idx_row, axis=0)
                y = jnp.take(y_all, idx_row, axis=0)
                result = local_train_fn(variables, x, y, n_i, rng)
                w = n_i.astype(jnp.float32)
                hot = jax.nn.one_hot(gid, G) * w
                gacc = jax.tree_util.tree_map(
                    lambda a, p: a + hot.reshape((G,) + (1,) * p.ndim)
                    * p.astype(jnp.float32)[None, ...],
                    gacc, result.variables,
                )
                return (gacc, gw + hot, lsum + result.loss * w), 0.0

            zeros = jax.tree_util.tree_map(
                lambda v: jnp.zeros((G,) + v.shape, jnp.float32), variables
            )
            (gacc, gw, lsum), _ = jax.lax.scan(
                one_slot, (zeros, jnp.zeros(G), 0.0),
                (idx_l, counts_l, gids_l, rngs_l),
            )
            gacc = jax.lax.psum(gacc, "client")
            gw = jax.lax.psum(gw, "client")
            lsum = jax.lax.psum(lsum, "client")
            total = jnp.maximum(jnp.sum(gw), 1e-9)

            # the ring walk: group g contributes (partial_g + m_g - m_{g-1});
            # the final unmask removes m_{G-1}.  Masks come from the sp
            # twin's OWN derivation (_mask_like) so the wire-visible
            # intermediates are bit-identical across backends (trace-time
            # loop: G is small and static)
            from ..sp.turboaggregate.ta_api import _mask_like as mask_for

            proto = jax.tree_util.tree_map(lambda a: a[0], gacc)
            running = jax.tree_util.tree_map(jnp.zeros_like, proto)
            prev_mask = None
            for g in range(G):
                group_scaled = jax.tree_util.tree_map(
                    lambda a: a[g] / total, gacc
                )
                mask = mask_for(proto, mask_keys[g])
                masked = jax.tree_util.tree_map(jnp.add, group_scaled, mask)
                if prev_mask is not None:
                    masked = jax.tree_util.tree_map(jnp.subtract, masked, prev_mask)
                running = jax.tree_util.tree_map(jnp.add, running, masked)
                prev_mask = mask
            agg = jax.tree_util.tree_map(jnp.subtract, running, prev_mask)
            return agg, lsum / total

        self._round_fn = jax.jit(shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), P(), P(), P("client"), P("client"), P("client"),
                      P("client"), P()),
            out_specs=(P(), P()),
            check_vma=False,
        ))
        from ...core.schedule import SeqTrainScheduler

        self._scheduler = SeqTrainScheduler(self.n_dev)
        from ...ml.aggregator.aggregator_creator import create_server_aggregator

        self.aggregator = create_server_aggregator(model, args)
        self.aggregator.set_model_params(self.variables)
        self.metrics = MetricsLogger(args)
        self.eval_history: List[Dict[str, Any]] = []
        self._base_key = jax.random.PRNGKey(seed)

    def train(self) -> Dict[str, Any]:
        from ...core.sampling import client_sampling

        comm_round = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        counts_all = np.asarray(self.counts)
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            sampled = client_sampling(round_idx, self.num_clients, self.cpr)
            # groups by SAMPLED POSITION (sp twin: array_split over the
            # w_locals order), carried through the slot scheduler as gids
            L = min(self.group_num, len(sampled))
            pos_group = np.zeros(len(sampled), np.int32)
            for g, members in enumerate(np.array_split(np.arange(len(sampled)), L)):
                pos_group[members] = g
            sizes = [int(counts_all[int(c)]) for c in sampled]
            ids2d, mask2d, _ = self._scheduler.schedule(sampled, sizes)
            ids = ids2d.reshape(-1).astype(np.int64)
            cnt = np.where(mask2d.reshape(-1) > 0, counts_all[ids], 0).astype(np.int32)
            # slot -> group id via the client's position in the sampled list;
            # PADDED slots carry id 0 (possibly unsampled) with weight 0 —
            # any group is inert for them, so default to group 0
            pos_of = {int(c): i for i, c in enumerate(sampled)}
            gids = np.array(
                [pos_group[pos_of[int(c)]] if int(c) in pos_of else 0 for c in ids],
                np.int32,
            )
            rk = jax.random.fold_in(self._base_key, round_idx)
            rngs = jax.vmap(lambda c: jax.random.fold_in(rk, c))(jnp.asarray(ids))
            self._mask_key, *gkeys = jax.random.split(self._mask_key, self.group_num + 1)
            new_global, mean_loss = self._round_fn(
                self.variables, self.x_all, self.y_all,
                self.idx[jnp.asarray(ids)], jnp.asarray(cnt),
                jnp.asarray(gids), rngs, jnp.stack(gkeys),
            )
            self.variables = self.aggregator.on_after_aggregation(new_global)
            self.aggregator.set_model_params(self.variables)
            self.metrics.log({"round": round_idx, "train_loss": float(mean_loss)})
            if freq > 0 and (round_idx % freq == 0 or round_idx == comm_round - 1):
                last = self._test_global(round_idx)
        return last

    def _test_global(self, round_idx: int) -> Dict[str, Any]:
        stats = self.aggregator.test(self.test_global, None, self.args)
        out = {
            "round": round_idx,
            "test_acc": round(stats["test_correct"] / stats["test_total"], 4),
            "test_loss": round(stats["test_loss"] / stats["test_total"], 4),
        }
        self.eval_history.append(out)
        self.metrics.log(out)
        logger.info("turbo-aggregate in-mesh eval: %s", out)
        return out
