"""Multi-process simulation: the reference's MPI rank plane, process-real.

Parity with reference ``simulation/mpi/fedavg/`` (mpi4py ranks: rank 0
aggregates, workers train their share of each round's clients and reduce
through ``MPI.COMM_WORLD``): here each rank is an OS PROCESS joined through
the host-plane :class:`~fedml_tpu.core.distributed.collective.ProcessGroup`
(TCP star collectives — the transport role torch.distributed/mpi4py play),
and the per-client local training inside each rank is the same compiled
trainer the sp loop uses.

This is the multi-PROCESS counterpart of the in-mesh simulator: Parrot-XLA
(``simulation/xla``) is the blessed TPU path (ranks -> mesh axis, allreduce
-> psum over ICI, zero processes); this module exists for deployments that
genuinely need one process per accelerator host (the reference's
``mpirun -np N`` workflow) — each process trains on ITS devices and only
model-sized blobs ride the host plane, once per round.

Determinism contract: every rank derives the same per-round client sample
(``core/sampling.client_sampling``), takes the strided slice
``sampled[rank::world]``, and the weighted allreduce-mean reproduces the
single-process FedAvg aggregate exactly (tested in
tests/test_mpi_proc.py::test_matches_single_process).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from ...core.distributed.collective import ProcessGroup
from ...core.sampling import client_sampling
from ...utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


class MPIProcessSimulator:
    """One rank of the multi-process round.  ``args`` needs
    ``proc_rank_in_silo``-style fields: ``mpi_rank``, ``mpi_world_size``,
    ``pg_master_address``/``pg_master_port`` (rank 0 hosts the hub)."""

    def __init__(self, args, dataset, model, client_trainer=None):
        self.args = args
        (
            self.train_num, _test_num, train_global, self.test_global,
            self.local_num_dict, self.local_train_dict, _lt, self.class_num,
        ) = dataset
        self.rank = int(getattr(args, "mpi_rank", 0))
        self.world = int(getattr(args, "mpi_world_size", 1))
        # honest surface: this backend implements the weighted-mean family
        # only (FedAvg + the engine's proximal hook); the algorithm zoo
        # (incl. FedSGD, whose server averages GRADIENTS, not parameters)
        # and the attack/defense matrix ride sp or the in-mesh simulator
        opt = str(getattr(args, "federated_optimizer", "FedAvg")).lower()
        if opt not in ("fedavg", "fedprox"):
            raise NotImplementedError(
                f"backend MPI_PROC supports FedAvg/FedProx, not {opt!r}; "
                "use backend 'sp' or 'XLA' for the algorithm zoo"
            )
        from ...core.security.fedml_attacker import FedMLAttacker
        from ...core.security.fedml_defender import FedMLDefender

        if (FedMLAttacker.get_instance().is_attack_enabled()
                or FedMLDefender.get_instance().is_defense_enabled()):
            raise NotImplementedError(
                "backend MPI_PROC has no attack/defense hooks; use 'sp' or 'XLA'"
            )
        addr = (str(getattr(args, "pg_master_address", "127.0.0.1")),
                int(getattr(args, "pg_master_port", 29600)))
        token = str(getattr(args, "pg_token", None)
                    or f"{getattr(args, 'run_id', '0')}-mpi")
        self.pg = ProcessGroup(
            self.rank, self.world, addr=addr, token=token,
            timeout=float(getattr(args, "pg_timeout", 60.0)),
            op_timeout=float(getattr(args, "pg_op_timeout", 1800.0)),
        )
        if client_trainer is None:
            from ...ml.trainer.trainer_creator import create_model_trainer

            client_trainer = create_model_trainer(model, args)
        self.trainer = client_trainer
        if self.rank == 0 and self.trainer.get_model_params() is None:
            # rank 0 owns the round-0 init it broadcasts (reference: the MPI
            # server process initializes the global model)
            import jax.numpy as jnp

            from ...ml.engine.train import init_variables

            self.trainer.set_model_params(init_variables(
                model, jnp.asarray(train_global[0][:1]),
                seed=int(getattr(args, "random_seed", 0)),
            ))
        from ...ml.aggregator.aggregator_creator import create_server_aggregator

        self.aggregator = create_server_aggregator(model, args)
        self.metrics = MetricsLogger(args)

    def train(self) -> Dict[str, Any]:
        args = self.args
        comm_round = int(args.comm_round)
        cpr = int(args.client_num_per_round)
        n_total = int(args.client_num_in_total)
        freq = int(getattr(args, "frequency_of_the_test", 10))
        # rank 0's init is everyone's round-0 model (reference: server
        # broadcasts the global model at round start)
        params = self.pg.broadcast(
            self.trainer.get_model_params() if self.rank == 0 else None
        )
        last: Dict[str, Any] = {}
        for round_idx in range(comm_round):
            # stays on the uniform client_sampling seam (NOT a per-rank
            # PopulationManager): every rank must derive the identical
            # schedule from round_idx alone, and a state-driven policy's
            # rank-local registry would diverge across ranks
            sampled = client_sampling(round_idx, n_total, cpr)
            mine = [int(c) for c in sampled[self.rank :: self.world]]
            acc_tree = None
            n_sum = 0.0
            for cid in mine:
                x, y = self.local_train_dict[cid]
                n_i = int(self.local_num_dict[cid])
                if n_i <= 0:
                    continue
                self.trainer.set_model_params(params)
                self.trainer.set_id(cid)
                self.trainer.round_idx = round_idx
                # the full ClientTrainer hook contract (local DP noise lives
                # in on_after_local_training — skipping it would silently
                # aggregate un-noised updates with DP reported as on)
                self.trainer.on_before_local_training((x, y), None, args)
                self.trainer.train((x, y), None, args)
                self.trainer.on_after_local_training((x, y), None, args)
                w_i = self.trainer.get_model_params()
                w_i = jax.tree_util.tree_map(
                    lambda t: np.asarray(t, np.float32) * n_i, w_i
                )
                acc_tree = w_i if acc_tree is None else jax.tree_util.tree_map(
                    np.add, acc_tree, w_i
                )
                n_sum += n_i
            if acc_tree is None:  # more ranks than sampled clients this round
                local_mean = jax.tree_util.tree_map(
                    lambda t: np.zeros_like(np.asarray(t, np.float32)), params
                )
            else:
                local_mean = jax.tree_util.tree_map(
                    lambda t: t / n_sum, acc_tree
                )
            # every rank learns the round's total weight first (same value
            # everywhere, so the branch below stays collectively consistent);
            # a fully-empty round keeps the previous model instead of letting
            # the zero-weight mean replace it with zeros
            w_tot = float(self.pg.allreduce_sum(np.asarray(n_sum, np.float64)))
            if w_tot > 0:
                # the "MPI reduce": one weighted allreduce-mean on the host plane
                params = self.pg.allreduce_mean(local_mean, weight=n_sum)
                params = self._central_dp(params, round_idx)
            if self.rank == 0 and freq > 0 and (
                round_idx % freq == 0 or round_idx == comm_round - 1
            ):
                self.aggregator.set_model_params(params)
                stats = self.aggregator.test(self.test_global, None, args)
                last = {
                    "round": round_idx,
                    "test_acc": round(stats["test_correct"] / stats["test_total"], 4),
                    "test_loss": round(stats["test_loss"] / stats["test_total"], 4),
                }
                self.metrics.log(last)
                logger.info("mpi_proc eval: %s", last)
        self.trainer.set_model_params(params)
        self.pg.barrier()
        self.pg.close()
        return last

    def _central_dp(self, params, round_idx: int):
        """Central DP on the aggregate: rank 0 noises, then rebroadcasts so
        every rank carries the SAME noised global (per-rank noise would
        diverge the replicas)."""
        from ...core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy

        dp = FedMLDifferentialPrivacy.get_instance()
        if not dp.is_global_dp_enabled():
            return params
        if self.rank == 0:
            params = jax.tree_util.tree_map(np.asarray, dp.add_global_noise(params))
        return self.pg.broadcast(params if self.rank == 0 else None)

    def run(self) -> Dict[str, Any]:
        return self.train()


def _rank_entry(cfg: Dict[str, Any], rank: int, world: int, port: int, q,
                joined) -> None:
    """Child-process entry: rebuild args/data/model from the config dict
    (spawn-safe) and run one rank.  Honors FEDML_FORCE_CPU=1 (test harness:
    the axon sitecustomize would otherwise init the TPU tunnel per child).
    ``joined`` (mp.Event) is set once this rank's ProcessGroup rendezvous
    succeeded — the parent's retry logic keys on it."""
    import os

    if os.environ.get("FEDML_FORCE_CPU") == "1":
        os.environ["JAX_PLATFORMS"] = "cpu"
        from ...utils.platform import force_cpu_backend

        force_cpu_backend()
    import fedml_tpu
    from ...arguments import Arguments

    args = fedml_tpu.init(Arguments.from_dict(cfg).validate(),
                          should_init_logs=False)
    args.mpi_rank = rank
    args.mpi_world_size = world
    args.pg_master_port = port
    dataset, out_dim = fedml_tpu.data.load(args)
    model = fedml_tpu.models.create(args, out_dim)
    sim = MPIProcessSimulator(args, dataset, model)  # PG joins in here
    joined.set()
    metrics = sim.train()
    q.put((rank, metrics))


class _RanksDiedError(RuntimeError):
    def __init__(self, msg: str, rendezvous_done: bool):
        super().__init__(msg)
        self.rendezvous_done = rendezvous_done


def run_mpi_simulation(config: Dict[str, Any], world_size: int, port: int = 0,
                       deadline_s: float = 3600.0,
                       retries: int = 2) -> Dict[str, Any]:
    """The ``mpirun -np N`` replacement: spawn ``world_size`` rank processes
    from one nested config dict and return rank 0's final metrics.

    ``deadline_s`` bounds the whole run (size it to the job — non-toy models
    pay per-rank XLA compiles); per-collective timeouts come from the
    config's ``pg_timeout``/``pg_op_timeout``.  Auto-picked ports
    (``port=0``) are probed then released, which is inherently racy against
    other processes on the host — a failed rendezvous retries on a fresh
    port up to ``retries`` times; pass an explicit reserved ``port`` for
    deterministic placement."""
    for attempt in range(int(retries) + 1):
        try:
            return _run_once(config, world_size, port, deadline_s)
        except _RanksDiedError as e:
            # only a crash BEFORE every rank finished rendezvous smells like
            # a port race; a world that died mid-training is a real failure —
            # re-spawning it would triple time-to-failure and bury the
            # actual traceback
            if attempt == retries or port != 0 or e.rendezvous_done:
                raise
            logger.warning("mpi ranks died during rendezvous (possible port "
                           "race); retrying on a fresh port")
    raise AssertionError("unreachable")


def _run_once(config: Dict[str, Any], world_size: int, port: int,
              deadline_s: float) -> Dict[str, Any]:
    import multiprocessing as mp
    import queue as _queue
    import socket
    import time

    if port == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    joined = [ctx.Event() for _ in range(world_size)]
    procs = [
        ctx.Process(target=_rank_entry,
                    args=(config, r, world_size, port, q, joined[r]))
        for r in range(world_size)
    ]
    for p in procs:
        p.start()
    results: Dict[int, Any] = {}
    deadline = time.time() + float(deadline_s)
    try:
        while len(results) < world_size:
            try:
                rank, metrics = q.get(timeout=5)
                results[rank] = metrics
            except _queue.Empty:
                dead = [p.exitcode for p in procs
                        if not p.is_alive() and p.exitcode not in (0, None)]
                if dead:
                    # fail FAST on a crashed rank instead of starving on the
                    # queue until the deadline
                    raise _RanksDiedError(
                        f"mpi rank process(es) died: {dead}",
                        rendezvous_done=all(e.is_set() for e in joined),
                    )
                if time.time() > deadline:
                    raise TimeoutError("mpi simulation timed out")
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.terminate()
    return results.get(0, {})
