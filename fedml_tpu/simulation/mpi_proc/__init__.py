from .mpi_sim import MPIProcessSimulator, run_mpi_simulation

__all__ = ["MPIProcessSimulator", "run_mpi_simulation"]
