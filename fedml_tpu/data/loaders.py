"""Parsers for locally-cached real dataset files (no downloads — zero egress).

Covers the on-disk formats the reference's loaders consume
(``data/MNIST/data_loader.py`` LEAF json, ``data/cifar10/…`` python pickle
batches, idx-ubyte) so that if a user mounts real data under
``data_cache_dir`` the pipelines train on it transparently.
"""

from __future__ import annotations

import gzip
import json
import os
import pickle
import struct
from typing import Optional, Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(root: str, *names: str) -> Optional[str]:
    for dirpath, _, files in os.walk(root):
        for n in names:
            if n in files:
                return os.path.join(dirpath, n)
            for f in files:
                if f == n + ".gz":
                    return os.path.join(dirpath, f)
    return None


def load_mnist_idx(root: str) -> Optional[Arrays]:
    paths = [
        _find(root, "train-images-idx3-ubyte"),
        _find(root, "train-labels-idx1-ubyte"),
        _find(root, "t10k-images-idx3-ubyte"),
        _find(root, "t10k-labels-idx1-ubyte"),
    ]
    if any(p is None for p in paths):  # partial cache -> synthetic fallback
        return None
    xt = _read_idx(paths[0]).astype(np.float32) / 255.0
    yt = _read_idx(paths[1]).astype(np.int32)
    xe = _read_idx(paths[2]).astype(np.float32) / 255.0
    ye = _read_idx(paths[3]).astype(np.int32)
    return xt[..., None], yt, xe[..., None], ye


def load_leaf_json(root: str) -> Optional[Arrays]:
    """LEAF format: train/*.json + test/*.json with users/user_data."""
    tr_dir, te_dir = os.path.join(root, "train"), os.path.join(root, "test")
    if not (os.path.isdir(tr_dir) and os.path.isdir(te_dir)):
        return None

    def _collect(d):
        xs, ys = [], []
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".json"):
                continue
            with open(os.path.join(d, fn)) as f:
                blob = json.load(f)
            for u in blob.get("users", []):
                ud = blob["user_data"][u]
                xs.append(np.asarray(ud["x"], dtype=np.float32))
                ys.append(np.asarray(ud["y"], dtype=np.int32))
        if not xs:
            return None
        return np.concatenate(xs, 0), np.concatenate(ys, 0)

    tr = _collect(tr_dir)
    te = _collect(te_dir)
    if tr is None or te is None:
        return None
    xt, yt = tr
    xe, ye = te
    if xt.ndim == 2 and xt.shape[1] == 784:
        xt = xt.reshape(-1, 28, 28, 1)
        xe = xe.reshape(-1, 28, 28, 1)
    return xt, yt, xe, ye


def load_cifar_pickle(root: str, coarse100: bool = False) -> Optional[Arrays]:
    batches = []
    test = None
    for dirpath, _, files in os.walk(root):
        for f in files:
            if f.startswith("data_batch") or f in ("train",):
                batches.append(os.path.join(dirpath, f))
            elif f in ("test_batch", "test"):
                test = os.path.join(dirpath, f)
    if not batches or test is None:
        return None

    def _load(path):
        with open(path, "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        key = b"fine_labels" if b"fine_labels" in d else b"labels"
        y = np.asarray(d[key], dtype=np.int32)
        return x, y

    xs, ys = zip(*[_load(b) for b in sorted(batches)])
    xt, yt = np.concatenate(xs), np.concatenate(ys)
    xe, ye = _load(test)
    return xt, yt, xe, ye


def load_image_folder(root: str, size: int = 32) -> Optional[Arrays]:
    """ImageFolder layout (CINIC-10 release format): ``{train,test}/<class>/
    *.png`` — class = sorted subdirectory index.  Needs Pillow."""
    try:
        from PIL import Image
    except ImportError:  # pragma: no cover - Pillow is in the base image
        return None

    def _split(split_dir):
        if not os.path.isdir(split_dir):
            return None
        classes = sorted(
            d for d in os.listdir(split_dir)
            if os.path.isdir(os.path.join(split_dir, d))
        )
        if not classes:
            return None
        xs, ys = [], []
        for ci, cname in enumerate(classes):
            cdir = os.path.join(split_dir, cname)
            for f in sorted(os.listdir(cdir)):
                if not f.lower().endswith((".png", ".jpg", ".jpeg")):
                    continue
                img = Image.open(os.path.join(cdir, f)).convert("RGB")
                if img.size != (size, size):
                    img = img.resize((size, size))
                xs.append(np.asarray(img, np.float32) / 255.0)
                ys.append(ci)
        if not xs:
            return None
        return np.stack(xs), np.asarray(ys, np.int32)

    train = _split(os.path.join(root, "train"))
    test = (_split(os.path.join(root, "test")) or _split(os.path.join(root, "valid"))
            or _split(os.path.join(root, "val")))
    if train is None or test is None:
        return None
    return train[0], train[1], test[0], test[1]


def load_csv_labeled(root: str) -> Optional[Arrays]:
    """Tabular CSV parser (UCI / lending_club-style files, reference
    ``data/data_loader.py`` tabular branches): ``train.csv`` (+ optional
    ``test.csv``, else a 80/20 tail split).  The label column is the one
    named 'label'/'target'/'y' in the header, else the LAST column; features
    must be numeric."""
    train_path = _find(root, "train.csv")
    if train_path is None:
        return None

    def _parse(path):
        with open(path) as f:
            header = f.readline().strip().split(",")
        names = [h.strip().lower() for h in header]
        has_header = not all(_is_float(h) for h in names)
        data = np.genfromtxt(path, delimiter=",", skip_header=1 if has_header else 0,
                             dtype=np.float64)
        if data.ndim == 1:
            data = data[None, :]
        label_col = len(names) - 1
        if has_header:
            for cand in ("label", "target", "y"):
                if cand in names:
                    label_col = names.index(cand)
                    break
        y = data[:, label_col].astype(np.int32)
        x = np.delete(data, label_col, axis=1).astype(np.float32)
        return x, y

    xt, yt = _parse(train_path)
    test_path = _find(root, "test.csv")
    if test_path is not None:
        xe, ye = _parse(test_path)
    else:
        # seeded shuffle before the 80/20 split: exported CSVs are often
        # label-sorted, and an unshuffled tail would be single-class
        perm = np.random.RandomState(0).permutation(len(yt))
        xt, yt = xt[perm], yt[perm]
        cut = max(int(len(yt) * 0.8), 1)
        xe, ye = xt[cut:], yt[cut:]
        xt, yt = xt[:cut], yt[:cut]
        if len(ye) == 0:
            xe, ye = xt, yt  # degenerate tiny file: eval on train
    return xt, yt, xe, ye


def _is_float(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False


def try_load_real(name: str, cache_dir: str) -> Optional[Arrays]:
    if not cache_dir or not os.path.isdir(cache_dir):
        return None
    sub = os.path.join(cache_dir, name)
    roots = [sub, cache_dir]
    for root in roots:
        if not os.path.isdir(root):
            continue
        if name in ("mnist", "fashionmnist"):
            out = load_mnist_idx(root) or load_leaf_json(root)
        elif name == "femnist":
            out = load_leaf_json(root)
        elif name == "cinic10":
            out = load_image_folder(root) or load_cifar_pickle(root)
        elif name.startswith("cifar") or name == "fed_cifar100":
            out = load_cifar_pickle(root, coarse100="100" in name)
        elif name in ("shakespeare", "fed_shakespeare", "stackoverflow_nwp", "stackoverflow_lr"):
            out = load_leaf_json(root)
        elif name in ("uci", "lending_club"):
            out = load_csv_labeled(root)
        elif name in ("imagenet", "ilsvrc2012", "tiny_imagenet"):
            out = load_imagenet_folder(root)
        elif name in ("gld23k", "gld160k", "landmarks"):
            out = load_landmarks_csv(root)
        elif name in ("nuswide", "nus_wide"):
            out = load_nuswide(root)
        elif name == "fets2021":
            out = load_fets_nifti(root)
        else:
            out = None
        if out is not None:
            return out
    return None


# -- ImageNet / ILSVRC2012 ---------------------------------------------------


def load_imagenet_folder(root: str, size: int = 32) -> Optional[Arrays]:
    """ImageNet/ILSVRC2012 directory layout (reference
    ``data/ImageNet/datasets.py:83-106``): ``train/<wnid>/*.JPEG`` +
    ``val/<wnid>/*.JPEG`` (torchvision-foldered val).  Same ImageFolder
    traversal as CINIC-10; class index = sorted wnid order.  Images are
    resized to ``size`` (downsampled-ImageNet style) for TPU-static shapes."""
    return load_image_folder(root, size=size)


# -- Google Landmarks (gld23k / gld160k) ------------------------------------


def load_landmarks_csv(root: str, size: int = 32) -> Optional[Arrays]:
    """Google Landmarks federated split (reference
    ``data/Landmarks/data_loader.py:123-150``): mapping CSVs with
    ``user_id,image_id,class`` columns + an image directory.  Train CSV is
    the first of ``*train*.csv`` / ``data_user.csv``; test is ``*test*.csv``;
    images are searched as ``<image_id>.jpg`` under ``images/``, ``train/``,
    or the root.  Needs Pillow."""
    try:
        from PIL import Image
    except ImportError:  # pragma: no cover
        return None
    import csv as _csv
    import glob as _glob

    def _find_csv(*pats):
        for p in pats:
            hits = sorted(_glob.glob(os.path.join(root, p)))
            if hits:
                return hits[0]
        return None

    train_csv = _find_csv("*train*.csv", "data_user.csv")
    test_csv = _find_csv("*test*.csv")
    if train_csv is None or test_csv is None:
        return None
    img_dirs = [os.path.join(root, d) for d in ("images", "train", "")]

    def _load_split(path):
        xs, ys = [], []
        with open(path, newline="") as f:
            rows = list(_csv.DictReader(f))
        if not rows or not {"image_id", "class"} <= set(rows[0]):
            return None
        for row in rows:
            fname = row["image_id"] + ".jpg"
            for d in img_dirs:
                p = os.path.join(d, fname)
                if os.path.isfile(p):
                    img = Image.open(p).convert("RGB")
                    if img.size != (size, size):
                        img = img.resize((size, size))
                    xs.append(np.asarray(img, np.float32) / 255.0)
                    ys.append(int(row["class"]))
                    break
        if not xs:
            return None
        return np.stack(xs), np.asarray(ys, np.int32)

    train, test = _load_split(train_csv), _load_split(test_csv)
    if train is None or test is None:
        return None
    return train[0], train[1], test[0], test[1]


# -- NUS-WIDE (multi-label; the reference's vertical-FL dataset) ------------


def load_nuswide(root: str, top_k: int = 5) -> Optional[Arrays]:
    """NUS-WIDE low-level-features + multi-label groundtruth (reference
    ``data/NUS_WIDE/nus_wide_dataset.py:8-60`` layout):
    ``Groundtruth/TrainTestLabels/Labels_<name>_<Train|Test>.txt`` (one 0/1
    per line) and ``Low_Level_Features/*_<Train|Test>_*.dat`` (whitespace-
    separated floats per line, concatenated feature blocks).  A full mount
    has 81 concept files; like the reference's ``get_top_k_labels`` the
    ``top_k`` most frequent (by train positives) are kept so label width
    matches the registered spec.  Returns multi-hot y [N, top_k]."""
    import glob as _glob

    lab_dir = os.path.join(root, "Groundtruth", "TrainTestLabels")
    feat_dir = os.path.join(root, "Low_Level_Features")
    if not (os.path.isdir(lab_dir) and os.path.isdir(feat_dir)):
        return None
    names = sorted(
        os.path.basename(p)[len("Labels_"):-len("_Train.txt")]
        for p in _glob.glob(os.path.join(lab_dir, "Labels_*_Train.txt"))
    )
    if not names:
        return None
    if len(names) > top_k:
        counts = {}
        for nm in names:
            try:
                counts[nm] = float(
                    np.loadtxt(os.path.join(lab_dir, f"Labels_{nm}_Train.txt")).sum()
                )
            except (OSError, ValueError):
                counts[nm] = -1.0
        names = sorted(sorted(counts, key=counts.get, reverse=True)[:top_k])

    def _labels(dtype):
        cols = []
        for nm in names:
            p = os.path.join(lab_dir, f"Labels_{nm}_{dtype}.txt")
            if not os.path.isfile(p):
                return None
            cols.append(np.loadtxt(p, dtype=np.float32).reshape(-1))
        return np.stack(cols, axis=1)

    def _feats(dtype):
        blocks = []
        for p in sorted(_glob.glob(os.path.join(feat_dir, f"*_{dtype}_*.dat"))):
            blocks.append(np.loadtxt(p, dtype=np.float32, ndmin=2))
        if not blocks:
            return None
        return np.concatenate(blocks, axis=1)

    xt, yt = _feats("Train"), _labels("Train")
    xe, ye = _feats("Test"), _labels("Test")
    if any(v is None for v in (xt, yt, xe, ye)):
        return None
    n_tr, n_te = min(len(xt), len(yt)), min(len(xe), len(ye))
    return xt[:n_tr], yt[:n_tr], xe[:n_te], ye[:n_te]


# -- FeTS 2021 (medical segmentation, NIfTI volumes) ------------------------

_NIFTI_DTYPES = {2: np.uint8, 4: np.int16, 8: np.int32, 16: np.float32,
                 64: np.float64, 256: np.int8, 512: np.uint16}


def _read_nifti(path: str) -> Optional[np.ndarray]:
    """Minimal little-endian NIfTI-1 reader (no nibabel in the image):
    348-byte header — dim[8] @40, datatype @70, vox_offset @108; data is
    Fortran-ordered."""
    import gzip
    import struct

    op = gzip.open if path.endswith(".gz") else open
    try:
        with op(path, "rb") as f:
            buf = f.read()
    except (OSError, EOFError, gzip.BadGzipFile):
        return None  # corrupt/truncated volume: skip subject, don't abort load
    if len(buf) < 352 or struct.unpack_from("<i", buf, 0)[0] != 348:
        return None
    dim = struct.unpack_from("<8h", buf, 40)
    ndim = max(1, min(dim[0], 7))
    shape = tuple(int(d) for d in dim[1 : 1 + ndim])
    dt = _NIFTI_DTYPES.get(struct.unpack_from("<h", buf, 70)[0])
    if dt is None or any(s <= 0 for s in shape):
        return None
    vox = int(struct.unpack_from("<f", buf, 108)[0]) or 352
    n = int(np.prod(shape))
    if vox + n * np.dtype(dt).itemsize > len(buf):
        return None  # truncated data section
    arr = np.frombuffer(buf, dtype=dt, offset=vox, count=n)
    return arr.reshape(shape, order="F")


def _mid_slice_resized(vol: np.ndarray, size: int) -> np.ndarray:
    """Middle axial slice, nearest-neighbor resized to [size, size]."""
    sl = vol[:, :, vol.shape[2] // 2] if vol.ndim >= 3 else vol
    sl = np.asarray(sl, np.float32)
    iy = np.linspace(0, sl.shape[0] - 1, size).astype(int)
    ix = np.linspace(0, sl.shape[1] - 1, size).astype(int)
    return sl[np.ix_(iy, ix)]


def load_fets_nifti(root: str, size: int = 32) -> Optional[Arrays]:
    """FeTS 2021 (reference ``data/FeTS2021``; BraTS per-subject layout):
    ``<subject>/<subject>_{t1,t1ce,t2,flair}.nii[.gz]`` + ``_seg``.  Takes
    the middle axial slice, stacks 3 modalities as channels (normalized
    per-slice), maps seg labels {0,1,2,4} -> {0,1,2}, and splits subjects
    80/20 (sorted order, deterministic)."""
    subjects = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    xs, ys = [], []
    for s in subjects:
        sdir = os.path.join(root, s)
        files = {f.lower(): os.path.join(sdir, f) for f in os.listdir(sdir)}

        def _mod(name):
            # exact modality suffix: "_t1" must not match "..._t1ce.nii.gz"
            for k, p in files.items():
                if k.endswith((f"{name}.nii", f"{name}.nii.gz")):
                    return _read_nifti(p)
            return None

        seg = _mod("_seg")
        mods = [m for m in (_mod("_t1ce"), _mod("_t1"), _mod("_t2"), _mod("_flair"))
                if m is not None][:3]
        if seg is None or not mods:
            continue
        while len(mods) < 3:
            mods.append(mods[-1])
        chans = []
        for m in mods:
            sl = _mid_slice_resized(m, size)
            denom = sl.max() - sl.min()
            chans.append((sl - sl.min()) / (denom if denom > 0 else 1.0))
        mask = _mid_slice_resized(seg, size).astype(np.int32)
        mask = np.where(mask >= 2, 2, mask)
        xs.append(np.stack(chans, axis=-1))
        ys.append(mask)
    if len(xs) < 2:
        return None
    x, y = np.stack(xs), np.stack(ys)
    cut = max(1, int(0.8 * len(x)))
    return x[:cut], y[:cut], x[cut:], y[cut:]


# -- edge-case backdoor example pools (ARDIS / Southwest) --------------------


def load_edge_case_pool(root: str) -> Optional[dict]:
    """Edge-case backdoor example pools (reference
    ``data/edge_case_examples/data_loader.py``: ARDIS '7's for MNIST,
    Southwest airliners for CIFAR — pickles of image arrays).  Accepts any
    ``*.pkl`` under ``root`` holding an ndarray [N, ...] or a dict with a
    'data' entry.  A mounted dir typically mixes sample shapes (MNIST-shaped
    ARDIS next to CIFAR-shaped Southwest), so pools are grouped BY SAMPLE
    SHAPE: returns ``{sample_shape_tuple: float_images_in_[0,1]}``."""
    import glob as _glob
    import pickle

    groups: dict = {}
    for p in sorted(_glob.glob(os.path.join(root, "*.pkl"))):
        try:
            with open(p, "rb") as f:
                obj = pickle.load(f)
        except Exception:
            continue
        if isinstance(obj, dict):
            obj = obj.get("data")
        arr = np.asarray(obj)
        if arr.ndim >= 2 and len(arr):
            arr = arr.astype(np.float32)
            if arr.max() > 1.5:  # uint8-coded images
                arr = arr / 255.0
            groups.setdefault(tuple(arr.shape[1:]), []).append(arr)
    if not groups:
        return None
    return {shape: np.concatenate(pools, axis=0) for shape, pools in groups.items()}
