"""Parsers for locally-cached real dataset files (no downloads — zero egress).

Covers the on-disk formats the reference's loaders consume
(``data/MNIST/data_loader.py`` LEAF json, ``data/cifar10/…`` python pickle
batches, idx-ubyte) so that if a user mounts real data under
``data_cache_dir`` the pipelines train on it transparently.
"""

from __future__ import annotations

import gzip
import json
import os
import pickle
import struct
from typing import Optional, Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(root: str, *names: str) -> Optional[str]:
    for dirpath, _, files in os.walk(root):
        for n in names:
            if n in files:
                return os.path.join(dirpath, n)
            for f in files:
                if f == n + ".gz":
                    return os.path.join(dirpath, f)
    return None


def load_mnist_idx(root: str) -> Optional[Arrays]:
    paths = [
        _find(root, "train-images-idx3-ubyte"),
        _find(root, "train-labels-idx1-ubyte"),
        _find(root, "t10k-images-idx3-ubyte"),
        _find(root, "t10k-labels-idx1-ubyte"),
    ]
    if any(p is None for p in paths):  # partial cache -> synthetic fallback
        return None
    xt = _read_idx(paths[0]).astype(np.float32) / 255.0
    yt = _read_idx(paths[1]).astype(np.int32)
    xe = _read_idx(paths[2]).astype(np.float32) / 255.0
    ye = _read_idx(paths[3]).astype(np.int32)
    return xt[..., None], yt, xe[..., None], ye


def load_leaf_json(root: str) -> Optional[Arrays]:
    """LEAF format: train/*.json + test/*.json with users/user_data."""
    tr_dir, te_dir = os.path.join(root, "train"), os.path.join(root, "test")
    if not (os.path.isdir(tr_dir) and os.path.isdir(te_dir)):
        return None

    def _collect(d):
        xs, ys = [], []
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".json"):
                continue
            with open(os.path.join(d, fn)) as f:
                blob = json.load(f)
            for u in blob.get("users", []):
                ud = blob["user_data"][u]
                xs.append(np.asarray(ud["x"], dtype=np.float32))
                ys.append(np.asarray(ud["y"], dtype=np.int32))
        if not xs:
            return None
        return np.concatenate(xs, 0), np.concatenate(ys, 0)

    tr = _collect(tr_dir)
    te = _collect(te_dir)
    if tr is None or te is None:
        return None
    xt, yt = tr
    xe, ye = te
    if xt.ndim == 2 and xt.shape[1] == 784:
        xt = xt.reshape(-1, 28, 28, 1)
        xe = xe.reshape(-1, 28, 28, 1)
    return xt, yt, xe, ye


def load_cifar_pickle(root: str, coarse100: bool = False) -> Optional[Arrays]:
    batches = []
    test = None
    for dirpath, _, files in os.walk(root):
        for f in files:
            if f.startswith("data_batch") or f in ("train",):
                batches.append(os.path.join(dirpath, f))
            elif f in ("test_batch", "test"):
                test = os.path.join(dirpath, f)
    if not batches or test is None:
        return None

    def _load(path):
        with open(path, "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        key = b"fine_labels" if b"fine_labels" in d else b"labels"
        y = np.asarray(d[key], dtype=np.int32)
        return x, y

    xs, ys = zip(*[_load(b) for b in sorted(batches)])
    xt, yt = np.concatenate(xs), np.concatenate(ys)
    xe, ye = _load(test)
    return xt, yt, xe, ye


def load_image_folder(root: str, size: int = 32) -> Optional[Arrays]:
    """ImageFolder layout (CINIC-10 release format): ``{train,test}/<class>/
    *.png`` — class = sorted subdirectory index.  Needs Pillow."""
    try:
        from PIL import Image
    except ImportError:  # pragma: no cover - Pillow is in the base image
        return None

    def _split(split_dir):
        if not os.path.isdir(split_dir):
            return None
        classes = sorted(
            d for d in os.listdir(split_dir)
            if os.path.isdir(os.path.join(split_dir, d))
        )
        if not classes:
            return None
        xs, ys = [], []
        for ci, cname in enumerate(classes):
            cdir = os.path.join(split_dir, cname)
            for f in sorted(os.listdir(cdir)):
                if not f.lower().endswith((".png", ".jpg", ".jpeg")):
                    continue
                img = Image.open(os.path.join(cdir, f)).convert("RGB")
                if img.size != (size, size):
                    img = img.resize((size, size))
                xs.append(np.asarray(img, np.float32) / 255.0)
                ys.append(ci)
        if not xs:
            return None
        return np.stack(xs), np.asarray(ys, np.int32)

    train = _split(os.path.join(root, "train"))
    test = _split(os.path.join(root, "test")) or _split(os.path.join(root, "valid"))
    if train is None or test is None:
        return None
    return train[0], train[1], test[0], test[1]


def load_csv_labeled(root: str) -> Optional[Arrays]:
    """Tabular CSV parser (UCI / lending_club-style files, reference
    ``data/data_loader.py`` tabular branches): ``train.csv`` (+ optional
    ``test.csv``, else a 80/20 tail split).  The label column is the one
    named 'label'/'target'/'y' in the header, else the LAST column; features
    must be numeric."""
    train_path = _find(root, "train.csv")
    if train_path is None:
        return None

    def _parse(path):
        with open(path) as f:
            header = f.readline().strip().split(",")
        names = [h.strip().lower() for h in header]
        has_header = not all(_is_float(h) for h in names)
        data = np.genfromtxt(path, delimiter=",", skip_header=1 if has_header else 0,
                             dtype=np.float64)
        if data.ndim == 1:
            data = data[None, :]
        label_col = len(names) - 1
        if has_header:
            for cand in ("label", "target", "y"):
                if cand in names:
                    label_col = names.index(cand)
                    break
        y = data[:, label_col].astype(np.int32)
        x = np.delete(data, label_col, axis=1).astype(np.float32)
        return x, y

    xt, yt = _parse(train_path)
    test_path = _find(root, "test.csv")
    if test_path is not None:
        xe, ye = _parse(test_path)
    else:
        # seeded shuffle before the 80/20 split: exported CSVs are often
        # label-sorted, and an unshuffled tail would be single-class
        perm = np.random.RandomState(0).permutation(len(yt))
        xt, yt = xt[perm], yt[perm]
        cut = max(int(len(yt) * 0.8), 1)
        xe, ye = xt[cut:], yt[cut:]
        xt, yt = xt[:cut], yt[:cut]
        if len(ye) == 0:
            xe, ye = xt, yt  # degenerate tiny file: eval on train
    return xt, yt, xe, ye


def _is_float(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False


def try_load_real(name: str, cache_dir: str) -> Optional[Arrays]:
    if not cache_dir or not os.path.isdir(cache_dir):
        return None
    sub = os.path.join(cache_dir, name)
    roots = [sub, cache_dir]
    for root in roots:
        if not os.path.isdir(root):
            continue
        if name in ("mnist", "fashionmnist"):
            out = load_mnist_idx(root) or load_leaf_json(root)
        elif name == "femnist":
            out = load_leaf_json(root)
        elif name == "cinic10":
            out = load_image_folder(root) or load_cifar_pickle(root)
        elif name.startswith("cifar") or name == "fed_cifar100":
            out = load_cifar_pickle(root, coarse100="100" in name)
        elif name in ("shakespeare", "fed_shakespeare", "stackoverflow_nwp", "stackoverflow_lr"):
            out = load_leaf_json(root)
        elif name in ("uci", "lending_club"):
            out = load_csv_labeled(root)
        else:
            out = None
        if out is not None:
            return out
    return None
