from . import data_loader
from .data_loader import load, load_centralized
