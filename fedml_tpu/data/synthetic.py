"""Deterministic synthetic dataset generators.

This container has zero egress, so the reference's auto-download data layer
(``data/data_loader.py:234-582`` + S3 URLs) is replaced by: (1) parsers for
locally-cached real files when present (see loaders.py), and (2) these
procedurally-generated fallbacks with the SAME shapes/cardinalities as the
real datasets, so every pipeline/benchmark runs end-to-end.  Generated data
is class-separable (gaussian class prototypes + noise + per-class structured
masks) so models demonstrably learn; accuracy numbers on synthetic data are
NOT comparable to the reference's published accuracy (throughput numbers are).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def make_classification(
    n: int,
    num_classes: int,
    feature_shape: Tuple[int, ...],
    seed: int = 0,
    noise: float = 0.35,
    dirichlet_label_skew: float = 0.0,
    proto_seed: int = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Class-prototype + gaussian-noise images/features, labels uniform (or
    Dir-skewed when ``dirichlet_label_skew`` > 0).

    ``proto_seed`` fixes the class prototypes independently of the sample
    seed so train and test splits share one distribution (pass the same
    proto_seed with different ``seed``)."""
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(seed if proto_seed is None else proto_seed)
    dim = int(np.prod(feature_shape))
    protos = proto_rng.randn(num_classes, dim).astype(np.float32)
    # low-frequency structure: smooth prototypes so convs have something to find
    if len(feature_shape) >= 2:
        h, w = feature_shape[0], feature_shape[1]
        yy, xx = np.mgrid[0:h, 0:w]
        for c in range(num_classes):
            fx, fy = 1 + c % 3, 1 + (c // 3) % 3
            wave = np.sin(2 * np.pi * fx * xx / w) * np.cos(2 * np.pi * fy * yy / h)
            p = protos[c].reshape(feature_shape)
            p += 1.5 * wave[(...,) + (None,) * (len(feature_shape) - 2)]
            protos[c] = p.reshape(-1)
    if dirichlet_label_skew > 0:
        pvals = rng.dirichlet(np.repeat(dirichlet_label_skew, num_classes))
        y = rng.choice(num_classes, size=n, p=pvals)
    else:
        y = rng.randint(0, num_classes, size=n)
    x = protos[y] + noise * rng.randn(n, dim).astype(np.float32)
    x = x.reshape((n,) + tuple(feature_shape)).astype(np.float32)
    return x, y.astype(np.int32)


def make_sequence_classification(
    n: int, num_classes: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Token sequences whose class is recoverable from token statistics."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, num_classes, size=n).astype(np.int32)
    # each class favors a band of the vocabulary
    band = vocab_size // max(num_classes, 1)
    x = np.empty((n, seq_len), dtype=np.int32)
    for i in range(n):
        lo = y[i] * band
        favored = rng.randint(lo, max(lo + band, lo + 1), size=seq_len)
        uniform = rng.randint(0, vocab_size, size=seq_len)
        pick = rng.rand(seq_len) < 0.6
        x[i] = np.where(pick, favored, uniform)
    return x, y


def make_next_token_corpus(
    n: int, seq_len: int, vocab_size: int, seed: int = 0, proto_seed: int = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Markov-chain token streams for next-word-prediction tasks: x=[n,L],
    y=[n,L] (x shifted by one).  ``proto_seed`` fixes the transition matrix
    (the "language") independently of the sampled sequences."""
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(seed if proto_seed is None else proto_seed)
    # sparse row-stochastic transition matrix with strong structure
    trans = proto_rng.dirichlet(np.full(vocab_size, 0.05), size=vocab_size)
    seqs = np.empty((n, seq_len + 1), dtype=np.int32)
    state = rng.randint(0, vocab_size, size=n)
    seqs[:, 0] = state
    for t in range(1, seq_len + 1):
        u = rng.rand(n)
        cdf = np.cumsum(trans[seqs[:, t - 1]], axis=1)
        seqs[:, t] = (u[:, None] > cdf).sum(axis=1)
    return seqs[:, :-1], seqs[:, 1:]


def make_segmentation(
    n: int, image_hw: Tuple[int, int] = (32, 32), seed: int = 0, proto_seed: int = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic segmentation pairs: images [n, H, W, 3] with a random circle
    (class 1) and/or rectangle (class 2) on textured background (class 0);
    masks [n, H, W] int32.  Shape-faithful stand-in for VOC/COCO-style data
    when no cache is mounted (FedSeg)."""
    h, w = image_hw
    rng = np.random.RandomState(seed)
    # the class "appearance" (object colors) is the distribution — it derives
    # from proto_seed so train and test share it (same contract as
    # make_classification's prototypes)
    proto_rng = np.random.RandomState(seed if proto_seed is None else proto_seed)
    circle_color = np.array([0.9, 0.2, 0.2]) + 0.05 * proto_rng.randn(3)
    rect_color = np.array([0.2, 0.2, 0.9]) + 0.05 * proto_rng.randn(3)
    x = rng.rand(n, h, w, 3).astype(np.float32) * 0.2
    masks = np.zeros((n, h, w), dtype=np.int32)
    yy, xx = np.mgrid[0:h, 0:w]
    for i in range(n):
        if rng.rand() < 0.8:  # circle
            cy, cx = rng.randint(h // 4, 3 * h // 4), rng.randint(w // 4, 3 * w // 4)
            r = rng.randint(min(h, w) // 8, min(h, w) // 4)
            circ = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
            masks[i][circ] = 1
            x[i][circ] = circle_color + 0.1 * rng.randn(3)
        if rng.rand() < 0.8:  # rectangle (drawn second: may occlude)
            y0, x0 = rng.randint(0, h // 2), rng.randint(0, w // 2)
            hh, ww = rng.randint(h // 6, h // 3), rng.randint(w // 6, w // 3)
            rect = np.zeros((h, w), bool)
            rect[y0 : y0 + hh, x0 : x0 + ww] = True
            masks[i][rect] = 2
            x[i][rect] = rect_color + 0.1 * rng.randn(3)
    return x, masks


def make_graph_classification(
    n: int, num_nodes: int = 16, feat_dim: int = 8, num_classes: int = 4,
    seed: int = 0, proto_seed: int = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic graph-classification set packed as [n, N, F+N] (node
    features ‖ dense adjacency — the layout models/gcn.py consumes).  Class
    signal: per-class node-feature prototypes AND class-dependent edge
    density, so both the feature and the structure path of a GNN carry
    information."""
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(seed if proto_seed is None else proto_seed)
    protos = proto_rng.randn(num_classes, feat_dim).astype(np.float32)
    densities = np.linspace(0.15, 0.6, num_classes)
    y = rng.randint(0, num_classes, size=n).astype(np.int32)
    x = np.zeros((n, num_nodes, feat_dim + num_nodes), np.float32)
    for i in range(n):
        c = y[i]
        n_real = rng.randint(max(num_nodes // 2, 2), num_nodes + 1)
        feats = protos[c] + 0.5 * rng.randn(n_real, feat_dim)
        upper = rng.rand(n_real, n_real) < densities[c]
        adj = np.triu(upper, 1)
        adj = (adj | adj.T).astype(np.float32)
        x[i, :n_real, :feat_dim] = feats
        x[i, :n_real, feat_dim : feat_dim + n_real] = adj
    return x, y


def make_sequence_tagging(
    n: int, num_tags: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-token tagging corpus: each token's tag is its vocabulary band
    (NER/POS-shaped — reference app/fednlp/seq_tagging).  x [n, L] int32,
    y [n, L] int32 in [0, num_tags)."""
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab_size, size=(n, seq_len)).astype(np.int32)
    band = max(vocab_size // max(num_tags, 1), 1)
    y = np.minimum(x // band, num_tags - 1).astype(np.int32)
    # tag noise: a small fraction of tokens carry a random tag so the task
    # is not trivially 100% learnable
    flip = rng.rand(n, seq_len) < 0.05
    y = np.where(flip, rng.randint(0, num_tags, size=(n, seq_len)), y).astype(np.int32)
    return x, y


def make_span_extraction(
    n: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Span-extraction corpus (SQuAD-shaped — reference
    app/fednlp/span_extraction): the answer is a contiguous run of tokens
    from a distinct vocabulary band ([2, 50) vs context [60, vocab)), so the
    extraction RULE is generalizable; y [n, 2] = (start, end) indices.
    (A pure marker-bracket design lets a memorizing net hit zero held-out
    exact-match — band coding keeps the task rule-learnable at CI scale.)"""
    rng = np.random.RandomState(seed)
    x = rng.randint(60, max(vocab_size, 61), size=(n, seq_len)).astype(np.int32)
    y = np.zeros((n, 2), np.int32)
    for i in range(n):
        start = rng.randint(1, seq_len - 4)
        end = min(start + rng.randint(1, 5), seq_len - 2)
        x[i, start:end + 1] = rng.randint(2, 50, size=end - start + 1)
        y[i] = (start, end)
    return x, y


def make_detection(
    n: int, hw: Tuple[int, int], num_classes: int, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Single-object detection set (reference app/fedcv/object_detection
    shape): one axis-aligned bright box per image, class = box color channel
    pattern.  x [n, H, W, 3] f32; y [n, 5] f32 = (class, cx, cy, w, h) with
    box coords normalized to [0, 1]."""
    rng = np.random.RandomState(seed)
    H, W = hw
    x = (rng.rand(n, H, W, 3) * 0.15).astype(np.float32)
    y = np.zeros((n, 5), np.float32)
    for i in range(n):
        cls = rng.randint(0, num_classes)
        bw = rng.randint(W // 6, W // 2)
        bh = rng.randint(H // 6, H // 2)
        x0 = rng.randint(0, W - bw)
        y0 = rng.randint(0, H - bh)
        patch = np.full((bh, bw, 3), 0.2, np.float32)
        patch[..., cls % 3] = 0.95  # class-dependent dominant channel
        if cls >= 3:  # second pattern axis: bright frame
            patch[0, :, :] = patch[-1, :, :] = patch[:, 0, :] = patch[:, -1, :] = 1.0
        x[i, y0:y0 + bh, x0:x0 + bw] = patch
        y[i] = (cls, (x0 + bw / 2) / W, (y0 + bh / 2) / H, bw / W, bh / H)
    return x, y


def make_seq2seq(
    n: int, src_len: int, tgt_len: int, vocab_size: int, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Seq2seq corpus packed for a causal decoder-only LM (the TPU-first
    redesign of reference app/fednlp/seq2seq's encoder-decoder BART: one
    causal stack over [src ‖ SEP ‖ tgt] with loss masked to target positions
    — same task contract, no cross-attention module to shard).

    Task: emit each source token's successor in vocab order (tgt[j] =
    succ(src[j]) — a constant relative-offset attention pattern plus a
    learned token mapping, the right-sized learnability gate for a RoPE
    causal stack; reversal's varying offsets need far more steps than a CI
    smoke test allows).  x [n, L] int32 with L = src_len + tgt_len: src
    tokens in [2, vocab), SEP = 1, then the teacher-forced target prefix.
    y [n, L] int32: -1 on source positions, target token ids elsewhere
    (engine loss kind "s2s")."""
    rng = np.random.RandomState(seed)
    L = src_len + tgt_len
    x = np.zeros((n, L), np.int32)
    y = np.full((n, L), -1, np.int32)
    src = rng.randint(2, max(vocab_size, 3), size=(n, src_len)).astype(np.int32)
    tgt = (2 + (src - 2 + 1) % (vocab_size - 2)).astype(np.int32)
    x[:, :src_len] = src
    x[:, src_len] = 1  # SEP starts decoding
    x[:, src_len + 1 :] = tgt[:, : tgt_len - 1]
    y[:, src_len:] = tgt
    return x, y


def make_link_prediction(
    n: int, num_nodes: int = 16, feat_dim: int = 8, seed: int = 0,
    bipartite: bool = False, holdout: float = 0.3, proto_seed: int = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Link-prediction subgraphs (reference app/fedgraphnn
    ego_networks_link_pred; ``bipartite=True`` is the recsys
    user-item variant, recsys_subgraph_link_pred).

    Each sample: nodes carry a latent community (or user-group/item-category
    when bipartite); edges form mostly within-community (across matching
    user-group/item-category pairs when bipartite).  A ``holdout`` fraction
    of true edges is removed from the observed adjacency and becomes the
    positive labels; an equal number of true non-edges becomes the
    negatives.  x [n, N, F+N] (features ‖ observed adjacency, the gcn.py
    packing); y [n, N, N] f32 in {-1, 0, 1} (engine loss kind "linkpred")."""
    rng = np.random.RandomState(seed)
    prng = np.random.RandomState((seed if proto_seed is None else proto_seed) + 77)
    protos = prng.randn(2, feat_dim).astype(np.float32)
    x = np.zeros((n, num_nodes, feat_dim + num_nodes), np.float32)
    y = np.full((n, num_nodes, num_nodes), -1.0, np.float32)
    half = num_nodes // 2
    for i in range(n):
        if bipartite:
            # nodes [0, half) = users, [half, N) = items; community = group
            comm = np.concatenate([rng.randint(0, 2, half), rng.randint(0, 2, num_nodes - half)])
            is_user = np.arange(num_nodes) < half
            cross = is_user[:, None] != is_user[None, :]
            p_edge = np.where(comm[:, None] == comm[None, :], 0.8, 0.05) * cross
        else:
            comm = rng.randint(0, 2, num_nodes)
            p_edge = np.where(comm[:, None] == comm[None, :], 0.7, 0.05)
        feats = protos[comm] + 0.4 * rng.randn(num_nodes, feat_dim)
        upper = np.triu(rng.rand(num_nodes, num_nodes) < p_edge, 1)
        true_adj = (upper | upper.T)
        # hold out a fraction of true edges as positive labels
        iu, ju = np.nonzero(np.triu(true_adj, 1))
        if len(iu) == 0:
            x[i, :, :feat_dim] = feats
            continue
        k = max(1, int(holdout * len(iu)))
        pick = rng.choice(len(iu), size=k, replace=False)
        obs = true_adj.copy()
        obs[iu[pick], ju[pick]] = obs[ju[pick], iu[pick]] = False
        # negatives: sample k true non-edges (off-diagonal)
        neg_mask = ~true_adj & ~np.eye(num_nodes, dtype=bool)
        if bipartite:
            neg_mask &= cross
        ni, nj = np.nonzero(np.triu(neg_mask, 1))
        npick = rng.choice(len(ni), size=min(k, len(ni)), replace=False)
        y[i, iu[pick], ju[pick]] = y[i, ju[pick], iu[pick]] = 1.0
        y[i, ni[npick], nj[npick]] = y[i, nj[npick], ni[npick]] = 0.0
        x[i, :, :feat_dim] = feats
        x[i, :, feat_dim:] = obs.astype(np.float32)
    return x, y


def make_multitask_graphs(
    n: int, num_nodes: int = 16, feat_dim: int = 8, num_tasks: int = 8,
    seed: int = 0, proto_seed: int = None, label_frac: float = 0.7,
) -> Tuple[np.ndarray, np.ndarray]:
    """Multi-task molecular-property-style graphs with PARTIAL labels — the
    SpreadGNN setting (reference research/SpreadGNN; moleculenet sider/tox21
    carry per-task label masks).  Each graph has a latent prototype; task t's
    binary label is sign(w_t · prototype); each (graph, task) entry is
    observed with prob ``label_frac`` else -1.  x packed as [n, N, F+N]
    (gcn.py layout); y [n, T] f32 in {-1, 0, 1} (engine loss "mtl_bce")."""
    rng = np.random.RandomState(seed)
    prng = np.random.RandomState(seed if proto_seed is None else proto_seed)
    n_proto = 6
    protos = prng.randn(n_proto, feat_dim).astype(np.float32)
    task_w = prng.randn(num_tasks, feat_dim).astype(np.float32)
    x = np.zeros((n, num_nodes, feat_dim + num_nodes), np.float32)
    y = np.zeros((n, num_tasks), np.float32)
    densities = np.linspace(0.15, 0.6, n_proto)
    for i in range(n):
        c = rng.randint(0, n_proto)
        n_real = rng.randint(max(num_nodes // 2, 2), num_nodes + 1)
        feats = protos[c] + 0.4 * rng.randn(n_real, feat_dim)
        upper = rng.rand(n_real, n_real) < densities[c]
        adj = np.triu(upper, 1)
        adj = (adj | adj.T).astype(np.float32)
        x[i, :n_real, :feat_dim] = feats
        x[i, :n_real, feat_dim : feat_dim + n_real] = adj
        labels = (task_w @ protos[c] > 0).astype(np.float32)
        observed = rng.rand(num_tasks) < label_frac
        y[i] = np.where(observed, labels, -1.0)
    return x, y


def make_iot_traffic(
    n: int, feat_dim: int = 24, seed: int = 0, proto_seed: int = None,
    anomaly_frac: float = 0.0, latent_dim: int = 4,
) -> Tuple[np.ndarray, np.ndarray]:
    """IoT network-traffic-shaped anomaly set (reference
    ``iot/anomaly_detection_for_cybersecurity``'s N-BaIoT-style data):
    benign rows live on a low-rank manifold (latent z @ W + noise) that an
    autoencoder can compress; anomalies (``anomaly_frac``) are structure-
    breaking uniform rows.  Returns (x [n, F], flags [n] in {0, 1}).
    Train splits use anomaly_frac=0 (benign-only, the reference's setup)."""
    rng = np.random.RandomState(seed)
    prng = np.random.RandomState((seed if proto_seed is None else proto_seed) + 31)
    w = prng.randn(latent_dim, feat_dim).astype(np.float32)
    z = rng.randn(n, latent_dim).astype(np.float32)
    x = z @ w + 0.05 * rng.randn(n, feat_dim).astype(np.float32)
    flags = np.zeros(n, np.int32)
    if anomaly_frac > 0:
        k = max(1, int(anomaly_frac * n))
        idx = rng.choice(n, size=k, replace=False)
        x[idx] = rng.uniform(-4.0, 4.0, size=(k, feat_dim)).astype(np.float32)
        flags[idx] = 1
    return x, flags


def make_node_classification(
    n: int, num_nodes: int = 16, feat_dim: int = 8, num_classes: int = 3,
    seed: int = 0, proto_seed: int = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-node classification graphs (reference app/fedgraphnn
    ego_networks_node_clf): each node's class is its community; features
    carry the community prototype, edges form mostly within-community, so
    both feature and structure paths are informative.  x [n, N, F+N]
    (gcn.py packing); y [n, N] int32 node labels (padding nodes get 0 and
    are silenced by the model's node mask)."""
    rng = np.random.RandomState(seed)
    prng = np.random.RandomState((seed if proto_seed is None else proto_seed) + 53)
    protos = prng.randn(num_classes, feat_dim).astype(np.float32)
    x = np.zeros((n, num_nodes, feat_dim + num_nodes), np.float32)
    y = np.zeros((n, num_nodes), np.int32)
    for i in range(n):
        comm = rng.randint(0, num_classes, num_nodes)
        feats = protos[comm] + 0.5 * rng.randn(num_nodes, feat_dim)
        p_edge = np.where(comm[:, None] == comm[None, :], 0.5, 0.05)
        upper = np.triu(rng.rand(num_nodes, num_nodes) < p_edge, 1)
        adj = (upper | upper.T).astype(np.float32)
        x[i, :, :feat_dim] = feats
        x[i, :, feat_dim:] = adj
        y[i] = comm
    return x, y


def make_graph_regression(
    n: int, num_nodes: int = 16, feat_dim: int = 8, seed: int = 0,
    proto_seed: int = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Graph-level property regression (reference app/fedgraphnn
    moleculenet_graph_reg): target = w · mean-node-features + density term
    (both paths of a GNN carry signal).  y [n, 1] f32."""
    rng = np.random.RandomState(seed)
    prng = np.random.RandomState((seed if proto_seed is None else proto_seed) + 67)
    w = prng.randn(feat_dim).astype(np.float32)
    x = np.zeros((n, num_nodes, feat_dim + num_nodes), np.float32)
    y = np.zeros((n, 1), np.float32)
    for i in range(n):
        feats = rng.randn(num_nodes, feat_dim).astype(np.float32)
        density = rng.uniform(0.1, 0.6)
        upper = np.triu(rng.rand(num_nodes, num_nodes) < density, 1)
        adj = (upper | upper.T).astype(np.float32)
        x[i, :, :feat_dim] = feats
        x[i, :, feat_dim:] = adj
        y[i, 0] = feats.mean(axis=0) @ w + 2.0 * density
    return x, y
