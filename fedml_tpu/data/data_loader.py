"""Federated data loading: ``load(args)`` -> (dataset, class_num).

API parity with reference ``data/data_loader.py:234`` (``fedml.data.load``):
returns the 8-tuple the runtimes consume::

    [train_data_num, test_data_num, train_data_global, test_data_global,
     train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
     class_num]

Differences from the reference, by design:
* data are numpy ``(x, y)`` array pairs, not torch DataLoaders — the TPU
  engine batches/pads on device (ml/engine/train.py);
* zero-egress: if real files exist under ``args.data_cache_dir`` they are
  parsed (MNIST idx / CIFAR pickle / LEAF json), else shape-faithful
  synthetic data is generated (data/synthetic.py) and
  ``dataset_is_synthetic=True`` is set on args;
* partitioning is explicit: ``partition_method`` hetero (Dirichlet LDA,
  ``partition_alpha``) / homo — same keys as the reference configs.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Tuple

import numpy as np

from ..core.data.noniid_partition import (
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
    quantity_skew_partition,
)
from . import loaders, synthetic

logger = logging.getLogger(__name__)

# dataset key -> (num_classes, feature_shape, default train/test sizes, kind)
DATASET_SPECS: Dict[str, Dict[str, Any]] = {
    "mnist": dict(classes=10, shape=(28, 28, 1), train=60000, test=10000, kind="image"),
    "femnist": dict(classes=62, shape=(28, 28, 1), train=80000, test=10000, kind="image"),
    "fashionmnist": dict(classes=10, shape=(28, 28, 1), train=60000, test=10000, kind="image"),
    "cifar10": dict(classes=10, shape=(32, 32, 3), train=50000, test=10000, kind="image"),
    "cifar100": dict(classes=100, shape=(32, 32, 3), train=50000, test=10000, kind="image"),
    "fed_cifar100": dict(classes=100, shape=(32, 32, 3), train=50000, test=10000, kind="image"),
    "cinic10": dict(classes=10, shape=(32, 32, 3), train=90000, test=90000, kind="image"),
    "shakespeare": dict(classes=90, shape=(80,), train=40000, test=4000, kind="nwp", vocab=90),
    "fed_shakespeare": dict(classes=90, shape=(80,), train=40000, test=4000, kind="nwp", vocab=90),
    "stackoverflow_nwp": dict(classes=10004, shape=(20,), train=50000, test=5000, kind="nwp", vocab=10004),
    "stackoverflow_lr": dict(classes=500, shape=(10004,), train=50000, test=5000, kind="taglr"),
    "synthetic": dict(classes=10, shape=(60,), train=9600, test=2400, kind="feature"),
    "synthetic_1_1": dict(classes=10, shape=(60,), train=9600, test=2400, kind="feature"),
    # segmentation (FedSeg; reference uses pascal_voc/coco — synthetic fallback
    # keeps 3 shape classes at 32x32 for practical FL round sizes)
    "synthetic_seg": dict(classes=3, shape=(32, 32, 3), train=2000, test=400, kind="segmentation"),
    "pascal_voc": dict(classes=3, shape=(32, 32, 3), train=2000, test=400, kind="segmentation"),
    # fednlp text classification (reference app/fednlp: 20news/agnews/sst_2)
    "agnews": dict(classes=4, shape=(64,), train=12000, test=2000, kind="seqcls", vocab=2000),
    "sst_2": dict(classes=2, shape=(32,), train=8000, test=1000, kind="seqcls", vocab=2000),
    "20news": dict(classes=20, shape=(128,), train=11000, test=2000, kind="seqcls", vocab=4000),
    # fedgraphnn (reference app/fedgraphnn: moleculenet graph classification)
    "synthetic_graph": dict(classes=4, shape=(16, 24), train=2000, test=400, kind="graph",
                            num_nodes=16, feat_dim=8),
    "sider": dict(classes=4, shape=(16, 24), train=1400, test=300, kind="graph",
                  num_nodes=16, feat_dim=8),
    "clintox": dict(classes=2, shape=(16, 24), train=1400, test=300, kind="graph",
                    num_nodes=16, feat_dim=8),
    # healthcare / tabular (reference data: UCI, lending_club, FeTS)
    "uci": dict(classes=2, shape=(32,), train=8000, test=1600, kind="feature"),
    "lending_club": dict(classes=2, shape=(90,), train=10000, test=2000, kind="feature"),
    "fets2021": dict(classes=3, shape=(32, 32, 3), train=1000, test=200, kind="segmentation"),
    # ImageNet family (reference data/ImageNet; downsampled 32px variant for
    # TPU-static shapes — mounted train/val wnid folders parse via loaders)
    "imagenet": dict(classes=1000, shape=(32, 32, 3), train=20000, test=4000, kind="image"),
    "ilsvrc2012": dict(classes=1000, shape=(32, 32, 3), train=20000, test=4000, kind="image"),
    "tiny_imagenet": dict(classes=200, shape=(32, 32, 3), train=20000, test=4000, kind="image"),
    # Google Landmarks federated splits (reference data/Landmarks)
    "gld23k": dict(classes=203, shape=(32, 32, 3), train=23080, test=1959, kind="image"),
    "gld160k": dict(classes=2028, shape=(32, 32, 3), train=40000, test=4000, kind="image"),
    # NUS-WIDE multi-label low-level features (reference data/NUS_WIDE,
    # the vertical-FL dataset: 634-dim concatenated feature blocks, top-5 labels)
    "nuswide": dict(classes=5, shape=(634,), train=20000, test=4000, kind="taglr"),
    # IoT anomaly detection (reference iot/anomaly_detection_for_cybersecurity,
    # N-BaIoT-style benign-traffic autoencoder; classes = benign/anomaly)
    "iot_anomaly": dict(classes=2, shape=(24,), train=8000, test=1600, kind="recon",
                        anomaly_frac=0.1),
    "nbaiot": dict(classes=2, shape=(115,), train=8000, test=1600, kind="recon",
                   anomaly_frac=0.1),
    # fednlp sequence tagging / span extraction (reference app/fednlp
    # seq_tagging + span_extraction; synthetic corpora share the shapes)
    "onto_tagging": dict(classes=8, shape=(32,), train=8000, test=1600, kind="seqtag", vocab=2000),
    "wikiner": dict(classes=5, shape=(48,), train=8000, test=1600, kind="seqtag", vocab=2000),
    "squad_span": dict(classes=64, shape=(64,), train=8000, test=1600, kind="span", vocab=200),
    # fedcv object detection (reference app/fedcv/object_detection)
    "synthetic_det": dict(classes=6, shape=(32, 32, 3), train=4000, test=800, kind="detection"),
    "coco_det": dict(classes=6, shape=(32, 32, 3), train=4000, test=800, kind="detection"),
    # fednlp seq2seq (reference app/fednlp/seq2seq: CornellMovieDialogue);
    # classes = vocab (the LM head width over the packed sequence)
    "synthetic_s2s": dict(classes=64, shape=(24,), train=8000, test=1600, kind="s2s",
                          vocab=64, src_len=12, tgt_len=12),
    "cornell_movie_dialogue": dict(classes=64, shape=(24,), train=8000, test=1600, kind="s2s",
                                   vocab=64, src_len=12, tgt_len=12),
    # fedgraphnn link prediction (reference app/fedgraphnn
    # ego_networks_link_pred + recsys_subgraph_link_pred)
    "ego_linkpred": dict(classes=2, shape=(16, 24), train=2000, test=400, kind="linkpred",
                         num_nodes=16, feat_dim=8),
    "recsys_linkpred": dict(classes=2, shape=(16, 24), train=2000, test=400, kind="linkpred",
                            num_nodes=16, feat_dim=8, bipartite=True),
    # multi-task molecular property prediction with partial labels
    # (reference research/SpreadGNN; moleculenet sider/tox21 masks)
    "moleculenet_mtl": dict(classes=8, shape=(16, 24), train=2000, test=400, kind="mtl_graph",
                            num_nodes=16, feat_dim=8, num_tasks=8),
    # fedgraphnn node classification + graph regression (reference
    # app/fedgraphnn/{ego_networks_node_clf,moleculenet_graph_reg})
    "ego_nodeclf": dict(classes=3, shape=(16, 24), train=2000, test=400, kind="nodeclf",
                        num_nodes=16, feat_dim=8),
    "freesolv": dict(classes=1, shape=(16, 24), train=2000, test=400, kind="graphreg",
                     num_nodes=16, feat_dim=8),
    "esol": dict(classes=1, shape=(16, 24), train=2000, test=400, kind="graphreg",
                 num_nodes=16, feat_dim=8),
    "lipophilicity": dict(classes=1, shape=(16, 24), train=2000, test=400, kind="graphreg",
                          num_nodes=16, feat_dim=8),
}


def _generate(spec: Dict[str, Any], n: int, seed: int, scale_override: int = 0,
              proto_seed: int = 0, is_test: bool = False):
    kind = spec["kind"]
    n = int(scale_override or n)
    if kind == "recon":
        # benign-only train split (targets = inputs); test split carries
        # injected anomalies with 0/1 flags (the IoT detection setup)
        x, flags = synthetic.make_iot_traffic(
            n, int(spec["shape"][0]), seed=seed, proto_seed=proto_seed,
            anomaly_frac=float(spec.get("anomaly_frac", 0.1)) if is_test else 0.0,
        )
        return (x, flags) if is_test else (x, x.copy())
    if kind in ("image", "feature"):
        return synthetic.make_classification(
            n, spec["classes"], tuple(spec["shape"]), seed=seed, proto_seed=proto_seed
        )
    if kind == "nwp":
        return synthetic.make_next_token_corpus(
            n, int(spec["shape"][0]), spec["vocab"], seed=seed, proto_seed=proto_seed
        )
    if kind == "segmentation":
        return synthetic.make_segmentation(
            n, tuple(spec["shape"][:2]), seed=seed, proto_seed=proto_seed
        )
    if kind == "seqcls":
        # class->vocab-band mapping is deterministic, so train/test share the
        # distribution without a proto_seed
        return synthetic.make_sequence_classification(
            n, spec["classes"], int(spec["shape"][0]), spec["vocab"], seed=seed
        )
    if kind == "graph":
        return synthetic.make_graph_classification(
            n, spec["num_nodes"], spec["feat_dim"], spec["classes"],
            seed=seed, proto_seed=proto_seed,
        )
    if kind == "seqtag":
        return synthetic.make_sequence_tagging(
            n, spec["classes"], int(spec["shape"][0]), spec["vocab"], seed=seed
        )
    if kind == "span":
        return synthetic.make_span_extraction(
            n, int(spec["shape"][0]), spec["vocab"], seed=seed
        )
    if kind == "detection":
        return synthetic.make_detection(
            n, tuple(spec["shape"][:2]), spec["classes"], seed=seed
        )
    if kind == "s2s":
        return synthetic.make_seq2seq(
            n, spec["src_len"], spec["tgt_len"], spec["vocab"], seed=seed
        )
    if kind == "linkpred":
        return synthetic.make_link_prediction(
            n, spec["num_nodes"], spec["feat_dim"], seed=seed,
            bipartite=bool(spec.get("bipartite", False)), proto_seed=proto_seed,
        )
    if kind == "mtl_graph":
        return synthetic.make_multitask_graphs(
            n, spec["num_nodes"], spec["feat_dim"], spec["num_tasks"],
            seed=seed, proto_seed=proto_seed,
        )
    if kind == "nodeclf":
        return synthetic.make_node_classification(
            n, spec["num_nodes"], spec["feat_dim"], spec["classes"],
            seed=seed, proto_seed=proto_seed,
        )
    if kind == "graphreg":
        return synthetic.make_graph_regression(
            n, spec["num_nodes"], spec["feat_dim"], seed=seed, proto_seed=proto_seed,
        )
    if kind == "taglr":
        x, y = synthetic.make_classification(
            n, spec["classes"], (64,), seed=seed, proto_seed=proto_seed
        )
        # sparse bag-of-words style expansion; projection is part of the
        # "distribution" so it derives from proto_seed (shared train/test)
        rngl = np.random.RandomState(proto_seed + 1)
        proj = rngl.randn(64, spec["shape"][0]).astype(np.float32)
        return (x @ proj > 1.0).astype(np.float32), y
    raise ValueError(kind)


def load_centralized(args) -> Dict[str, Any]:
    """-> dict(x_train, y_train, x_test, y_test, class_num, input_shape)."""
    name = str(getattr(args, "dataset", "mnist")).lower()
    if name not in DATASET_SPECS:
        raise ValueError(f"unknown dataset {name!r}; known: {sorted(DATASET_SPECS)}")
    spec = DATASET_SPECS[name]
    cache = getattr(args, "data_cache_dir", None)
    seed = int(getattr(args, "random_seed", 0))
    real = loaders.try_load_real(name, cache) if cache else None
    if real is not None:
        x_train, y_train, x_test, y_test = real
        args.dataset_is_synthetic = False
        logger.info("loaded real %s from %s", name, cache)
    else:
        scale = int(getattr(args, "synthetic_train_size", 0))
        x_train, y_train = _generate(spec, spec["train"], seed, scale, proto_seed=seed)
        x_test, y_test = _generate(
            spec, spec["test"], seed + 10_000, scale // 5 if scale else 0,
            proto_seed=seed, is_test=True,
        )
        args.dataset_is_synthetic = True
        logger.info("generated synthetic %s (no cached files under %r)", name, cache)
    return dict(
        x_train=x_train,
        y_train=y_train,
        x_test=x_test,
        y_test=y_test,
        class_num=spec["classes"],
        input_shape=tuple(x_train.shape[1:]),
    )


def load(args) -> Tuple[list, int]:
    """Reference-shaped federated load (``data_loader.py:234``)."""
    data = load_centralized(args)
    client_num = int(getattr(args, "client_num_in_total", 1))
    method = str(getattr(args, "partition_method", "hetero")).lower()
    alpha = float(getattr(args, "partition_alpha", 0.5))
    seed = int(getattr(args, "random_seed", 0))
    y_train, y_test = data["y_train"], data["y_test"]

    if method in ("hetero", "noniid", "dirichlet"):
        name = str(getattr(args, "dataset", "mnist")).lower()
        kind = DATASET_SPECS.get(name, {}).get("kind")
        if y_train.ndim == 1:
            part_labels = y_train
        elif kind == "detection":
            part_labels = y_train[:, 0].astype(int)  # object class column
        elif kind == "segmentation":
            # dominant FOREGROUND class per image: a mask-mean bucket would
            # put ~every image in bucket 0 (background majority) and the
            # Dirichlet split would degenerate to quantity-only
            flat = y_train.reshape(len(y_train), -1)
            counts = np.stack(
                [(flat == c).sum(axis=1) for c in range(data["class_num"])], axis=1
            )
            fg = counts[:, 1:]
            part_labels = np.where(fg.max(axis=1) > 0, fg.argmax(axis=1) + 1, 0)
        elif kind == "graphreg":
            # continuous target: quartile-bin the property so the Dirichlet
            # split skews by target range (class_num is 1 for regression)
            t = y_train.reshape(len(y_train), -1)[:, 0]
            part_labels = np.digitize(t, np.quantile(t, [0.25, 0.5, 0.75]))
            train_map = non_iid_partition_with_dirichlet_distribution(
                part_labels, client_num, 4, alpha, seed=seed
            )
            part_labels = None  # handled
        elif kind in ("linkpred", "mtl_graph"):
            # labels carry -1 sentinels; bucket by positive-label count
            # (graph density / task profile), clipped to the class range
            pos = (y_train.reshape(len(y_train), -1) > 0).sum(axis=1)
            if kind == "linkpred":
                pos //= 2  # symmetric pairs: raw counts are always even
            part_labels = (pos % data["class_num"]).astype(int)
        elif kind == "s2s":
            # bucket by mean target token (ignore the -1 source positions)
            flat = y_train.reshape(len(y_train), -1)
            valid = flat >= 0
            mean_tok = (flat * valid).sum(axis=1) / np.maximum(valid.sum(axis=1), 1)
            part_labels = (mean_tok % data["class_num"]).astype(int)
        else:
            # NWP labels are sequences; bucket by sequence-mean token
            part_labels = (
                y_train.reshape(len(y_train), -1).mean(axis=1) % data["class_num"]
            ).astype(int)
        if part_labels is not None:
            train_map = non_iid_partition_with_dirichlet_distribution(
                part_labels, client_num, data["class_num"], alpha, seed=seed
            )
    elif method in ("homo", "iid"):
        train_map = homo_partition(len(y_train), client_num, seed=seed)
    elif method == "quantity_skew":
        train_map = quantity_skew_partition(len(y_train), client_num, alpha, seed=seed)
    else:
        raise ValueError(f"unknown partition_method {method!r}")
    test_map = homo_partition(len(y_test), client_num, seed=seed + 1)

    x_train, x_test = data["x_train"], data["x_test"]
    train_data_local_dict = {}
    test_data_local_dict = {}
    train_data_local_num_dict = {}
    for i in range(client_num):
        tr_idx, te_idx = train_map[i], test_map[i]
        train_data_local_dict[i] = (x_train[tr_idx], y_train[tr_idx])
        test_data_local_dict[i] = (x_test[te_idx], y_test[te_idx])
        train_data_local_num_dict[i] = int(len(tr_idx))

    dataset = [
        len(y_train),
        len(y_test),
        (x_train, y_train),
        (x_test, y_test),
        train_data_local_num_dict,
        train_data_local_dict,
        test_data_local_dict,
        data["class_num"],
    ]
    return dataset, data["class_num"]
