"""Cross-silo intra-silo data split (reference
``data/data_loader_cross_silo.py`` ``split_data_for_dist_trainers``): divide
a silo's local data across its intra-silo trainer ranks (the mesh-sharded
batch of the hierarchical scenario)."""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def split_data_for_dist_trainers(train_data: Tuple[np.ndarray, np.ndarray],
                                 n_proc_in_silo: int) -> List[Tuple[np.ndarray, np.ndarray]]:
    """(x, y) -> n near-equal shards (contiguous; order preserved)."""
    x, y = train_data
    n = max(int(n_proc_in_silo), 1)
    xs = np.array_split(np.asarray(x), n)
    ys = np.array_split(np.asarray(y), n)
    return list(zip(xs, ys))
