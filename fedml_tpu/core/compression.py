"""Update/gradient compression for communication-efficient FL.

Parity with reference ``utils/compression.py`` (NoneCompressor,
TopKCompressor + error-feedback EFTopK, QuantizationCompressor, QSGD):
the same five schemes, reformulated TPU-first —

* functional, pytree-level API (no name->residual mutable registries):
  ``compress_update`` returns the wire payload AND the new residual tree,
  so error feedback composes with jit and with checkpointing;
* per-leaf top-k via ``jax.lax.top_k`` on |x| (one fused kernel per leaf,
  no host-side sorting); quantizers are vectorized jnp ops with an explicit
  PRNG key for QSGD's stochastic rounding (reproducible rounds).

Wire format: a self-describing dict (``__fedml_compressed__`` marker) of
per-leaf (values, indices, shape) triples for top-k or dense quantized
leaves otherwise — picklable by every comm backend, decompressed
server-side by :func:`maybe_decompress_update`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

_MARKER = "__fedml_compressed__"

# largest flat index an int32 can address; beyond it top-k indices are int64
_INT32_MAX = 2**31 - 1


# ---------------------------------------------------------------------------
# leaf kernels
# ---------------------------------------------------------------------------

def topk_k(ratio: float, n: int) -> int:
    """Deterministic k for a top-``ratio`` selection over ``n`` entries.

    ``int(round(...))`` is banker's rounding: ``round(0.5) == 0`` but
    ``round(1.5) == 2``, so the kept fraction of a .5-boundary leaf
    drifts with its size (and with any platform that rounds half away
    from zero).  Half-up (``+ 0.5`` then truncate) is monotone in both
    arguments and identical everywhere."""
    return max(1, int(float(ratio) * int(n) + 0.5))


def topk_leaf(x: jnp.ndarray, ratio: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Keep the top ``ratio`` fraction of entries by |value|; returns
    (values [k], flat indices [k])."""
    flat = x.reshape(-1)
    k = topk_k(ratio, flat.shape[0])
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    # int32 flat indices silently wrap past 2^31-1 elements; huge embedding
    # leaves need the wide dtype (the wire cost is honest via wire_bytes)
    idx_dtype = jnp.int64 if flat.shape[0] > _INT32_MAX else jnp.int32
    return flat[idx], idx.astype(idx_dtype)


def quantize_leaf(x: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Deterministic norm-scaled level quantization (reference
    ``QuantizationCompressor.get_naive_quantize``)."""
    s = float(2 ** bits - 1)
    norm = jnp.linalg.norm(x.reshape(-1)).astype(jnp.float32)
    norm = jnp.maximum(norm, 1e-12)
    level = jnp.floor(s * jnp.abs(x) / norm)
    return jnp.sign(x) * norm * level / s


def qsgd_leaf(x: jnp.ndarray, bits: int, key: jax.Array,
              is_biased: bool = True) -> jnp.ndarray:
    """QSGD stochastic quantization (reference ``QSGDCompressor.get_qsgd``):
    floor plus a Bernoulli step so the value is preserved in expectation;
    the biased variant applies the variance-bound scale."""
    s = float(2 ** bits - 1)
    norm = jnp.linalg.norm(x.reshape(-1)).astype(jnp.float32)
    norm = jnp.maximum(norm, 1e-12)
    level_float = s * jnp.abs(x) / norm
    previous = jnp.floor(level_float)
    step = (jax.random.uniform(key, x.shape) < (level_float - previous)).astype(x.dtype)
    new_level = previous + step
    scale = 1.0
    if is_biased:
        d = float(x.size)
        scale = 1.0 / (min(d / (s ** 2), np.sqrt(d) / s) + 1.0)
    return scale * jnp.sign(x) * norm * new_level / s


# ---------------------------------------------------------------------------
# pytree API
# ---------------------------------------------------------------------------

def compress_update(
    tree: Pytree,
    method: str = "topk",
    ratio: float = 0.05,
    bits: int = 8,
    key: Optional[jax.Array] = None,
    residuals: Optional[Pytree] = None,
) -> Tuple[Dict[str, Any], Optional[Pytree]]:
    """Compress a model-update pytree for the wire.

    Returns ``(payload, new_residuals)``.  ``method``:
    ``none`` | ``topk`` | ``eftopk`` (error feedback: the dropped mass is
    carried in ``residuals`` and added before the next selection) |
    ``quantize`` | ``qsgd``.
    """
    method = method.lower()
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if method == "none":
        return {_MARKER: "none", "tree": tree}, residuals

    if method in ("topk", "eftopk"):
        res_leaves = (jax.tree_util.tree_leaves(residuals)
                      if residuals is not None else [None] * len(leaves))
        out, new_res = [], []
        for leaf, res in zip(leaves, res_leaves):
            leaf = jnp.asarray(leaf)
            work = leaf + res if (method == "eftopk" and res is not None) else leaf
            values, idx = topk_leaf(work, ratio)
            out.append((np.asarray(values), np.asarray(idx), tuple(leaf.shape),
                        str(leaf.dtype)))
            if method == "eftopk":
                kept = jnp.zeros(work.size, work.dtype).at[idx].set(values)
                new_res.append(work - kept.reshape(work.shape))
        payload = {_MARKER: method, "leaves": out,
                   "treedef": jax.tree_util.tree_structure(tree)}
        residuals_out = (jax.tree_util.tree_unflatten(treedef, new_res)
                         if method == "eftopk" else residuals)
        return payload, residuals_out

    if method in ("quantize", "qsgd"):
        if method == "qsgd" and key is None:
            key = jax.random.PRNGKey(0)
        out = []
        for i, leaf in enumerate(leaves):
            leaf = jnp.asarray(leaf)
            if not jnp.issubdtype(leaf.dtype, jnp.floating):
                out.append(np.asarray(leaf))
                continue
            if method == "quantize":
                q = quantize_leaf(leaf, bits)
            else:
                q = qsgd_leaf(leaf, bits, jax.random.fold_in(key, i))
            out.append(np.asarray(q))
        return {_MARKER: method, "leaves": out,
                "treedef": jax.tree_util.tree_structure(tree)}, residuals

    raise ValueError(f"unknown compression method {method!r}")


def decompress_update(payload: Dict[str, Any]) -> Pytree:
    method = payload[_MARKER]
    if method == "none":
        return payload["tree"]
    treedef = payload["treedef"]
    if method in ("topk", "eftopk"):
        leaves = []
        for values, idx, shape, dtype in payload["leaves"]:
            dense = np.zeros(int(np.prod(shape)), dtype=dtype)
            dense[idx] = values
            leaves.append(jnp.asarray(dense.reshape(shape)))
        return jax.tree_util.tree_unflatten(treedef, leaves)
    if method in ("quantize", "qsgd"):
        return jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(l) for l in payload["leaves"]]
        )
    raise ValueError(f"unknown compression method {method!r}")


def wire_bytes(payload: Any) -> int:
    """Honest payload size in bytes for a (possibly compressed) update.

    Counts the array bytes that actually ride the wire — dense leaves for
    ``none``/``quantize``/``qsgd``, (values + indices) pairs for
    ``topk``/``eftopk`` — and ignores framing/treedef overhead (shared by
    every scheme, so it cancels out of a comparison).  Accepts a raw
    pytree too, so codec negotiation can compare "as is" against each
    candidate scheme with one estimator.
    """
    def _nbytes(a: Any) -> int:
        arr = np.asarray(a)
        return int(arr.size) * int(arr.dtype.itemsize)

    if not is_compressed(payload):
        return int(sum(_nbytes(l) for l in jax.tree_util.tree_leaves(payload)))
    method = payload[_MARKER]
    if method == "none":
        return int(sum(_nbytes(l)
                       for l in jax.tree_util.tree_leaves(payload["tree"])))
    if method in ("topk", "eftopk"):
        return int(sum(_nbytes(values) + _nbytes(idx)
                       for values, idx, _shape, _dtype in payload["leaves"]))
    if method in ("quantize", "qsgd"):
        return int(sum(_nbytes(l) for l in payload["leaves"]))
    raise ValueError(f"unknown compression method {method!r}")


def is_compressed(obj: Any) -> bool:
    return isinstance(obj, dict) and _MARKER in obj


def maybe_decompress_update(obj: Any) -> Pytree:
    """Transparent receive-side hook: decompress if the payload carries the
    marker, else pass through unchanged."""
    return decompress_update(obj) if is_compressed(obj) else obj
