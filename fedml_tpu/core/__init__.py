"""Low-level API package (reference ``core/__init__.py`` parity): the
distributed kernel, algorithm frame, privacy/security, MPC, scheduling and
MLOps subsystems, re-exported for user code."""

from .aggregate import FedMLAggOperator
from .alg_frame.client_trainer import ClientTrainer
from .alg_frame.params import Params
from .alg_frame.server_aggregator import ServerAggregator
from .distributed.comm_manager import FedMLCommManager
from .distributed.communication.message import Message
from .distributed.flow import FedMLAlgorithmFlow, FedMLExecutor

__all__ = [
    "FedMLAggOperator",
    "ClientTrainer",
    "Params",
    "ServerAggregator",
    "FedMLCommManager",
    "Message",
    "FedMLAlgorithmFlow",
    "FedMLExecutor",
]
