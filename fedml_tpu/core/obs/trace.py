"""Deterministic cross-process tracing for the federated round protocol.

The reference framework's only timeline primitive is the wall-clock
start/end event pair (``core/mlops/mlops_profiler_event.py``) — no ids, no
parent/child structure, no propagation, so "where did round 17 spend its
time" is unanswerable once four transports, retransmits, and a server
restart are in play.  This module is the span layer under
``fedml_tpu.core.obs``:

* **Deterministic ids** — ``trace_id = H(run_id, round_idx)`` and
  ``span_id = H(trace_id, name, sender, seq)`` (SHA-256 prefixes, no
  wall-clock, no process randomness).  Every incarnation of the server
  derives the SAME id for round ``r``'s root span, which is what lets a
  crash-restarted server CLOSE the span its dead predecessor opened — the
  report pairs start/end by id, not by process.
* **W3C-style propagation** — ``00-<trace_id>-<span_id>-01`` rides as a
  plain string under ``Message.MSG_ARG_KEY_TRACEPARENT``; JSON transports
  keep strings and binary transports pickle the whole params dict, so one
  header covers LOOPBACK / TRPC / GRPC / MQTT_S3 with zero per-backend
  code.
* **Sink records, not objects** — a span is two flat records
  (``span_start`` / ``span_end`` topics) plus zero or more ``span_event``
  annotations, emitted through the mlops sink fan (JSONL / broker /
  in-memory).  ``tools/trace_report.py`` reconstructs the trees offline.

Durations are measured with ``time.monotonic()`` (wall time is sink
metadata only, added by the FanoutSink): the start-side monotonic stamp is
kept in-process and the end record carries the difference, so an NTP step
mid-round cannot produce a negative span.  Cross-process pairs (a restart
closing its predecessor's round span) carry no duration — the report falls
back to the records' wall timestamps for those.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from typing import Any, Callable, Dict, Optional

TOPIC_SPAN_START = "span_start"
TOPIC_SPAN_END = "span_end"
TOPIC_SPAN_EVENT = "span_event"

_TRACE_VERSION = "00"

# ambient span stack (per-thread): ``with tracer.span(...)`` pushes its
# context so library layers far below the call site (e.g. the compiled
# aggregation plane under FedMLAggOperator) can parent their spans without
# threading a ctx through every signature.  Entries are SpanContexts.
_ambient = threading.local()


def _ambient_stack() -> list:
    stack = getattr(_ambient, "stack", None)
    if stack is None:
        stack = []
        _ambient.stack = stack
    return stack


def active_ctx() -> Optional["SpanContext"]:
    """The innermost ``with``-entered span's context on this thread, or
    None.  Telemetry-only: callers use it as a default parent, never as a
    correctness input."""
    stack = getattr(_ambient, "stack", None)
    return stack[-1] if stack else None


def trace_id_for(run_id: Any, round_idx: int) -> str:
    """32-hex trace id: one trace per (run, round)."""
    h = hashlib.sha256(f"fedml-trace:{run_id}:{int(round_idx)}".encode())
    return h.hexdigest()[:32]


def span_id_for(trace_id: str, name: str, sender: Any = 0, seq: int = 0) -> str:
    """16-hex span id, deterministic in (trace, name, sender, seq)."""
    h = hashlib.sha256(f"{trace_id}:{name}:{sender}:{int(seq)}".encode())
    return h.hexdigest()[:16]


class SpanContext:
    """The propagated half of a span: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)

    def to_traceparent(self) -> str:
        return f"{_TRACE_VERSION}-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: Any) -> Optional["SpanContext"]:
        if not isinstance(header, str):
            return None
        parts = header.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return cls(parts[1], parts[2])

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, SpanContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __repr__(self) -> str:  # pragma: no cover
        return f"SpanContext({self.trace_id!r}, {self.span_id!r})"


def round_root_ctx(run_id: Any, round_idx: int) -> SpanContext:
    """The round root span's context, reconstructible by ANY node from
    (run_id, round_idx) alone — the fallback parent when a message arrived
    without a traceparent (legacy peer, fault-injected path)."""
    tid = trace_id_for(run_id, round_idx)
    return SpanContext(tid, span_id_for(tid, "round", 0, 0))


class Span:
    """One open span; emits ``span_start`` on creation, ``span_end`` on
    :meth:`end` (idempotent — a crash-recovery double close is harmless)."""

    def __init__(self, tracer: "Tracer", name: str, ctx: SpanContext,
                 parent_id: Optional[str], round_idx: Optional[int],
                 node: Any, attrs: Optional[Dict[str, Any]], annotate: bool,
                 emit_start: bool = True):
        self.tracer = tracer
        self.name = str(name)
        self.ctx = ctx
        self._t0 = time.monotonic()
        self._ended = False
        self._adopted = not emit_start
        self._ann = None
        if annotate:
            # make the protocol phase visible inside XLA/TensorBoard traces
            try:
                import jax.profiler

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # pragma: no cover - profiler unavailable
                self._ann = None
        rec: Dict[str, Any] = {
            "trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "name": self.name, "node": node,
        }
        if parent_id is not None:
            rec["parent_span_id"] = parent_id
        if round_idx is not None:
            rec["round_idx"] = int(round_idx)
        if attrs:
            rec.update(attrs)
        if emit_start:
            tracer._emit(TOPIC_SPAN_START, rec)

    def event(self, name: str, **attrs: Any) -> None:
        self.tracer.span_event(name, self.ctx, **attrs)

    def end(self, **attrs: Any) -> None:
        if self._ended:
            return
        self._ended = True
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        rec: Dict[str, Any] = {
            "trace_id": self.ctx.trace_id, "span_id": self.ctx.span_id,
            "name": self.name,
        }
        if self._adopted:
            # this process did not open the span (crash-restart adoption):
            # its monotonic origin is meaningless here, so the end record
            # carries no duration and the report falls back to wall ts
            rec["adopted"] = True
        else:
            rec["duration_s"] = round(time.monotonic() - self._t0, 6)
        if attrs:
            rec.update(attrs)
        self.tracer._emit(TOPIC_SPAN_END, rec)

    def __enter__(self) -> "Span":
        _ambient_stack().append(self.ctx)
        self._pushed = True
        return self

    def __exit__(self, *exc) -> None:
        if getattr(self, "_pushed", False):
            self._pushed = False
            stack = _ambient_stack()
            # pop by identity from the top: tolerant of out-of-order exits
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is self.ctx:
                    del stack[i]
                    break
        self.end()


class _NullSpan:
    """The disabled fast path: every operation is a no-op and ``ctx`` is
    None, so call sites never branch on ``obs.enabled()`` themselves."""

    ctx = None
    name = ""

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def end(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Span factory bound to one run and one emit function.

    ``emit`` is ``Sink.emit``-shaped (``(topic, record) -> None``); the obs
    facade hands it the mlops fan, so span records ride the same JSONL /
    broker / in-memory sinks as every other telemetry topic.  Emission
    failures are swallowed: observability must never take the run down.
    """

    def __init__(self, run_id: Any, emit: Callable[[str, Dict[str, Any]], None]):
        self.run_id = run_id
        self._emit_fn = emit
        self._lock = threading.Lock()
        self._seq: Dict[str, int] = {}

    def _emit(self, topic: str, rec: Dict[str, Any]) -> None:
        try:
            self._emit_fn(topic, rec)
        except Exception:  # pragma: no cover - sink failure is non-fatal
            pass

    def _next_seq(self, key: str) -> int:
        with self._lock:
            n = self._seq.get(key, 0)
            self._seq[key] = n + 1
            return n

    # -- span construction ---------------------------------------------------
    def round_span(self, round_idx: int, node: Any = 0,
                   annotate: bool = False, **attrs: Any) -> Span:
        """Open round ``round_idx``'s root span (the deterministic id every
        incarnation agrees on)."""
        ctx = round_root_ctx(self.run_id, round_idx)
        return Span(self, "round", ctx, None, round_idx, node, attrs, annotate)

    def adopt_round_span(self, round_idx: int, node: Any = 0) -> Span:
        """A handle on round ``round_idx``'s root WITHOUT re-emitting its
        start: a crash-restarted server derives the same deterministic id
        its dead predecessor opened, so the adopter's eventual ``end``
        pairs with the original ``span_start`` in the report."""
        ctx = round_root_ctx(self.run_id, round_idx)
        return Span(self, "round", ctx, None, round_idx, node, None,
                    annotate=False, emit_start=False)

    def span(self, name: str, parent: Optional[SpanContext],
             round_idx: Optional[int] = None, node: Any = 0, seq: int = 0,
             annotate: bool = False, **attrs: Any) -> Span:
        """Open a child span under ``parent`` (or under the deterministic
        round root when ``parent`` is None and ``round_idx`` is given)."""
        if parent is None and round_idx is not None:
            parent = round_root_ctx(self.run_id, round_idx)
        if parent is not None:
            tid = parent.trace_id
            parent_id = parent.span_id
        else:
            tid = trace_id_for(self.run_id, -1)
            parent_id = None
        ctx = SpanContext(tid, span_id_for(tid, name, node, seq))
        return Span(self, name, ctx, parent_id, round_idx, node, attrs, annotate)

    def unique_span(self, name: str, parent: Optional[SpanContext],
                    round_idx: Optional[int] = None, node: Any = 0,
                    annotate: bool = False, **attrs: Any) -> Span:
        """Like :meth:`span` but with a per-tracer occurrence counter mixed
        into the id — for spans that can legitimately repeat with identical
        (name, node) coordinates (e.g. retransmit attempts)."""
        seq = self._next_seq(f"{name}:{node}:{parent.span_id if parent else ''}")
        return self.span(name, parent, round_idx=round_idx, node=node,
                         seq=seq, annotate=annotate, **attrs)

    def span_event(self, name: str, ctx: Optional[SpanContext],
                   round_idx: Optional[int] = None, node: Any = 0,
                   **attrs: Any) -> None:
        """Attach a point-in-time event to ``ctx`` (fault injections,
        rejoins, recovery milestones).  With no ctx, falls back to the round
        root when ``round_idx`` is known, else drops the event — events are
        annotations, never load-bearing."""
        if ctx is None:
            if round_idx is None:
                return
            ctx = round_root_ctx(self.run_id, round_idx)
        rec: Dict[str, Any] = {
            "trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "event": str(name), "node": node,
        }
        if round_idx is not None:
            rec["round_idx"] = int(round_idx)
        if attrs:
            rec.update(attrs)
        self._emit(TOPIC_SPAN_EVENT, rec)


@contextlib.contextmanager
def null_context():
    yield NULL_SPAN
