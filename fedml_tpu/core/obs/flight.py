"""Flight recorder: a bounded ring of recent telemetry, dumped on trouble.

The JSONL sink tells you what happened *if the file survives and someone
kept it*; a crashed server's most valuable records are the last few
hundred before the crash, and under ``server_kill`` chaos those are
exactly the ones a supervisor restart scrolls past.  The recorder keeps a
fixed-capacity in-memory ring of every record the obs fan emits
(span_start / span_end / span_event / metrics / ...) and writes an atomic,
crc-framed JSONL snapshot when something goes wrong:

* ``server_kill`` / ``server_restore`` / ``slow_round`` span events (the
  obs facade's emit tap watches for them);
* an unhandled exception in a server manager's message handler
  (``comm_manager._dispatch`` calls :func:`fedml_tpu.core.obs.flight_dump`);
* any explicit ``obs.flight_dump(reason)`` call.

Frame format — one record per line, ``crc32_hex8 + " " + json``:

    1c291ca3 {"topic": "span_start", ...}

The crc covers the JSON payload bytes, so :meth:`FlightRecorder.load` can
drop a torn tail line (the dump itself is atomic, but operators also point
``load`` at live sink JSONL or partially copied files) and any line a text
editor mangled, without losing the rest.  Everything here is telemetry:
dump failures return ``None`` and never raise into the round path.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_FLIGHT_CAPACITY = 2048

# span events that trigger an automatic dump when seen on the emit tap
# (device_loss: the elastic topology fault — the ring around a lost chip is
# exactly the forensic window a remesh post-mortem needs;
# mid_message_disconnect / truncated_frame: the chunked-upload faults — the
# ring holds the chunk spans showing where in the stream the link died;
# health.watchdog_expired / health.anomaly: the health plane's reactions —
# a wedged worker or an out-of-band SLO series dumps the window that led
# up to it, with the health snapshot riding the dump meta)
DUMP_EVENTS = ("server_kill", "server_restore", "slow_round", "device_loss",
               "mid_message_disconnect", "truncated_frame",
               "health.watchdog_expired", "health.anomaly")

# hard cap on dumps per recorder: a slow-round storm must not turn the
# flight recorder into a disk-filling firehose
DEFAULT_MAX_DUMPS = 32

_REASON_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


def frame_line(rec: Dict[str, Any]) -> str:
    """One crc-framed line for ``rec`` (no trailing newline)."""
    payload = json.dumps(rec, sort_keys=True, default=str)
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {payload}"


def parse_line(line: str) -> Optional[Dict[str, Any]]:
    """The record behind one framed line, or None for a corrupt/torn line."""
    if len(line) < 10 or line[8] != " ":
        return None
    crc_hex, payload = line[:8], line[9:]
    try:
        want = int(crc_hex, 16)
    except ValueError:
        return None
    if zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF != want:
        return None
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


class FlightRecorder:
    """Fixed-capacity ring of ``(topic, record)`` telemetry + atomic dump.

    ``record`` is called from the obs emit tap on whatever thread emitted
    (round loop, upload handlers, retransmitter), so everything is under
    one lock and the per-record work is one dict copy + deque append.
    """

    def __init__(self, capacity: int = DEFAULT_FLIGHT_CAPACITY,
                 directory: Optional[str] = None, run_id: Any = "0",
                 max_dumps: int = DEFAULT_MAX_DUMPS):
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.directory = str(directory) if directory else None
        self.run_id = str(run_id)
        self.max_dumps = int(max_dumps)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._dropped = 0      # records aged out of the ring
        self._n_dumps = 0
        self._last_dump_path: Optional[str] = None
        # optional zero-arg callables returning extra dict keys for the dump
        # meta line (the telemetry merger hangs its merge counters on the
        # legacy single-slot attribute; the health plane adds its snapshot
        # via add_meta_provider); failures are swallowed — meta enrichment
        # must not cost a dump
        self.meta_provider = None
        self._meta_providers: List[Any] = []

    def add_meta_provider(self, provider: Any) -> None:
        """Register an additional dump-meta provider (zero-arg callable
        returning a dict); composes with the legacy single-slot
        ``meta_provider`` attribute, earlier keys winning ties."""
        self._meta_providers.append(provider)

    # -- recording -----------------------------------------------------------
    def record(self, topic: str, rec: Dict[str, Any]) -> Optional[str]:
        """Append one record; returns a dump *reason* when ``rec`` is a
        trigger event (the caller decides whether/when to dump so the
        trigger record itself is already in the ring)."""
        entry = dict(rec)
        entry["topic"] = str(topic)
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(entry)
        if topic == "span_event" and rec.get("event") in DUMP_EVENTS:
            return str(rec["event"])
        return None

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    @property
    def n_dumps(self) -> int:
        with self._lock:
            return self._n_dumps

    @property
    def last_dump_path(self) -> Optional[str]:
        with self._lock:
            return self._last_dump_path

    # -- dumping -------------------------------------------------------------
    def dump(self, reason: str) -> Optional[str]:
        """Atomically write the ring as crc-framed JSONL; returns the dump
        path, or None when no directory is configured, the dump budget is
        exhausted, or the write fails (telemetry never raises)."""
        with self._lock:
            if self.directory is None or self._n_dumps >= self.max_dumps:
                return None
            self._n_dumps += 1
            seq = self._n_dumps
            records = list(self._ring)
            dropped = self._dropped
        safe = _REASON_SAFE.sub("_", str(reason)) or "dump"
        meta = {
            "topic": "flight_meta", "reason": str(reason),
            "run_id": self.run_id, "seq": seq, "n_records": len(records),
            "capacity": self.capacity, "dropped": dropped,
        }
        for provider in [self.meta_provider] + list(self._meta_providers):
            if provider is None:
                continue
            try:
                extra = provider()
                if isinstance(extra, dict):
                    for k, v in extra.items():
                        meta.setdefault(str(k), v)
            except Exception:
                pass
        name = f"flight-{self.run_id}-{seq:03d}-{safe}.jsonl"
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                f.write("\n".join(
                    [frame_line(meta)] + [frame_line(r) for r in records]
                ) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            try:  # directory entry durability, best-effort
                dfd = os.open(self.directory, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        with self._lock:
            self._last_dump_path = path
        return path

    # -- reloading -----------------------------------------------------------
    @staticmethod
    def load(path: str) -> Tuple[List[Dict[str, Any]], int]:
        """Parse a dump tolerantly: returns ``(records, n_bad_lines)``.
        Corrupt or truncated lines (crc mismatch, torn json) are counted and
        skipped — a partial dump still yields every intact record."""
        records: List[Dict[str, Any]] = []
        n_bad = 0
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                rec = parse_line(line)
                if rec is None:
                    n_bad += 1
                else:
                    records.append(rec)
        return records, n_bad
