"""Round-span bookkeeping shared by the message-plane server managers.

The cross-silo and cross-device servers drive structurally identical round
state machines (open → invite fan-out → collect uploads → aggregate →
broadcast/close); this mixin holds the one copy of the span bookkeeping so
each manager's instrumentation stays a handful of ``with`` blocks.

Host requirements: ``self.args`` (with ``round_idx``) and — optionally —
``self.rank`` for node labeling.  Every helper degrades to
:data:`~.trace.NULL_SPAN` when tracing is off, so call sites never branch
on ``obs.enabled()``.

Crash-restart contract: a restored server calls :meth:`_obs_adopt_round`
instead of :meth:`_obs_open_round` — it holds the restored round's root
WITHOUT re-emitting ``span_start`` (ids are deterministic in
``(run_id, round_idx)``, so the adopter's eventual end pairs with the dead
incarnation's start and chaos runs still report zero unclosed spans).
"""

from __future__ import annotations

from typing import Any, Optional

from . import enabled, run_id, span, tracer
from .trace import NULL_SPAN, SpanContext, round_root_ctx


class RoundObsMixin:
    # class-level default so managers need no extra __init__ wiring
    _obs_round = None

    def _obs_node(self) -> int:
        return int(getattr(self, "rank", 0) or 0)

    def _obs_open_round(self, **attrs: Any) -> None:
        """Open the root span for ``args.round_idx`` (no-op when off)."""
        if not enabled():
            self._obs_round = None
            return
        t = tracer()
        self._obs_round = t.round_span(int(self.args.round_idx),
                                       node=self._obs_node(), **attrs)

    def _obs_adopt_round(self) -> None:
        """Hold the restored round's root without re-emitting its start."""
        t = tracer()
        if t is None:
            self._obs_round = None
            return
        self._obs_round = t.adopt_round_span(int(self.args.round_idx),
                                             node=self._obs_node())

    def _obs_round_ctx(self) -> Optional[SpanContext]:
        """The current round root's context — derived deterministically even
        when no local Span object is held (a handler racing round open)."""
        sp = self._obs_round
        if sp is not None and sp.ctx is not None:
            return sp.ctx
        if enabled():
            return round_root_ctx(run_id(), int(self.args.round_idx))
        return None

    def _obs_phase(self, name: str, parent: Optional[SpanContext] = None,
                   round_idx: Optional[int] = None, seq: int = 0,
                   **attrs: Any):
        """A child span of the current round root (or of ``parent``)."""
        if not enabled():
            return NULL_SPAN
        return span(
            name,
            parent if parent is not None else self._obs_round_ctx(),
            round_idx=int(self.args.round_idx if round_idx is None
                          else round_idx),
            node=self._obs_node(), seq=seq, **attrs)

    def _obs_close_round(self, **attrs: Any) -> None:
        sp = self._obs_round
        self._obs_round = None
        if sp is not None:
            sp.end(**attrs)
