"""Live health & SLO plane: watchdogs, rolling anomaly windows, reactions.

Everything under ``core/obs`` so far is post-hoc: traces, metrics, and the
flight recorder tell you what happened after the round closes — or never,
if a long-lived worker wedges.  This module is the real-time half: a
:class:`HealthPlane` that rides the existing emit tap and MetricsRegistry
and maintains three kinds of live state:

* **Watchdogs** — every long-lived worker (ingest dispatch worker, journal
  group-commit committer, chunk pump threads, edge flush loop, async flush
  scheduler, metrics exporter thread) registers a named :class:`Watchdog`
  and calls ``beat()`` from its loop.  A heartbeat-mode watchdog expires
  when it is *armed* and no beat has landed within ``deadline_s`` on the
  plane's clock; a thread-mode watchdog (for workers that legitimately
  block forever, like the exporter's ``serve_forever``) expires the moment
  its thread is no longer alive.  Expiry raises a
  ``health.watchdog_expired`` span event — a dump trigger — instead of the
  round silently hanging.  ``idle()`` disarms (a committer waiting on an
  empty queue is not wedged); a beat after expiry emits
  ``health.watchdog_recovered``.
* **Rolling SLO windows** — EWMA mean/variance per series with z-score
  firing (``|x - μ| / σ > z`` after ``warmup`` samples).  Feeds come from
  the emit tap (round span durations), explicit ``observe()`` calls, and
  per-tick registry pulls (``ingest.queue_depth`` gauge,
  ``journal.fsync_seconds`` / ``round.seconds`` histogram delta means,
  straggler fraction from the population counters).  A window fires a
  structured ``health.anomaly`` event ONCE on the transition out of band
  and re-arms only after ``recover_ticks`` consecutive in-band samples —
  one flight dump per incident, not one per sample.
* **Silence monitors** — the inverse of a heartbeat: ``note()`` marks
  activity (a chunk ack, an edge forward) and a tick finds the age past
  ``max_age_s`` while armed, firing a ``health.anomaly`` with
  ``kind="silence"`` (chunk-stream stall, mute edge aggregator).

A tick folds all three into a :data:`STATUS_OK` / :data:`STATUS_DEGRADED`
/ :data:`STATUS_CRITICAL` state machine (critical = any expired watchdog;
degraded = any firing window or silence; recovery requires
``recover_ticks`` clean ticks), mirrored to the ``fedml_health_status``
gauge and the exporter's ``/healthz`` endpoint.  Status transitions emit
``health.status`` events.

Determinism: the plane holds NO thread of its own.  All checks run inside
``tick()`` on whatever thread calls it (the round-close
``maybe_export_metrics`` path in production, the test body under a
:class:`~fedml_tpu.core.async_fl.clock.ManualClock` in chaos legs), and
all time arithmetic uses the injected clock — so every expiry and anomaly
in the chaos plan fires on an exact schedule.  Everything here is
telemetry: events are annotations, emission failures are swallowed, and
with ``obs_health`` off the facade hands out :data:`NULL_WATCHDOG` /
:data:`NULL_SILENCE` so call sites stay branch-free and the run is
bit-identical.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..async_fl.clock import MonotonicClock

STATUS_OK = "ok"
STATUS_DEGRADED = "degraded"
STATUS_CRITICAL = "critical"
STATUS_CODE = {STATUS_OK: 0, STATUS_DEGRADED: 1, STATUS_CRITICAL: 2}

# the exposition gauge name (already exposition-legal: no sanitizing drift
# between the registry name and the scrape name)
HEALTH_STATUS_GAUGE = "fedml_health_status"

# span-event names; the first two are flight-dump triggers (DUMP_EVENTS)
EVENT_WATCHDOG_EXPIRED = "health.watchdog_expired"
EVENT_ANOMALY = "health.anomaly"
EVENT_WATCHDOG_RECOVERED = "health.watchdog_recovered"
EVENT_RECOVERED = "health.recovered"
EVENT_STATUS = "health.status"

DEFAULT_WATCHDOG_DEADLINE_S = 30.0
DEFAULT_Z_THRESHOLD = 4.0
DEFAULT_EWMA_ALPHA = 0.3
DEFAULT_WARMUP_SAMPLES = 8
DEFAULT_RECOVER_TICKS = 3

# keep an unemittable backlog bounded when no emitter is attached yet
# (standalone plane in tests, configure() mid-flight)
_MAX_PENDING = 256


class _NullHandle:
    """The disabled fast path: ``beat`` / ``idle`` / ``note`` / ``close``
    are all no-ops, so wired subsystems never branch on whether the health
    plane is configured."""

    name = ""

    def beat(self) -> None:
        pass

    def idle(self) -> None:
        pass

    def note(self) -> None:
        pass

    def close(self) -> None:
        pass


NULL_WATCHDOG = _NullHandle()
NULL_SILENCE = _NullHandle()


class Watchdog:
    """One registered liveness contract.  Heartbeat mode (``thread`` is
    None): expired iff armed and the last beat is older than
    ``deadline_s``.  Thread mode: expired iff the registered thread is no
    longer alive — for workers whose loop legitimately blocks forever.
    All mutation goes through the owning plane (one lock, events drained
    outside it)."""

    __slots__ = ("name", "deadline_s", "thread", "armed", "last_beat",
                 "expired", "expirations", "closed", "_plane")

    def __init__(self, plane: "HealthPlane", name: str, deadline_s: float,
                 thread: Optional[threading.Thread] = None):
        self._plane = plane
        self.name = str(name)
        self.deadline_s = float(deadline_s)
        self.thread = thread
        self.armed = thread is not None  # thread mode is always armed
        self.last_beat: Optional[float] = None
        self.expired = False
        self.expirations = 0
        self.closed = False

    def beat(self) -> None:
        self._plane._beat(self)

    def idle(self) -> None:
        self._plane._idle(self)

    def close(self) -> None:
        self._plane._close_watchdog(self)

    @property
    def mode(self) -> str:
        return "thread" if self.thread is not None else "heartbeat"


class SilenceMonitor:
    """Fires a ``health.anomaly`` (``kind="silence"``) when an expected
    activity stream goes quiet for more than ``max_age_s`` while armed."""

    __slots__ = ("series", "max_age_s", "armed", "firing", "last_note",
                 "fired", "closed", "_plane")

    def __init__(self, plane: "HealthPlane", series: str, max_age_s: float):
        self._plane = plane
        self.series = str(series)
        self.max_age_s = float(max_age_s)
        self.armed = False
        self.firing = False
        self.last_note: Optional[float] = None
        self.fired = 0
        self.closed = False

    def note(self) -> None:
        self._plane._note(self)

    def idle(self) -> None:
        self._plane._silence_idle(self)

    def close(self) -> None:
        self._plane._close_silence(self)


class _Window:
    """EWMA mean/variance over one series with z-score firing."""

    __slots__ = ("series", "n", "mean", "var", "last", "firing", "clean",
                 "fired")

    def __init__(self, series: str):
        self.series = str(series)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0
        self.last = 0.0
        self.firing = False
        self.clean = 0
        self.fired = 0

    def std(self) -> float:
        return math.sqrt(self.var) if self.var > 0 else 0.0


class HealthPlane:
    """The live health state machine.  Passive: no threads, no timers —
    ``tick()`` (round-close cadence in production, explicit in tests) is
    the only place watchdogs/silences are checked and status recomputed,
    which is what makes the chaos legs deterministic under a ManualClock.

    ``emitter`` is a ``(event_name, attrs_dict) -> None`` callable the obs
    facade points at the tracer; events raised while it is unset queue (up
    to a bound) and drain on the next call."""

    def __init__(self, registry: Any = None, clock: Any = None, *,
                 z_threshold: float = DEFAULT_Z_THRESHOLD,
                 ewma_alpha: float = DEFAULT_EWMA_ALPHA,
                 watchdog_deadline_s: float = DEFAULT_WATCHDOG_DEADLINE_S,
                 warmup: int = DEFAULT_WARMUP_SAMPLES,
                 recover_ticks: int = DEFAULT_RECOVER_TICKS):
        if not (z_threshold > 0):
            raise ValueError(f"z_threshold must be > 0, got {z_threshold}")
        if not (0 < ewma_alpha <= 1):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        if not (watchdog_deadline_s > 0):
            raise ValueError(
                f"watchdog_deadline_s must be > 0, got {watchdog_deadline_s}")
        self._registry = registry
        self.clock = clock if clock is not None else MonotonicClock()
        self.z_threshold = float(z_threshold)
        self.ewma_alpha = float(ewma_alpha)
        self.watchdog_deadline_s = float(watchdog_deadline_s)
        self.warmup = max(2, int(warmup))
        self.recover_ticks = max(1, int(recover_ticks))
        self.emitter: Optional[Callable[[str, Dict[str, Any]], None]] = None
        self._lock = threading.Lock()
        self._watchdogs: Dict[str, Watchdog] = {}
        self._silences: Dict[str, SilenceMonitor] = {}
        self._windows: Dict[str, _Window] = {}
        self._pending: List[Tuple[str, Dict[str, Any]]] = []
        self._status = STATUS_OK
        self._clean_streak = 0
        self._ticks = 0
        self.events_emitted = 0
        self.last_round_idx = 0
        # per-tick delta cursors for the registry feeds
        self._hist_cursor: Dict[str, Tuple[float, float]] = {}
        self._pop_cursor = (0.0, 0.0)  # (invited, reported)

    # -- registration --------------------------------------------------------
    def register(self, name: str, deadline_s: Optional[float] = None,
                 thread: Optional[threading.Thread] = None) -> Watchdog:
        """Register (or re-register) the named watchdog.  Re-registration
        replaces the old handle — a restarted worker gets a fresh,
        unexpired contract."""
        wd = Watchdog(self, str(name),
                      self.watchdog_deadline_s if deadline_s is None
                      else float(deadline_s),
                      thread=thread)
        now = self.clock.now()
        with self._lock:
            wd.last_beat = now
            self._watchdogs[wd.name] = wd
        return wd

    def silence(self, series: str,
                max_age_s: Optional[float] = None) -> SilenceMonitor:
        """The silence monitor for ``series`` (created on first use, shared
        after — multiple producers may ``note()`` the same stream)."""
        key = str(series)
        with self._lock:
            mon = self._silences.get(key)
            if mon is None or mon.closed:
                mon = SilenceMonitor(
                    self, key,
                    self.watchdog_deadline_s if max_age_s is None
                    else float(max_age_s))
                self._silences[key] = mon
            return mon

    # -- watchdog mutations (called via the handle) --------------------------
    def _beat(self, wd: Watchdog) -> None:
        now = self.clock.now()
        with self._lock:
            if wd.closed:
                return
            wd.last_beat = now
            if wd.thread is None:
                wd.armed = True
            if wd.expired:
                wd.expired = False
                self._queue(EVENT_WATCHDOG_RECOVERED,
                            {"watchdog": wd.name, "mode": wd.mode})
        self._drain()

    def _idle(self, wd: Watchdog) -> None:
        with self._lock:
            if wd.closed or wd.thread is not None:
                return  # thread mode has no idle state
            wd.armed = False
            wd.expired = False

    def _close_watchdog(self, wd: Watchdog) -> None:
        with self._lock:
            wd.closed = True
            wd.armed = False
            wd.expired = False
            if self._watchdogs.get(wd.name) is wd:
                del self._watchdogs[wd.name]

    # -- silence mutations ---------------------------------------------------
    def _note(self, mon: SilenceMonitor) -> None:
        now = self.clock.now()
        with self._lock:
            if mon.closed:
                return
            mon.last_note = now
            mon.armed = True
            if mon.firing:
                mon.firing = False
                self._queue(EVENT_RECOVERED,
                            {"series": mon.series, "kind": "silence"})
        self._drain()

    def _silence_idle(self, mon: SilenceMonitor) -> None:
        with self._lock:
            mon.armed = False
            mon.firing = False

    def _close_silence(self, mon: SilenceMonitor) -> None:
        with self._lock:
            mon.closed = True
            mon.armed = False
            mon.firing = False
            if self._silences.get(mon.series) is mon:
                del self._silences[mon.series]

    # -- rolling windows -----------------------------------------------------
    def observe(self, series: str, value: float) -> None:
        """Push one sample into ``series``'s EWMA window (creating it on
        first sight); may fire a ``health.anomaly`` on the out-of-band
        transition."""
        with self._lock:
            self._observe_locked(str(series), float(value))
        self._drain()

    def _observe_locked(self, series: str, value: float) -> None:
        w = self._windows.get(series)
        if w is None:
            w = _Window(series)
            self._windows[series] = w
        w.last = value
        out = False
        if w.n >= self.warmup:
            std = w.std()
            z = (value - w.mean) / std if std > 0 else 0.0
            out = abs(z) > self.z_threshold
            if out and not w.firing:
                w.firing = True
                w.clean = 0
                w.fired += 1
                self._queue(EVENT_ANOMALY, {
                    "series": series, "kind": "zscore",
                    "value": round(value, 6), "z": round(z, 3),
                    "mean": round(w.mean, 6), "std": round(std, 6),
                    "n": w.n, "threshold": self.z_threshold,
                })
            elif w.firing:
                if out:
                    w.clean = 0
                else:
                    w.clean += 1
                    if w.clean >= self.recover_ticks:
                        w.firing = False
                        self._queue(EVENT_RECOVERED,
                                    {"series": series, "kind": "zscore"})
        # EWMA update AFTER the test: the anomalous sample still folds in,
        # so a sustained level shift becomes the new normal and recovers
        d = value - w.mean
        w.mean += self.ewma_alpha * d
        w.var = (1.0 - self.ewma_alpha) * (w.var + self.ewma_alpha * d * d)
        w.n += 1

    # -- the emit tap --------------------------------------------------------
    def tap(self, emit: Callable[[str, Dict[str, Any]], None]
            ) -> Callable[[str, Dict[str, Any]], None]:
        """Wrap a sink emit so the plane sees every record (round-span
        durations feed the latency window; round_idx anchors health
        events).  The plane's OWN events pass through unobserved —
        that, plus atomic pending-drains, is what keeps the tap
        reentrancy-safe when a drain fires mid-emit."""
        def health_tapped(topic: str, rec: Dict[str, Any]) -> None:
            try:
                self.observe_record(topic, rec)
            except Exception:  # telemetry never blocks the sink
                pass
            emit(topic, rec)
        return health_tapped

    def observe_record(self, topic: str, rec: Dict[str, Any]) -> None:
        """One emit-stream record: feed the windows it maps to."""
        if topic == "span_event" and str(rec.get("event", "")).startswith(
                "health."):
            return
        ridx = rec.get("round_idx")
        if ridx is not None:
            try:
                self.last_round_idx = int(ridx)
            except (TypeError, ValueError):
                pass
        if topic == "span_end" and rec.get("name") == "round":
            dur = rec.get("duration_s")
            if dur is not None:
                self.observe("round.seconds", float(dur))

    # -- registry feeds (pulled per tick) ------------------------------------
    def _pull_registry_feeds(self) -> List[Tuple[str, float]]:
        reg = self._registry
        if reg is None:
            return []
        out: List[Tuple[str, float]] = []
        try:
            if reg.series_count("ingest.queue_depth"):
                out.append(("ingest.queue_depth",
                            float(reg.get_gauge("ingest.queue_depth"))))
            for hist in ("journal.fsync_seconds", "round.seconds"):
                h = reg.get_histogram(hist)
                if h is None:
                    continue
                prev_sum, prev_count = self._hist_cursor.get(hist, (0.0, 0.0))
                d_count = h["count"] - prev_count
                if d_count > 0:
                    mean = (h["sum"] - prev_sum) / d_count
                    # the tap already feeds round.seconds from span ends;
                    # the histogram delta covers the sims that only
                    # observe the metric — same series, same unit
                    out.append((hist, float(mean)))
                self._hist_cursor[hist] = (h["sum"], h["count"])
            invited = float(reg.get_counter("population.invited"))
            reported = float(reg.get_counter("population.reported"))
            p_inv, p_rep = self._pop_cursor
            d_inv, d_rep = invited - p_inv, reported - p_rep
            if d_inv > 0:
                out.append(("straggler.fraction",
                            max(0.0, (d_inv - d_rep) / d_inv)))
                self._pop_cursor = (invited, reported)
        except Exception:  # a torn registry read must not kill the tick
            pass
        return out

    # -- the tick ------------------------------------------------------------
    def tick(self) -> str:
        """Run every check against ``clock.now()``: registry feeds,
        watchdog deadlines, silence ages, then the status fold.  Returns
        the (possibly new) status."""
        feeds = self._pull_registry_feeds()
        now = self.clock.now()
        with self._lock:
            for series, value in feeds:
                self._observe_locked(series, value)
            for wd in list(self._watchdogs.values()):
                if wd.thread is not None:
                    if wd.thread.is_alive():
                        wd.last_beat = now
                    elif not wd.expired:
                        wd.expired = True
                        wd.expirations += 1
                        self._queue(EVENT_WATCHDOG_EXPIRED, {
                            "watchdog": wd.name, "mode": "thread",
                            "deadline_s": wd.deadline_s,
                        })
                elif (wd.armed and not wd.expired
                        and wd.last_beat is not None
                        and now - wd.last_beat > wd.deadline_s):
                    wd.expired = True
                    wd.expirations += 1
                    self._queue(EVENT_WATCHDOG_EXPIRED, {
                        "watchdog": wd.name, "mode": "heartbeat",
                        "age_s": round(now - wd.last_beat, 6),
                        "deadline_s": wd.deadline_s,
                    })
            for mon in list(self._silences.values()):
                if (mon.armed and not mon.firing
                        and mon.last_note is not None
                        and now - mon.last_note > mon.max_age_s):
                    mon.firing = True
                    mon.fired += 1
                    self._queue(EVENT_ANOMALY, {
                        "series": mon.series, "kind": "silence",
                        "age_s": round(now - mon.last_note, 6),
                        "max_age_s": mon.max_age_s,
                    })
            self._ticks += 1
            status = self._fold_status_locked()
        if self._registry is not None:
            try:
                self._registry.gauge_set(HEALTH_STATUS_GAUGE,
                                         float(STATUS_CODE[status]))
            except Exception:
                pass
        self._drain()
        return status

    def _fold_status_locked(self) -> str:
        if any(wd.expired for wd in self._watchdogs.values()):
            target = STATUS_CRITICAL
        elif (any(w.firing for w in self._windows.values())
                or any(m.firing for m in self._silences.values())):
            target = STATUS_DEGRADED
        else:
            target = STATUS_OK
        cur = self._status
        if STATUS_CODE[target] >= STATUS_CODE[cur]:
            self._clean_streak = 0
            new = target
        else:
            # recovery hysteresis: hold the worse status until
            # recover_ticks consecutive clean ticks
            self._clean_streak += 1
            new = target if self._clean_streak >= self.recover_ticks else cur
            if new != cur:
                self._clean_streak = 0
        if new != cur:
            self._queue(EVENT_STATUS, {
                "from": cur, "to": new, "code": STATUS_CODE[new]})
            self._status = new
        return self._status

    # -- event plumbing ------------------------------------------------------
    def _queue(self, name: str, attrs: Dict[str, Any]) -> None:
        # caller holds self._lock
        if len(self._pending) >= _MAX_PENDING:
            del self._pending[0]
        self._pending.append((name, attrs))

    def _drain(self) -> None:
        emitter = self.emitter
        if emitter is None:
            return
        while True:
            with self._lock:
                if not self._pending:
                    return
                batch, self._pending = self._pending, []
            for name, attrs in batch:
                try:
                    emitter(name, attrs)
                except Exception:
                    pass
                self.events_emitted += 1

    # -- introspection -------------------------------------------------------
    @property
    def status(self) -> str:
        return self._status

    @property
    def status_code(self) -> int:
        return STATUS_CODE[self._status]

    def snapshot(self) -> Dict[str, Any]:
        """The full health state (the report tool's input and the
        exporter's ``/healthz`` + final-snapshot body)."""
        now = self.clock.now()
        with self._lock:
            watchdogs = {
                wd.name: {
                    "mode": wd.mode, "armed": wd.armed,
                    "expired": wd.expired, "expirations": wd.expirations,
                    "deadline_s": wd.deadline_s,
                    "last_beat_age_s": (None if wd.last_beat is None
                                        else round(now - wd.last_beat, 6)),
                } for wd in self._watchdogs.values()}
            silences = {
                m.series: {
                    "armed": m.armed, "firing": m.firing, "fired": m.fired,
                    "max_age_s": m.max_age_s,
                    "age_s": (None if m.last_note is None
                              else round(now - m.last_note, 6)),
                } for m in self._silences.values()}
            windows = {
                w.series: {
                    "n": w.n, "mean": round(w.mean, 6),
                    "std": round(w.std(), 6), "last": round(w.last, 6),
                    "firing": w.firing, "fired": w.fired,
                } for w in self._windows.values()}
            return {
                "schema": "fedml-health-1",
                "status": self._status,
                "status_code": STATUS_CODE[self._status],
                "ticks": self._ticks,
                "events_emitted": self.events_emitted,
                "watchdogs": watchdogs,
                "silences": silences,
                "windows": windows,
            }

    def snapshot_compact(self) -> Dict[str, Any]:
        """The few keys worth spending flight-dump meta bytes on."""
        with self._lock:
            return {
                "status": self._status,
                "status_code": STATUS_CODE[self._status],
                "ticks": self._ticks,
                "expired_watchdogs": sorted(
                    wd.name for wd in self._watchdogs.values() if wd.expired),
                "firing_series": sorted(
                    [w.series for w in self._windows.values() if w.firing]
                    + [m.series for m in self._silences.values()
                       if m.firing]),
            }
