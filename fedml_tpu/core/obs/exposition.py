"""OpenMetrics-style exposition for the :class:`MetricsRegistry`.

The registry's native export is sink-shaped (flat JSON records on the
``metrics`` topic, flushed on an interval) — fine for offline analysis,
useless for a long-lived cross-silo server an operator wants to *scrape*.
This module is the one rendering path from registry state to the
Prometheus/OpenMetrics text format, plus the two delivery mechanisms:

* :func:`render_openmetrics` — deterministic text rendering of every
  family: counters as ``name_total``, gauges as ``name``, histograms as
  cumulative ``name_bucket{le="..."}`` + ``name_sum`` / ``name_count``.
  Metric names are sanitized (``agg.step_seconds`` →
  ``agg_step_seconds``); label values are escaped per the spec
  (backslash, double-quote, newline).  Cardinality-cap overflow series
  render like any other series (their ``overflow="true"`` label is the
  marker) and per-family drop counts surface as one
  ``fedml_metric_dropped_series`` gauge family.
* :class:`MetricsExporter` — an optional stdlib ``ThreadingHTTPServer``
  pull endpoint (``GET /metrics``) on a daemon thread plus atomic file
  snapshots, both rendering the live registry.  ``shutdown`` is
  idempotent and writes a final snapshot so a finished run leaves its
  last state on disk.

``tools/lint_obs.py`` forbids calling :func:`render_openmetrics` outside
``core/obs`` — the exporter is the single exposition path, so overhead
stays accounted by bench.py's obs-overhead keys.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")

# one synthetic gauge family carrying every family's cardinality-cap drop
# count (labeled by the original metric name), rendered after the real
# families so scrapes can alert on label explosions
DROPPED_SERIES_METRIC = "fedml_metric_dropped_series"


def sanitize_metric_name(name: str) -> str:
    """A legal exposition metric name: bad chars (``.`` most commonly)
    become ``_``; a leading digit gets an underscore prefix."""
    out = _NAME_BAD.sub("_", str(name))
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value: Any) -> str:
    return (str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _unescape_label_value(value: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _fmt_value(v: Any) -> str:
    """Exact round-trip formatting: ints as ints, floats via ``repr`` (the
    shortest string that parses back to the same float)."""
    if isinstance(v, bool):  # pragma: no cover - registries never store bools
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _labels_text(labels: Dict[str, Any],
                 extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = [(str(k), str(v)) for k, v in sorted(labels.items())]
    if extra:
        pairs.extend(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in pairs)
    return "{" + body + "}"


def render_openmetrics(registry: MetricsRegistry) -> str:
    """The registry's full state in OpenMetrics text format, deterministic
    in content (families and series render in sorted order)."""
    records = registry.export()
    by_family: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:  # export() is already (family, label-key) sorted
        by_family.setdefault(rec["metric"], []).append(rec)

    lines: List[str] = []
    dropped: List[Tuple[str, int]] = []
    for name in sorted(by_family):
        recs = by_family[name]
        kind = recs[0]["kind"]
        sname = sanitize_metric_name(name)
        lines.append(f"# TYPE {sname} {kind}")
        for rec in recs:
            labels = rec.get("labels", {})
            if kind == "counter":
                lines.append(f"{sname}_total{_labels_text(labels)} "
                             f"{_fmt_value(rec['value'])}")
            elif kind == "gauge":
                lines.append(f"{sname}{_labels_text(labels)} "
                             f"{_fmt_value(rec['value'])}")
            else:  # histogram: registry buckets are per-bin, wire is cumulative
                cum = 0
                bounds = list(rec["buckets"]) + [None]
                for ub, n in zip(bounds, rec["bucket_counts"]):
                    cum += n
                    le = "+Inf" if ub is None else _fmt_value(float(ub))
                    lines.append(
                        f"{sname}_bucket"
                        f"{_labels_text(labels, extra=[('le', le)])} {cum}")
                lines.append(f"{sname}_sum{_labels_text(labels)} "
                             f"{_fmt_value(rec['sum'])}")
                lines.append(f"{sname}_count{_labels_text(labels)} "
                             f"{_fmt_value(rec['count'])}")
        n_dropped = recs[0].get("dropped_series", 0)
        if n_dropped:
            dropped.append((name, int(n_dropped)))
    if dropped:
        lines.append(f"# TYPE {DROPPED_SERIES_METRIC} gauge")
        for name, n in dropped:
            lines.append(
                f"{DROPPED_SERIES_METRIC}"
                f"{_labels_text({'metric': name})} {n}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str) -> Dict[str, Any]:
    """Minimal parser for the renderer's output (round-trip tests, gate
    tooling).  Returns ``{"types": {name: kind}, "samples": {(sample_name,
    ((label, value), ...)): float}}``."""
    types: Dict[str, str] = {}
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        name, labels, value = _parse_sample(line)
        samples[(name, labels)] = value
    return {"types": types, "samples": samples}


def _parse_sample(line: str) -> Tuple[str, Tuple[Tuple[str, str], ...], float]:
    brace = line.find("{")
    if brace < 0:
        name, _, val = line.partition(" ")
        return name, (), float(val)
    name = line[:brace]
    labels: List[Tuple[str, str]] = []
    i = brace + 1
    while i < len(line) and line[i] != "}":
        eq = line.index("=", i)
        key = line[i:eq]
        assert line[eq + 1] == '"', f"malformed label in {line!r}"
        j = eq + 2
        buf: List[str] = []
        while line[j] != '"':
            if line[j] == "\\":
                buf.append(line[j:j + 2])
                j += 2
            else:
                buf.append(line[j])
                j += 1
        labels.append((key, _unescape_label_value("".join(buf))))
        i = j + 1
        if i < len(line) and line[i] == ",":
            i += 1
    val = line[i + 1:].strip()
    return name, tuple(labels), float(val)


def _atomic_write_text(path: str, text: str) -> None:
    tmp = path + ".tmp"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class MetricsExporter:
    """Pull endpoint + file snapshots over one registry.

    ``port``: None disables HTTP; 0 binds an ephemeral localhost port
    (tests); >0 binds that port.  ``snapshot_path``: None disables file
    snapshots.  Both render the *live* registry at request/snapshot time.
    ``health_provider``: optional zero-arg callable returning the health
    plane's snapshot dict; when set, ``GET /healthz`` serves it as JSON
    with 200 for ok/degraded and 503 for critical (external probes key on
    the code, dashboards on the body), and every file snapshot — including
    the final one ``shutdown`` writes — gets a ``<snapshot_path>.health.json``
    sibling.  ``shutdown`` is idempotent and safe to call without
    ``start``.
    """

    def __init__(self, registry: MetricsRegistry,
                 port: Optional[int] = None,
                 snapshot_path: Optional[str] = None,
                 host: str = "127.0.0.1",
                 health_provider: Optional[Any] = None):
        self._registry = registry
        self._requested_port = port
        self.snapshot_path = str(snapshot_path) if snapshot_path else None
        self.health_provider = health_provider
        self.host = host
        self.port: Optional[int] = None
        self._server: Any = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._shut = False

    def start(self) -> "MetricsExporter":
        if self._requested_port is None or self._server is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = self._registry
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib API
                route = self.path.split("?", 1)[0]
                if route == "/healthz":
                    self._serve_healthz()
                    return
                if route not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = render_openmetrics(registry).encode("utf-8")
                except Exception as e:  # registry must never 500 silently
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_healthz(self) -> None:
                provider = exporter.health_provider
                if provider is None:
                    self.send_error(404, "no health plane configured")
                    return
                try:
                    snap = provider()
                except Exception as e:
                    self.send_error(500, str(e))
                    return
                status = str(snap.get("status", "ok"))
                code = 503 if status == "critical" else 200
                body = json.dumps(snap, sort_keys=True,
                                  default=str).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: Any) -> None:
                pass  # scrapes must not spam the run's stderr

        self._server = ThreadingHTTPServer(
            (self.host, int(self._requested_port)), _Handler)
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="obs-metrics-exporter",
            daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self.host}:{self.port}/metrics"

    @property
    def serve_thread(self) -> Optional[threading.Thread]:
        """The HTTP serve thread (the health plane registers a thread-mode
        watchdog on it), or None when HTTP is off."""
        return self._thread

    @property
    def health_snapshot_path(self) -> Optional[str]:
        if self.snapshot_path is None or self.health_provider is None:
            return None
        return self.snapshot_path + ".health.json"

    def snapshot(self) -> Optional[str]:
        """Atomic file snapshot of the current rendering (or None when file
        snapshots are off); with a health provider attached, also refreshes
        the sibling health-snapshot JSON."""
        if self.snapshot_path is None:
            return None
        _atomic_write_text(self.snapshot_path,
                           render_openmetrics(self._registry))
        hpath = self.health_snapshot_path
        if hpath is not None:
            try:
                snap = self.health_provider()
                _atomic_write_text(
                    hpath, json.dumps(snap, sort_keys=True, default=str,
                                      indent=1) + "\n")
            except Exception:  # health snapshot is best-effort telemetry
                pass
        return self.snapshot_path

    def shutdown(self) -> None:
        with self._lock:
            if self._shut:
                return
            self._shut = True
            server, self._server = self._server, None
            thread, self._thread = self._thread, None
        try:
            self.snapshot()
        except OSError:
            pass
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=2.0)
