"""Cross-host telemetry plane: best-effort span/metric fan-in.

PRs 5 and 8 built tracing, metrics, and the flight recorder, but every
record still lives in the process that emitted it — the server
reconstructs a round's span tree without ever seeing the client-side
``client.train`` interior, so "straggler" meant "slow upload span" with
no way to tell compute-bound from network-bound from scheduler-deferred.
This module closes that gap without adding a transport:

* **Client side** — :class:`ClientTelemetry` buffers compact span/metric
  records (train sub-phases, per-step timings, proc RSS, comm stats)
  into a bounded ring with monotonically increasing sequence numbers,
  and :meth:`ClientTelemetry.attach` drains the ring into ONE msgpack
  blob piggybacked on an existing upload/report :class:`Message` under
  :data:`TELEMETRY_KEY` (plus :meth:`flush_message` for a standalone
  :data:`TOPIC_TELEMETRY` message in async mode).
* **Server side** — :class:`TelemetryMerger` decodes blobs, dedups by
  sequence number (a retransmitted message carries the *same* blob, so
  duplicates collapse; a dropped message shows up as a counted gap,
  never a retry), re-emits remote spans into the local sink fan keyed by
  the existing deterministic trace ids (``tools/trace_report.py``'s
  first-wins pairing grafts them into the round tree), and merges metric
  records into the process registry as ``client``-labeled series (the
  PR 5 cardinality cap bounds the fan-in).

**Best-effort contract** (the hard requirement): telemetry must never
perturb training.  Records only read clocks and ``/proc``; the blob is a
single extra message param that JSON transports silently drop and binary
transports carry opaquely; decode/merge failures count a metric and
return.  Dropped, duplicated, or delayed telemetry under the PR 1 fault
seam changes *observability output only* — convergence is bit-exact with
telemetry on or off.

This file is the ONE wire seam: ``tools/lint_obs.py`` forbids the
:data:`TELEMETRY_KEY` message param anywhere else in the tree.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .trace import (
    TOPIC_SPAN_END,
    TOPIC_SPAN_START,
    SpanContext,
    round_root_ctx,
    span_id_for,
    trace_id_for,
)

# The single Message-param wire key (lint-enforced to stay in this file).
TELEMETRY_KEY = "__obs_telemetry__"

# Standalone flush message type for async mode, where uploads can be
# minutes apart but the operator still wants live straggler data.
TOPIC_TELEMETRY = "telemetry"

BLOB_VERSION = 1

DEFAULT_RING_CAPACITY = 512
DEFAULT_FLUSH_S = 0.0  # 0 = piggyback-only (no standalone flush messages)

# record kinds (one-letter keys keep the wire blob small: a full ring of
# 512 records stays well under a single model-delta chunk)
_KIND_SPAN = "s"
_KIND_COUNTER = "c"
_KIND_GAUGE = "g"


def encode_blob(node: Any, run_id: Any, records: List[Dict[str, Any]],
                dropped: int) -> bytes:
    import msgpack

    return msgpack.packb(
        {"v": BLOB_VERSION, "node": node, "run": str(run_id),
         "recs": records, "dropped": int(dropped)},
        use_bin_type=True)


def decode_blob(blob: bytes) -> Dict[str, Any]:
    import msgpack

    data = msgpack.unpackb(bytes(blob), raw=False, strict_map_key=False)
    if not isinstance(data, dict) or data.get("v") != BLOB_VERSION:
        raise ValueError("unknown telemetry blob version")
    if not isinstance(data.get("recs"), list):
        raise ValueError("telemetry blob missing record list")
    return data


class ClientTelemetry:
    """Per-node bounded telemetry ring + blob encoder.

    One instance per manager/simulator object, NOT process-global: the
    test harness runs every node of a deployment in one process, where a
    shared buffer would interleave nodes' sequence spaces and break the
    gap/dup accounting.
    """

    def __init__(self, node: Any, run_id: Any,
                 capacity: int = DEFAULT_RING_CAPACITY):
        self.node = node
        self.run_id = str(run_id)
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._seq = 0  # next sequence number to assign (never reused)
        self.dropped_total = 0  # aged out of the ring before a drain
        self.bytes_sent = 0
        self.blobs_sent = 0
        self._last_flush = time.monotonic()

    # -- recording -----------------------------------------------------------
    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            rec["q"] = self._seq
            self._seq += 1
            if len(self._ring) == self.capacity:
                # ring overflow: the oldest record is lost client-side and
                # will be accounted as a sequence gap by the merger
                self.dropped_total += 1
            self._ring.append(rec)

    def _ids(self, name: str, parent: Optional[SpanContext],
             round_idx: Optional[int], seq: int):
        if parent is not None:
            tid, psid = parent.trace_id, parent.span_id
        elif round_idx is not None:
            root = round_root_ctx(self.run_id, round_idx)
            tid, psid = root.trace_id, root.span_id
        else:
            tid, psid = trace_id_for(self.run_id, -1), None
        return tid, span_id_for(tid, name, self.node, seq), psid

    def record_span(self, name: str, duration_s: float,
                    parent: Optional[SpanContext] = None,
                    round_idx: Optional[int] = None, seq: int = 0,
                    **attrs: Any) -> SpanContext:
        """Record one completed remote span; returns its context so
        sub-phases can nest under it.  Ids are the same deterministic
        hashes the live tracer uses, so a span recorded here and one
        emitted locally for the same (name, node, seq) coordinates
        collapse to one node in the report — which is exactly what makes
        in-process loopback tests safe."""
        tid, sid, psid = self._ids(name, parent, round_idx, seq)
        rec: Dict[str, Any] = {
            "k": _KIND_SPAN, "t": tid, "s": sid, "n": str(name),
            "d": round(float(duration_s), 6),
        }
        if psid is not None:
            rec["p"] = psid
        if round_idx is not None:
            rec["r"] = int(round_idx)
        if attrs:
            rec["a"] = attrs
        self._append(rec)
        return SpanContext(tid, sid)

    @contextlib.contextmanager
    def phase(self, name: str, parent: Optional[SpanContext] = None,
              round_idx: Optional[int] = None, seq: int = 0, **attrs: Any):
        """Time a client-side sub-phase and record it as a remote span.
        Yields the phase's :class:`SpanContext` for nesting."""
        tid, sid, psid = self._ids(name, parent, round_idx, seq)
        ctx = SpanContext(tid, sid)
        t0 = time.monotonic()
        try:
            yield ctx
        finally:
            rec: Dict[str, Any] = {
                "k": _KIND_SPAN, "t": tid, "s": sid, "n": str(name),
                "d": round(time.monotonic() - t0, 6),
            }
            if psid is not None:
                rec["p"] = psid
            if round_idx is not None:
                rec["r"] = int(round_idx)
            if attrs:
                rec["a"] = attrs
            self._append(rec)

    def record_counter(self, name: str, value: float,
                       labels: Optional[Dict[str, Any]] = None) -> None:
        """A counter DELTA since the last record (merged additively)."""
        rec: Dict[str, Any] = {"k": _KIND_COUNTER, "n": str(name),
                               "v": float(value)}
        if labels:
            rec["l"] = {str(k): str(v) for k, v in labels.items()}
        self._append(rec)

    def record_gauge(self, name: str, value: float,
                     labels: Optional[Dict[str, Any]] = None) -> None:
        """A gauge sample (merged last-value-wins)."""
        rec: Dict[str, Any] = {"k": _KIND_GAUGE, "n": str(name),
                               "v": float(value)}
        if labels:
            rec["l"] = {str(k): str(v) for k, v in labels.items()}
        self._append(rec)

    def sample_resources(self) -> None:
        """Snapshot this process's RSS into the ring (best-effort)."""
        try:
            import os

            with open("/proc/self/statm", "rb") as f:
                rss_pages = int(f.read().split()[1])
            self.record_gauge(
                "proc.rss_bytes",
                float(rss_pages * os.sysconf("SC_PAGE_SIZE")))
        except (OSError, ValueError, IndexError):
            pass

    # -- draining ------------------------------------------------------------
    def pending(self) -> int:
        with self._lock:
            return len(self._ring)

    def drain(self) -> Optional[bytes]:
        """Encode-and-clear the ring; None when there is nothing to send."""
        with self._lock:
            if not self._ring:
                return None
            records = list(self._ring)
            self._ring.clear()
            dropped = self.dropped_total
        try:
            blob = encode_blob(self.node, self.run_id, records, dropped)
        except Exception:
            # encoding trouble loses these records (best-effort); the seq
            # gap at the merger accounts for them
            return None
        with self._lock:
            self.bytes_sent += len(blob)
            self.blobs_sent += 1
            self._last_flush = time.monotonic()
        return blob

    def attach(self, message: Any) -> int:
        """Piggyback the pending ring onto ``message``; returns the blob
        size in bytes (0 when nothing was pending).  The retransmitter
        reuses the same Message object, so a retransmit re-carries the
        SAME blob and the merger's seq dedup collapses it."""
        blob = self.drain()
        if blob is None:
            return 0
        message.add_params(TELEMETRY_KEY, blob)
        return len(blob)

    def flush_due(self, flush_s: float) -> bool:
        """True when a standalone flush message is warranted: records are
        pending and ``flush_s`` has elapsed since the last drain."""
        if flush_s <= 0:
            return False
        with self._lock:
            return (bool(self._ring)
                    and time.monotonic() - self._last_flush >= flush_s)

    def flush_message(self, sender: Any, receiver: Any) -> Optional[Any]:
        """A standalone :data:`TOPIC_TELEMETRY` message carrying the ring
        (async mode's periodic flush), or None when nothing is pending."""
        from ..distributed.communication.message import Message

        m = Message(TOPIC_TELEMETRY, sender, receiver)
        if self.attach(m) == 0:
            return None
        return m


class TelemetryRelay:
    """Edge-aggregator telemetry graft: collect leaf blobs, re-carry them
    upstream.

    An edge tier must not become a telemetry black hole — the root's
    :class:`TelemetryMerger` still wants per-LEAF span attribution, so an
    edge pops each leaf upload's blob off the message (undecoded: the
    blob is opaque bytes with its own node id and seq space) and grafts
    the collected batch onto its fused forward as a list under
    :data:`TELEMETRY_KEY`.  The merger's list-aware
    :meth:`TelemetryMerger.absorb` merges each as if the leaf had
    uploaded directly; a replayed forward re-carries the same blobs and
    the per-node seq dedup collapses them.  Bounded and best-effort like
    everything else on this plane.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._blobs: List[bytes] = []
        self.blobs_dropped = 0

    def collect(self, message: Any) -> Optional[bytes]:
        """Pop the blob riding a leaf upload (if any) into the relay
        buffer; returns it so the caller can journal it alongside the
        upload (a replayed edge then re-grafts the same bytes)."""
        try:
            blob = message.get(TELEMETRY_KEY)
        except Exception:
            return None
        if not isinstance(blob, (bytes, bytearray)):
            return None
        return self.offer(bytes(blob))

    def collect_many(self, message: Any) -> List[bytes]:
        """Pop the blob OR blob-list riding ``message`` (a mid absorbing a
        child edge's graft sees a list) into the relay buffer; returns the
        collected blobs for journaling."""
        try:
            blob = message.get(TELEMETRY_KEY)
        except Exception:
            return []
        blobs = blob if isinstance(blob, (list, tuple)) else [blob]
        out: List[bytes] = []
        for b in blobs:
            got = self.offer(b) if isinstance(b, (bytes, bytearray)) else None
            if got is not None:
                out.append(got)
        return out

    def offer(self, blob: Optional[bytes]) -> Optional[bytes]:
        """Buffer one raw blob (journal-replay re-entry point)."""
        if not isinstance(blob, (bytes, bytearray)):
            return None
        blob = bytes(blob)
        with self._lock:
            if len(self._blobs) >= self.capacity:
                self.blobs_dropped += 1
                return blob
            self._blobs.append(blob)
        return blob

    def pending(self) -> int:
        with self._lock:
            return len(self._blobs)

    def graft(self, message: Any, own: Optional[bytes] = None) -> int:
        """Attach the collected leaf blobs (plus the edge's ``own`` blob,
        if given) to the fused forward; returns the blob count.  The
        buffer drains — a later flush carries only newer leaf blobs."""
        with self._lock:
            blobs = list(self._blobs)
            self._blobs.clear()
        if isinstance(own, (bytes, bytearray)):
            blobs.append(bytes(own))
        if blobs:
            message.add_params(TELEMETRY_KEY, blobs)
        return len(blobs)


class TelemetryMerger:
    """Server-side blob fan-in: seq dedup/gap accounting, remote-span
    re-emission, ``client``-labeled metric merge.

    Per-manager-instance for the same reason as :class:`ClientTelemetry`.
    ``emit`` is sink-shaped (``(topic, record)``); ``registry`` is the
    process :class:`~.metrics.MetricsRegistry`.  Both may be None (merger
    then only keeps counters — the chaos tests use this shape).
    """

    def __init__(self, emit: Optional[Callable[[str, Dict[str, Any]], None]] = None,
                 registry: Any = None):
        self._emit = emit
        self._registry = registry
        self._lock = threading.Lock()
        self._next: Dict[Any, int] = {}       # node -> next expected seq
        self._train_seconds: Dict[Any, float] = {}
        self.blobs_merged = 0
        self.records_merged = 0
        self.dup_records = 0
        self.gap_records = 0
        self.bad_blobs = 0
        self.bytes_total = 0

    # -- ingestion -----------------------------------------------------------
    def absorb(self, message: Any) -> int:
        """Merge the blob riding ``message`` (if any); returns the number
        of FRESH records applied.  Never raises.  The param may be one
        blob (a direct client upload) or a list of blobs (an edge
        aggregator's graft: the leaf blobs it collected, re-carried on
        its fused forward) — each blob keeps its own node id and seq
        window, so per-leaf attribution survives the intermediate hop and
        a replayed forward's re-carried blobs collapse as duplicates."""
        try:
            blob = message.get(TELEMETRY_KEY)
        except Exception:
            return 0
        if isinstance(blob, (list, tuple)):
            fresh = 0
            for b in blob:
                if isinstance(b, (bytes, bytearray)):
                    fresh += self.merge(bytes(b))
            return fresh
        if not isinstance(blob, (bytes, bytearray)):
            return 0
        return self.merge(bytes(blob))

    def merge(self, blob: bytes) -> int:
        try:
            data = decode_blob(blob)
        except Exception:
            with self._lock:
                self.bad_blobs += 1
            self._mirror_counter("telemetry.bad_blobs", 1)
            return 0
        node = data.get("node")
        fresh: List[Dict[str, Any]] = []
        dups = gaps = 0
        with self._lock:
            self.blobs_merged += 1
            self.bytes_total += len(blob)
            nxt = self._next.get(node, None)
            for rec in data["recs"]:
                q = rec.get("q")
                if not isinstance(q, int):
                    continue
                if nxt is None:
                    nxt = q  # first blob from this node seeds the window
                if q < nxt:
                    dups += 1
                    continue
                if q > nxt:
                    gaps += q - nxt
                nxt = q + 1
                fresh.append(rec)
            if nxt is not None:
                self._next[node] = nxt
            self.dup_records += dups
            self.gap_records += gaps
            self.records_merged += len(fresh)
        for rec in fresh:
            try:
                self._apply(rec, node)
            except Exception:  # telemetry never raises into the round path
                pass
        self._mirror_counter("telemetry.blobs_merged", 1)
        if fresh:
            self._mirror_counter("telemetry.records_merged", len(fresh))
        if dups:
            self._mirror_counter("telemetry.dup_records", dups)
        if gaps:
            self._mirror_counter("telemetry.gap_records", gaps)
        self._mirror_counter("telemetry.bytes_total", len(blob))
        return len(fresh)

    def _mirror_counter(self, name: str, n: float) -> None:
        if self._registry is not None:
            try:
                self._registry.counter_inc(name, n)
            except Exception:
                pass

    def _apply(self, rec: Dict[str, Any], node: Any) -> None:
        kind = rec.get("k")
        if kind == _KIND_SPAN:
            self._apply_span(rec, node)
            return
        labels = dict(rec.get("l") or {})
        labels["client"] = str(node)
        name = str(rec.get("n"))
        value = float(rec.get("v", 0.0))
        if self._registry is None:
            return
        if kind == _KIND_COUNTER:
            self._registry.counter_inc(name, value, labels)
        elif kind == _KIND_GAUGE:
            self._registry.gauge_set(name, value, labels)

    def _apply_span(self, rec: Dict[str, Any], node: Any) -> None:
        name = str(rec.get("n"))
        dur = float(rec.get("d", 0.0))
        if name == "client.train":
            # the freshest measured train time feeds the population EMA
            with self._lock:
                self._train_seconds[node] = dur
        if self._emit is None:
            return
        start: Dict[str, Any] = {
            "trace_id": rec.get("t"), "span_id": rec.get("s"),
            "name": name, "node": node, "remote": True,
        }
        if rec.get("p") is not None:
            start["parent_span_id"] = rec["p"]
        if rec.get("r") is not None:
            start["round_idx"] = int(rec["r"])
        attrs = rec.get("a")
        if isinstance(attrs, dict):
            start.update(attrs)
        end = {"trace_id": rec.get("t"), "span_id": rec.get("s"),
               "name": name, "duration_s": dur, "remote": True}
        try:
            self._emit(TOPIC_SPAN_START, start)
            self._emit(TOPIC_SPAN_END, end)
        except Exception:
            pass

    # -- readback ------------------------------------------------------------
    def train_seconds(self, node: Any) -> Optional[float]:
        """The latest remote-measured ``client.train`` duration for
        ``node`` (the pacing/staleness EMA hint), or None."""
        with self._lock:
            return self._train_seconds.get(node)

    def counters(self) -> Dict[str, int]:
        """Merge counters for flight-recorder dump meta."""
        with self._lock:
            return {
                "telemetry_blobs_merged": self.blobs_merged,
                "telemetry_records_merged": self.records_merged,
                "telemetry_dup_records": self.dup_records,
                "telemetry_gap_records": self.gap_records,
                "telemetry_bad_blobs": self.bad_blobs,
                "telemetry_bytes_total": self.bytes_total,
            }
