"""``fedml_tpu.core.obs`` — the round-trace observability layer.

One process-global context (configured by ``core.mlops.init`` when
``args.obs_trace`` is set, torn down by ``mlops.finish``) exposing:

* a :class:`~.trace.Tracer` whose deterministic span ids and W3C-style
  ``traceparent`` header turn each federated round into one cross-process
  span tree (``round → select → invite → client.train → upload →
  journal.append → aggregate → broadcast``, with fault/recovery events
  attached — taxonomy in ``docs/OBSERVABILITY.md``);
* a :class:`~.metrics.MetricsRegistry` every library counter mirrors into
  (``tools/lint_obs.py`` forbids NEW bare counter bags outside this
  package and ``core/mlops``);
* module-level helpers (``span`` / ``span_event`` / ``inject`` /
  ``extract`` / ``counter_inc`` / ...) that are cheap no-ops until
  :func:`configure` runs — library code calls them unconditionally, and
  with ``obs_trace`` off the message flow stays bit-identical (no
  traceparent param is ever added).

Everything here is telemetry: emission failures are swallowed, ids carry
no wall-clock, and nothing round-critical may ever depend on a span.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

from .exposition import MetricsExporter
from .flight import DEFAULT_FLIGHT_CAPACITY, FlightRecorder
from .health import (
    DEFAULT_EWMA_ALPHA,
    DEFAULT_WATCHDOG_DEADLINE_S,
    DEFAULT_Z_THRESHOLD,
    HEALTH_STATUS_GAUGE,
    NULL_SILENCE,
    NULL_WATCHDOG,
    HealthPlane,
    SilenceMonitor,
    Watchdog,
)
from .metrics import DEFAULT_TIME_BUCKETS, MetricsRegistry
from .telemetry import (
    DEFAULT_FLUSH_S,
    DEFAULT_RING_CAPACITY,
    TOPIC_TELEMETRY,
    ClientTelemetry,
    TelemetryMerger,
)
from .trace import (
    NULL_SPAN,
    Span,
    SpanContext,
    Tracer,
    active_ctx,
    round_root_ctx,
    span_id_for,
    trace_id_for,
)

__all__ = [
    "MetricsRegistry", "Tracer", "Span", "SpanContext", "NULL_SPAN",
    "DEFAULT_TIME_BUCKETS", "trace_id_for", "span_id_for", "round_root_ctx",
    "active_ctx", "FlightRecorder", "MetricsExporter",
    "configure", "shutdown", "enabled", "tracer", "registry", "run_id",
    "span", "round_span", "unique_span", "span_event",
    "inject", "extract", "counter_inc", "gauge_set", "histogram_observe",
    "maybe_export_metrics", "slow_round_factor",
    "flight_recorder", "flight_dump", "exporter",
    "sample_resource_gauges", "compile_seconds_total",
    "ClientTelemetry", "TelemetryMerger", "TOPIC_TELEMETRY",
    "telemetry_enabled", "telemetry_flush_s",
    "make_client_telemetry", "make_telemetry_merger",
    "HealthPlane", "Watchdog", "SilenceMonitor",
    "NULL_WATCHDOG", "NULL_SILENCE", "HEALTH_STATUS_GAUGE",
    "health_plane", "health_enabled", "health_watchdog", "health_silence",
    "health_observe", "health_tick", "health_status",
]

_lock = threading.Lock()
_ctx: Dict[str, Any] = {"enabled": False}

# the registry outlives configure/shutdown cycles within a process run so
# counters survive mlops re-init (tests reset it explicitly)
_registry = MetricsRegistry()


def _tapped_emit(flight: FlightRecorder,
                 emit: Callable[[str, Dict[str, Any]], None]):
    """Wrap the sink emit so every record also lands in the flight ring,
    and trigger events (``server_kill`` / ``server_restore`` /
    ``slow_round``) dump the ring AFTER the record is forwarded — the
    trigger itself is the dump's last line."""
    def tapped(topic: str, rec: Dict[str, Any]) -> None:
        try:
            reason = flight.record(topic, rec)
        except Exception:  # recorder trouble must never block the sink
            reason = None
        emit(topic, rec)
        if reason is not None:
            try:
                flight.dump(reason)
            except Exception:
                pass
    return tapped


def _health_event_emitter(name: str, attrs: Dict[str, Any]) -> None:
    """The health plane's event sink: a span event anchored on the last
    round the emit stream saw, so dumps and reports land inside the round
    tree the incident belongs to."""
    t = _ctx.get("tracer")
    if t is None:
        return
    plane = _ctx.get("health")
    ridx = int(getattr(plane, "last_round_idx", 0) or 0) if plane else 0
    try:
        t.span_event(name, None, round_idx=ridx, **attrs)
    except Exception:  # telemetry never raises into the round path
        pass


def configure(args: Any, emit: Callable[[str, Dict[str, Any]], None]) -> None:
    """Enable tracing for this process.  ``emit`` is sink-shaped
    (``(topic, record)``) — ``mlops.init`` passes its fan's emit."""
    run = str(getattr(args, "run_id", "0"))
    health_obj: Optional[HealthPlane] = None
    if bool(int(getattr(args, "obs_health", 0) or 0)):
        try:
            health_obj = HealthPlane(
                registry=_registry,
                clock=getattr(args, "obs_health_clock", None),
                z_threshold=float(
                    getattr(args, "obs_health_z", DEFAULT_Z_THRESHOLD)
                    or DEFAULT_Z_THRESHOLD),
                ewma_alpha=float(
                    getattr(args, "obs_health_ewma_alpha", DEFAULT_EWMA_ALPHA)
                    or DEFAULT_EWMA_ALPHA),
                watchdog_deadline_s=float(
                    getattr(args, "obs_health_watchdog_s",
                            DEFAULT_WATCHDOG_DEADLINE_S)
                    or DEFAULT_WATCHDOG_DEADLINE_S),
                warmup=int(getattr(args, "obs_health_warmup", 8) or 8))
            # health tap wrapped FIRST so the flight tap stays outermost:
            # flight records (and dump-triggers on) every record,
            # including the plane's own events
            emit = health_obj.tap(emit)
        except Exception:  # health misconfig must not take the run down
            health_obj = None
    flight: Optional[FlightRecorder] = None
    cap = int(getattr(args, "obs_flight_capacity", DEFAULT_FLIGHT_CAPACITY)
              or 0)
    if cap > 0:
        flight = FlightRecorder(
            capacity=cap,
            directory=getattr(args, "obs_flight_dir", None) or None,
            run_id=run)
        emit = _tapped_emit(flight, emit)
        if health_obj is not None:
            plane = health_obj
            flight.add_meta_provider(
                lambda: {"health": plane.snapshot_compact()})
    exporter_obj: Optional[MetricsExporter] = None
    port = getattr(args, "obs_export_port", None)
    path = getattr(args, "obs_export_path", None) or None
    port = int(port) if port not in (None, "") else 0
    if port > 0 or path:
        try:
            exporter_obj = MetricsExporter(
                _registry, port=port if port > 0 else None,
                snapshot_path=path,
                health_provider=(health_obj.snapshot
                                 if health_obj is not None else None),
            ).start()
        except Exception:  # a taken port must not take the run down
            exporter_obj = None
    if (health_obj is not None and exporter_obj is not None
            and exporter_obj.serve_thread is not None):
        health_obj.register("obs.exporter",
                            thread=exporter_obj.serve_thread)
    with _lock:
        _ctx.update(
            health=health_obj,
            enabled=True,
            run_id=run,
            emit=emit,
            tracer=Tracer(run, emit),
            export_interval_s=float(
                getattr(args, "obs_metrics_export_interval", 0) or 0),
            slow_round_factor=float(
                getattr(args, "obs_slow_round_factor", 2.0) or 2.0),
            flight=flight,
            exporter=exporter_obj,
            telemetry=bool(int(getattr(args, "obs_telemetry", 0) or 0)),
            telemetry_ring=int(
                getattr(args, "obs_telemetry_ring", DEFAULT_RING_CAPACITY)
                or DEFAULT_RING_CAPACITY),
            telemetry_flush_s=float(
                getattr(args, "obs_telemetry_flush_s", DEFAULT_FLUSH_S)
                or DEFAULT_FLUSH_S),
        )
    if health_obj is not None:
        health_obj.emitter = _health_event_emitter
    _register_compile_listener()


def shutdown() -> None:
    """Final metrics flush + exporter/recorder teardown (idempotent)."""
    with _lock:
        emit = _ctx.get("emit")
        if emit is not None:
            sample_resource_gauges()
            _registry.export_to(emit)
        exporter_obj = _ctx.get("exporter")
        _ctx.clear()
        _ctx["enabled"] = False
    if exporter_obj is not None:
        try:  # joins the serve thread — outside the facade lock
            exporter_obj.shutdown()
        except Exception:
            pass


def enabled() -> bool:
    return bool(_ctx.get("enabled"))


def tracer() -> Optional[Tracer]:
    return _ctx.get("tracer")


def registry() -> MetricsRegistry:
    return _registry


def run_id() -> str:
    return str(_ctx.get("run_id", "0"))


def slow_round_factor() -> float:
    return float(_ctx.get("slow_round_factor", 2.0))


def flight_recorder() -> Optional[FlightRecorder]:
    return _ctx.get("flight")


def flight_dump(reason: str) -> Optional[str]:
    """Dump the flight ring now (server managers call this on unhandled
    handler exceptions); returns the dump path or None."""
    flight = _ctx.get("flight")
    if flight is None:
        return None
    try:
        return flight.dump(reason)
    except Exception:  # telemetry never raises into the round path
        return None


def exporter() -> Optional[MetricsExporter]:
    return _ctx.get("exporter")


# -- live health & SLO plane -------------------------------------------------

def health_plane() -> Optional[HealthPlane]:
    return _ctx.get("health")


def health_enabled() -> bool:
    return _ctx.get("health") is not None


def health_status() -> str:
    plane = _ctx.get("health")
    return plane.status if plane is not None else "ok"


def health_watchdog(name: str, deadline_s: Optional[float] = None,
                    thread: Any = None):
    """Register a named liveness watchdog for a long-lived worker; returns
    a handle whose ``beat`` / ``idle`` / ``close`` are no-ops when the
    health plane is off, so worker loops call them unconditionally."""
    plane = _ctx.get("health")
    if plane is None:
        return NULL_WATCHDOG
    try:
        return plane.register(name, deadline_s=deadline_s, thread=thread)
    except Exception:
        return NULL_WATCHDOG


def health_silence(series: str, max_age_s: Optional[float] = None):
    """The silence monitor for an expected activity stream (chunk acks,
    edge forwards); ``note()`` marks activity, a tick finds the stall."""
    plane = _ctx.get("health")
    if plane is None:
        return NULL_SILENCE
    try:
        return plane.silence(series, max_age_s=max_age_s)
    except Exception:
        return NULL_SILENCE


def health_observe(series: str, value: float) -> None:
    """Push one sample into a rolling SLO window (no-op with health off)."""
    plane = _ctx.get("health")
    if plane is not None:
        try:
            plane.observe(series, value)
        except Exception:
            pass


def health_tick() -> Optional[str]:
    """Run the health checks now; returns the status, or None when the
    plane is off.  Round-close paths get this for free via
    :func:`maybe_export_metrics`."""
    plane = _ctx.get("health")
    if plane is None:
        return None
    try:
        return plane.tick()
    except Exception:
        return None


# -- cross-host telemetry plane ---------------------------------------------

def telemetry_enabled() -> bool:
    return bool(_ctx.get("telemetry"))


def telemetry_flush_s() -> float:
    return float(_ctx.get("telemetry_flush_s", DEFAULT_FLUSH_S))


def make_client_telemetry(node: Any) -> Optional[ClientTelemetry]:
    """A per-manager telemetry capture ring, or None with the plane off.
    Per-instance on purpose: the in-process test harness runs every node
    of a deployment in one interpreter, where a process-global buffer
    would interleave nodes' sequence spaces."""
    if not _ctx.get("telemetry"):
        return None
    return ClientTelemetry(
        node, _ctx.get("run_id", "0"),
        capacity=int(_ctx.get("telemetry_ring", DEFAULT_RING_CAPACITY)))


def make_telemetry_merger() -> Optional[TelemetryMerger]:
    """A per-manager blob merger bound to the configured sink fan and the
    process registry, or None with the plane off."""
    if not _ctx.get("telemetry"):
        return None
    return TelemetryMerger(emit=_ctx.get("emit"), registry=_registry)


# -- resource attribution ---------------------------------------------------

def sample_resource_gauges() -> None:
    """Host memory gauges: current RSS (``/proc/self/statm``) and peak RSS
    (``getrusage``).  Called from every ``maybe_export_metrics`` site, so
    the round-close paths of both managers and both simulators sample it
    for free.  Best-effort on non-Linux."""
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux
        _registry.gauge_set("proc.max_rss_bytes", float(ru.ru_maxrss) * 1024.0)
    except Exception:
        pass
    try:
        with open("/proc/self/statm", "rb") as f:
            rss_pages = int(f.read().split()[1])
        _registry.gauge_set(
            "proc.rss_bytes", float(rss_pages * os.sysconf("SC_PAGE_SIZE")))
    except (OSError, ValueError, IndexError):
        pass


# XLA compile-time accumulator: jax.monitoring fires
# /jax/core/compile/backend_compile_duration for EVERY backend compile in
# the process (round fns, eval fns, the agg plane), so one listener gives
# the compile side of the compile-vs-execute split without touching any
# hot path.  Registered once per process; reads the live _ctx per event.
_compile_state = {"lock": threading.Lock(), "total": 0.0, "registered": False}


def _on_jax_event_duration(event: str, duration: float, **kw: Any) -> None:
    if not _ctx.get("enabled") or not str(event).endswith(
            "backend_compile_duration"):
        return
    with _compile_state["lock"]:
        _compile_state["total"] += float(duration)
    try:
        _registry.histogram_observe("xla.compile_seconds", float(duration))
    except Exception:
        pass


def _register_compile_listener() -> None:
    if _compile_state["registered"]:
        return
    try:
        from jax import monitoring as _monitoring

        _monitoring.register_event_duration_secs_listener(
            _on_jax_event_duration)
        _compile_state["registered"] = True
    except Exception:  # jax absent or API moved: attribution degrades
        pass


def compile_seconds_total() -> float:
    """Cumulative XLA backend-compile seconds observed so far; snapshot
    before/after a round call and the difference is that round's compile
    share."""
    with _compile_state["lock"]:
        return float(_compile_state["total"])


# -- span helpers (no-ops until configure) ----------------------------------

def round_span(round_idx: int, node: Any = 0, annotate: bool = False,
               **attrs: Any):
    t = _ctx.get("tracer")
    if t is None:
        return NULL_SPAN
    return t.round_span(int(round_idx), node=node, annotate=annotate, **attrs)


def span(name: str, parent: Optional[SpanContext] = None,
         round_idx: Optional[int] = None, node: Any = 0, seq: int = 0,
         annotate: bool = False, **attrs: Any):
    t = _ctx.get("tracer")
    if t is None:
        return NULL_SPAN
    return t.span(name, parent, round_idx=round_idx, node=node, seq=seq,
                  annotate=annotate, **attrs)


def unique_span(name: str, parent: Optional[SpanContext] = None,
                round_idx: Optional[int] = None, node: Any = 0,
                annotate: bool = False, **attrs: Any):
    t = _ctx.get("tracer")
    if t is None:
        return NULL_SPAN
    return t.unique_span(name, parent, round_idx=round_idx, node=node,
                         annotate=annotate, **attrs)


def span_event(name: str, ctx: Optional[SpanContext] = None,
               round_idx: Optional[int] = None, node: Any = 0,
               **attrs: Any) -> None:
    t = _ctx.get("tracer")
    if t is not None:
        t.span_event(name, ctx, round_idx=round_idx, node=node, **attrs)


# -- context propagation ----------------------------------------------------

def inject(message: Any, ctx: Optional[SpanContext]) -> None:
    """Stamp ``ctx`` into a :class:`Message`'s params as a ``traceparent``
    string (survives every backend: JSON keeps strings, binary transports
    pickle the whole dict).  No-op when tracing is off or ctx is None, so
    the disabled wire is byte-identical to the pre-obs wire."""
    if ctx is None or not enabled():
        return
    from ..distributed.communication.message import Message

    message.add_params(Message.MSG_ARG_KEY_TRACEPARENT, ctx.to_traceparent())


def extract(message: Any) -> Optional[SpanContext]:
    """The :class:`SpanContext` a peer injected, or None (legacy peer,
    tracing off at the sender, malformed header)."""
    from ..distributed.communication.message import Message

    return SpanContext.from_traceparent(
        message.get(Message.MSG_ARG_KEY_TRACEPARENT))


# -- metrics helpers --------------------------------------------------------

def counter_inc(name: str, n: float = 1,
                labels: Optional[Dict[str, Any]] = None) -> None:
    _registry.counter_inc(name, n, labels)


def gauge_set(name: str, value: float,
              labels: Optional[Dict[str, Any]] = None) -> None:
    _registry.gauge_set(name, value, labels)


def histogram_observe(name: str, value: float,
                      labels: Optional[Dict[str, Any]] = None,
                      buckets=None) -> None:
    _registry.histogram_observe(name, value, labels, buckets)


def maybe_export_metrics() -> bool:
    """Rate-limited registry flush to the sink (round-close call sites);
    obeys ``obs_metrics_export_interval`` (0 = only the shutdown flush).
    Also samples the host resource gauges and, when a sink flush fires,
    refreshes the exporter's file snapshot."""
    emit = _ctx.get("emit")
    if emit is None:
        return False
    sample_resource_gauges()
    health_tick()
    did = _registry.maybe_export(emit, float(_ctx.get("export_interval_s", 0)))
    if did:
        exporter_obj = _ctx.get("exporter")
        if exporter_obj is not None:
            try:
                exporter_obj.snapshot()
            except OSError:
                pass
    return did
