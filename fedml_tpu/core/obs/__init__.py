"""``fedml_tpu.core.obs`` — the round-trace observability layer.

One process-global context (configured by ``core.mlops.init`` when
``args.obs_trace`` is set, torn down by ``mlops.finish``) exposing:

* a :class:`~.trace.Tracer` whose deterministic span ids and W3C-style
  ``traceparent`` header turn each federated round into one cross-process
  span tree (``round → select → invite → client.train → upload →
  journal.append → aggregate → broadcast``, with fault/recovery events
  attached — taxonomy in ``docs/OBSERVABILITY.md``);
* a :class:`~.metrics.MetricsRegistry` every library counter mirrors into
  (``tools/lint_obs.py`` forbids NEW bare counter bags outside this
  package and ``core/mlops``);
* module-level helpers (``span`` / ``span_event`` / ``inject`` /
  ``extract`` / ``counter_inc`` / ...) that are cheap no-ops until
  :func:`configure` runs — library code calls them unconditionally, and
  with ``obs_trace`` off the message flow stays bit-identical (no
  traceparent param is ever added).

Everything here is telemetry: emission failures are swallowed, ids carry
no wall-clock, and nothing round-critical may ever depend on a span.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from .metrics import DEFAULT_TIME_BUCKETS, MetricsRegistry
from .trace import (
    NULL_SPAN,
    Span,
    SpanContext,
    Tracer,
    active_ctx,
    round_root_ctx,
    span_id_for,
    trace_id_for,
)

__all__ = [
    "MetricsRegistry", "Tracer", "Span", "SpanContext", "NULL_SPAN",
    "DEFAULT_TIME_BUCKETS", "trace_id_for", "span_id_for", "round_root_ctx",
    "active_ctx",
    "configure", "shutdown", "enabled", "tracer", "registry", "run_id",
    "span", "round_span", "unique_span", "span_event",
    "inject", "extract", "counter_inc", "gauge_set", "histogram_observe",
    "maybe_export_metrics", "slow_round_factor",
]

_lock = threading.Lock()
_ctx: Dict[str, Any] = {"enabled": False}

# the registry outlives configure/shutdown cycles within a process run so
# counters survive mlops re-init (tests reset it explicitly)
_registry = MetricsRegistry()


def configure(args: Any, emit: Callable[[str, Dict[str, Any]], None]) -> None:
    """Enable tracing for this process.  ``emit`` is sink-shaped
    (``(topic, record)``) — ``mlops.init`` passes its fan's emit."""
    with _lock:
        _ctx.update(
            enabled=True,
            run_id=str(getattr(args, "run_id", "0")),
            emit=emit,
            tracer=Tracer(str(getattr(args, "run_id", "0")), emit),
            export_interval_s=float(
                getattr(args, "obs_metrics_export_interval", 0) or 0),
            slow_round_factor=float(
                getattr(args, "obs_slow_round_factor", 2.0) or 2.0),
        )


def shutdown() -> None:
    """Final metrics flush + disable (idempotent)."""
    with _lock:
        emit = _ctx.get("emit")
        if emit is not None:
            _registry.export_to(emit)
        _ctx.clear()
        _ctx["enabled"] = False


def enabled() -> bool:
    return bool(_ctx.get("enabled"))


def tracer() -> Optional[Tracer]:
    return _ctx.get("tracer")


def registry() -> MetricsRegistry:
    return _registry


def run_id() -> str:
    return str(_ctx.get("run_id", "0"))


def slow_round_factor() -> float:
    return float(_ctx.get("slow_round_factor", 2.0))


# -- span helpers (no-ops until configure) ----------------------------------

def round_span(round_idx: int, node: Any = 0, annotate: bool = False,
               **attrs: Any):
    t = _ctx.get("tracer")
    if t is None:
        return NULL_SPAN
    return t.round_span(int(round_idx), node=node, annotate=annotate, **attrs)


def span(name: str, parent: Optional[SpanContext] = None,
         round_idx: Optional[int] = None, node: Any = 0, seq: int = 0,
         annotate: bool = False, **attrs: Any):
    t = _ctx.get("tracer")
    if t is None:
        return NULL_SPAN
    return t.span(name, parent, round_idx=round_idx, node=node, seq=seq,
                  annotate=annotate, **attrs)


def unique_span(name: str, parent: Optional[SpanContext] = None,
                round_idx: Optional[int] = None, node: Any = 0,
                annotate: bool = False, **attrs: Any):
    t = _ctx.get("tracer")
    if t is None:
        return NULL_SPAN
    return t.unique_span(name, parent, round_idx=round_idx, node=node,
                         annotate=annotate, **attrs)


def span_event(name: str, ctx: Optional[SpanContext] = None,
               round_idx: Optional[int] = None, node: Any = 0,
               **attrs: Any) -> None:
    t = _ctx.get("tracer")
    if t is not None:
        t.span_event(name, ctx, round_idx=round_idx, node=node, **attrs)


# -- context propagation ----------------------------------------------------

def inject(message: Any, ctx: Optional[SpanContext]) -> None:
    """Stamp ``ctx`` into a :class:`Message`'s params as a ``traceparent``
    string (survives every backend: JSON keeps strings, binary transports
    pickle the whole dict).  No-op when tracing is off or ctx is None, so
    the disabled wire is byte-identical to the pre-obs wire."""
    if ctx is None or not enabled():
        return
    from ..distributed.communication.message import Message

    message.add_params(Message.MSG_ARG_KEY_TRACEPARENT, ctx.to_traceparent())


def extract(message: Any) -> Optional[SpanContext]:
    """The :class:`SpanContext` a peer injected, or None (legacy peer,
    tracing off at the sender, malformed header)."""
    from ..distributed.communication.message import Message

    return SpanContext.from_traceparent(
        message.get(Message.MSG_ARG_KEY_TRACEPARENT))


# -- metrics helpers --------------------------------------------------------

def counter_inc(name: str, n: float = 1,
                labels: Optional[Dict[str, Any]] = None) -> None:
    _registry.counter_inc(name, n, labels)


def gauge_set(name: str, value: float,
              labels: Optional[Dict[str, Any]] = None) -> None:
    _registry.gauge_set(name, value, labels)


def histogram_observe(name: str, value: float,
                      labels: Optional[Dict[str, Any]] = None,
                      buckets=None) -> None:
    _registry.histogram_observe(name, value, labels, buckets)


def maybe_export_metrics() -> bool:
    """Rate-limited registry flush to the sink (round-close call sites);
    obeys ``obs_metrics_export_interval`` (0 = only the shutdown flush)."""
    emit = _ctx.get("emit")
    if emit is None:
        return False
    return _registry.maybe_export(emit, float(_ctx.get("export_interval_s", 0)))
