"""Unified metrics registry: counters / gauges / fixed-bucket histograms.

PRs 1-4 each grew an ad-hoc counter bag (``CommStats`` in faults.py, the
``cohort_stats`` dict in population/manager.py, the recovery counters in
checkpoint.py) — correct individually, but unjoinable: no shared naming, no
labels, no distribution type at all.  This registry is the one sink-side
shape for all of them, Prometheus-flavored but offline-first:

* **Counter** — monotonic ``inc``; **Gauge** — last-write ``set``;
  **Histogram** — fixed, instrument-declared bucket upper bounds with
  ``+Inf`` implicit, plus running sum/count (so mean and quantile bounds
  are derivable offline).
* **Labeled series** — each instrument fans out by a small label dict
  (``node``, ``backend``, ...).  Cardinality is capped per instrument
  (default 64 series): past the cap, new label sets collapse into a single
  ``{"overflow": "true"}`` series and a ``dropped_series`` count — a
  runaway label (client id as a label on a 1e5 fleet) degrades to one
  series instead of eating the process.
* **export()** — a flat list of records for the mlops sink (topic
  ``metrics``); ``maybe_export`` rate-limits by ``export_interval_s`` so
  per-upload instruments don't flood the JSONL.

The legacy ``comm_stats`` / ``cohort_stats`` topics keep emitting from
their original call sites — this registry is additive, existing dashboards
and tests stay valid.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

DEFAULT_MAX_SERIES = 64

# seconds-scale latency buckets: fine where rounds live (sub-second to
# minutes), one decade of headroom either side
DEFAULT_TIME_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                        30.0, 60.0, 300.0)

_LabelKey = Tuple[Tuple[str, str], ...]
_OVERFLOW_KEY: _LabelKey = (("overflow", "true"),)


def _label_key(labels: Optional[Dict[str, Any]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """One named instrument: a dict of label-keyed series.  All access goes
    through the owning registry's lock."""

    def __init__(self, name: str, kind: str, max_series: int,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.kind = kind
        self.max_series = int(max_series)
        self.buckets: Optional[Tuple[float, ...]] = None
        if kind == "histogram":
            b = tuple(sorted(float(x) for x in (buckets or DEFAULT_TIME_BUCKETS)))
            if not b:
                raise ValueError(f"histogram {name!r} needs at least one bucket")
            self.buckets = b
        self.series: Dict[_LabelKey, Any] = {}
        self.dropped_series = 0

    def resolve_key(self, key: _LabelKey) -> _LabelKey:
        """The storage key for ``key``: itself while under the cardinality
        cap, the shared overflow series once over it."""
        if key in self.series or len(self.series) < self.max_series:
            return key
        self.dropped_series += 1
        return _OVERFLOW_KEY

    def new_series(self) -> Any:
        if self.kind == "histogram":
            assert self.buckets is not None
            return {"bucket_counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
        return 0 if self.kind == "counter" else 0.0


class MetricsRegistry:
    """Thread-safe instrument registry.  One process-global instance lives
    behind the ``core.obs`` facade; tests construct their own."""

    def __init__(self, max_series_per_metric: int = DEFAULT_MAX_SERIES):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self.max_series_per_metric = int(max_series_per_metric)
        self._last_export = time.monotonic()

    def _family(self, name: str, kind: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            fam = _Family(name, kind, self.max_series_per_metric, buckets)
            self._families[name] = fam
        elif fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, not {kind}")
        return fam

    # -- instruments ---------------------------------------------------------
    def counter_inc(self, name: str, n: float = 1,
                    labels: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            fam = self._family(name, "counter")
            k = fam.resolve_key(_label_key(labels))
            fam.series[k] = fam.series.get(k, 0) + n

    def gauge_set(self, name: str, value: float,
                  labels: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            fam = self._family(name, "gauge")
            k = fam.resolve_key(_label_key(labels))
            fam.series[k] = float(value)

    def histogram_observe(self, name: str, value: float,
                          labels: Optional[Dict[str, Any]] = None,
                          buckets: Optional[Sequence[float]] = None) -> None:
        v = float(value)
        with self._lock:
            fam = self._family(name, "histogram", buckets)
            k = fam.resolve_key(_label_key(labels))
            s = fam.series.get(k)
            if s is None:
                s = fam.new_series()
                fam.series[k] = s
            assert fam.buckets is not None
            idx = len(fam.buckets)  # +Inf bucket
            for i, ub in enumerate(fam.buckets):
                if v <= ub:
                    idx = i
                    break
            s["bucket_counts"][idx] += 1
            s["sum"] += v
            s["count"] += 1

    # -- reads ---------------------------------------------------------------
    def get_counter(self, name: str,
                    labels: Optional[Dict[str, Any]] = None) -> float:
        with self._lock:
            fam = self._families.get(name)
            return fam.series.get(_label_key(labels), 0) if fam else 0

    def get_gauge(self, name: str,
                  labels: Optional[Dict[str, Any]] = None) -> float:
        with self._lock:
            fam = self._families.get(name)
            return fam.series.get(_label_key(labels), 0.0) if fam else 0.0

    def get_histogram(self, name: str,
                      labels: Optional[Dict[str, Any]] = None
                      ) -> Optional[Dict[str, Any]]:
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind != "histogram":
                return None
            s = fam.series.get(_label_key(labels))
            if s is None:
                return None
            return {"buckets": list(fam.buckets or ()),
                    "bucket_counts": list(s["bucket_counts"]),
                    "sum": s["sum"], "count": s["count"]}

    def series_count(self, name: str) -> int:
        with self._lock:
            fam = self._families.get(name)
            return len(fam.series) if fam else 0

    def dropped_series(self, name: str) -> int:
        with self._lock:
            fam = self._families.get(name)
            return fam.dropped_series if fam else 0

    # -- export --------------------------------------------------------------
    def export(self) -> List[Dict[str, Any]]:
        """Flat snapshot: one record per (metric, label-set)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                for key in sorted(fam.series):
                    rec: Dict[str, Any] = {
                        "metric": name, "kind": fam.kind,
                        "labels": {k: v for k, v in key},
                    }
                    if fam.kind == "histogram":
                        s = fam.series[key]
                        rec.update(buckets=list(fam.buckets or ()),
                                   bucket_counts=list(s["bucket_counts"]),
                                   sum=round(s["sum"], 6), count=s["count"])
                    else:
                        rec["value"] = fam.series[key]
                    if fam.dropped_series:
                        rec["dropped_series"] = fam.dropped_series
                    out.append(rec)
        return out

    def export_to(self, emit: Callable[[str, Dict[str, Any]], None]) -> int:
        """Emit every series as a ``metrics`` topic record; returns count."""
        records = self.export()
        for rec in records:
            try:
                emit("metrics", rec)
            except Exception:  # pragma: no cover - sink failure is non-fatal
                pass
        return len(records)

    def maybe_export(self, emit: Callable[[str, Dict[str, Any]], None],
                     interval_s: float) -> bool:
        """Rate-limited export: flush at most once per ``interval_s``
        seconds (0 disables periodic export — :meth:`export_to` still runs
        at shutdown).  Called from round-close paths, so no thread."""
        if interval_s <= 0:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._last_export < float(interval_s):
                return False
            self._last_export = now
        self.export_to(emit)
        return True

    def reset(self) -> None:
        with self._lock:
            self._families.clear()
            self._last_export = time.monotonic()
