"""Decentralized-FL topology managers.

Parity with reference ``core/distributed/topology/`` (261 LoC):
``SymmetricTopologyManager`` builds a ring + random Watts-Strogatz-style
symmetric neighbor graph with a row-normalized mixing (confusion) matrix
(``symmetric_topology_manager.py:21-56``); ``AsymmetricTopologyManager``
the directed variant.  The mixing matrix is what the decentralized
algorithms consume — on TPU the neighbor exchange itself is a
``lax.ppermute``/matmul with this matrix (see simulation/sp/decentralized).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List

import numpy as np


class BaseTopologyManager(ABC):
    @abstractmethod
    def generate_topology(self) -> None:
        ...

    @abstractmethod
    def get_in_neighbor_idx_list(self, node_index: int) -> List[int]:
        ...

    @abstractmethod
    def get_out_neighbor_idx_list(self, node_index: int) -> List[int]:
        ...


class SymmetricTopologyManager(BaseTopologyManager):
    """Ring + ``neighbor_num`` random symmetric extra edges per node."""

    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = int(n)
        self.neighbor_num = int(neighbor_num)
        self.seed = seed
        self.topology = np.zeros((self.n, self.n))

    def generate_topology(self) -> None:
        n = self.n
        rng = np.random.RandomState(self.seed)
        adj = np.eye(n)
        for i in range(n):  # ring
            adj[i, (i + 1) % n] = 1
            adj[i, (i - 1) % n] = 1
        extra = max(0, self.neighbor_num - 2)
        for i in range(n):  # random symmetric rewires (WS-flavored)
            if extra > 0:
                cand = [j for j in range(n) if j != i and adj[i, j] == 0]
                if cand:
                    for j in rng.choice(cand, size=min(extra, len(cand)), replace=False):
                        adj[i, j] = adj[j, i] = 1
        # row-normalized mixing matrix (uniform over neighbors incl. self)
        self.topology = adj / adj.sum(axis=1, keepdims=True)

    def get_in_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [j for j in range(self.n) if self.topology[j, node_index] > 0]

    def get_out_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [j for j in range(self.n) if self.topology[node_index, j] > 0]

    def get_symmetric_neighbor_list(self, node_index: int) -> np.ndarray:
        return self.topology[node_index]


class AsymmetricTopologyManager(BaseTopologyManager):
    """Directed graph: each node sends to ``out_neighbor_num`` random peers."""

    def __init__(self, n: int, out_neighbor_num: int = 2, seed: int = 0):
        self.n = int(n)
        self.out_neighbor_num = int(out_neighbor_num)
        self.seed = seed
        self.topology = np.zeros((self.n, self.n))

    def generate_topology(self) -> None:
        n = self.n
        rng = np.random.RandomState(self.seed)
        adj = np.eye(n)
        for i in range(n):
            adj[i, (i + 1) % n] = 1  # keep strong connectivity via ring
            cand = [j for j in range(n) if j != i and adj[i, j] == 0]
            k = min(max(0, self.out_neighbor_num - 1), len(cand))
            if k:
                for j in rng.choice(cand, size=k, replace=False):
                    adj[i, j] = 1
        self.topology = adj / adj.sum(axis=1, keepdims=True)

    def get_in_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [j for j in range(self.n) if self.topology[j, node_index] > 0]

    def get_out_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [j for j in range(self.n) if self.topology[node_index, j] > 0]
