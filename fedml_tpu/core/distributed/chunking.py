"""Chunked, resumable upload streaming (sub-message fault granularity).

A client that disconnects at 90% of a large delta upload re-sends the
WHOLE message today — at million-client scale over flaky edge links that
is the dominant wasted-bytes and tail-latency source (the regime Prime
CCL's fault-tolerant collectives target, arXiv:2505.14065).  This module
splits a serialized payload-bearing message into crc32-framed chunks and
rides each chunk on the PR 1 reliability machinery (per-chunk msg-id,
ack, dedup, retransmit), so after a link cut only the unacked tail of
the stream is re-sent: the acked prefix IS the resume state, no extra
protocol round trips.

Wire format — one ``comm_chunk`` message per slice, below the
application vocabulary like ``comm_ack``::

    chunk_stream : "c<rank>:<nonce>:<seq>"  sender-unique stream id
    chunk_idx    : 0-based slice index
    chunk_n      : total slices in the stream
    chunk_data   : the slice bytes
    chunk_crc    : crc32 of the slice (torn-frame detection)
    chunk_total  : total payload bytes
    chunk_inner_type : the inner message's msg_type (fault-plan scoping)
    round_idx    : copied from the inner message (fault-plan scoping)

plus a ``comm_chunk_reset`` control message (receiver -> sender) that
aborts a shed stream so the sender restarts it from scratch.

Capability negotiates DOWN per link, like the PR 18 codec negotiation:
every stamped outbound message additively advertises ``chunk_ok``; a
sender only chunks toward peers it has seen advertise.  Legacy peers
never advertise and keep whole-message uploads — wire-compatible in both
directions, zero extra round trips (the server's handshake/sync messages
precede any upload, so capability is known in time).

Durability composes with the PR 4/10/18 journal-before-ack contract one
level down: the receiving tier journals each accepted chunk BEFORE its
transport ack is released (via the ambient
:func:`~fedml_tpu.core.ingest.deferred_ack_scope` sink under the staged
pipeline, blocking append on the host path), so a server/edge kill
mid-upload replays its partial streams and resumes from the journal —
an acked chunk is never re-sent, a never-acked chunk is retransmitted
into the restored reassembler, and the application-level per-sender
dedup (``_journal_upload`` / edge ``_seen``) keeps the completed upload
exactly-once.

This file and ``core/ingest.py`` are the ONLY modules that may parse
chunk headers or mutate reassembly buffers (fedlint
``chunk-reassembly-seam``): a second parsing site is how resume
semantics and the exactly-once accounting silently fork.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import obs
from .communication.message import Message

logger = logging.getLogger(__name__)

#: transport-level chunk vocabulary (below MyMessage, like ``comm_ack``)
CHUNK_TYPE = "comm_chunk"
CHUNK_RESET_TYPE = "comm_chunk_reset"

#: additive capability advertisement on stamped messages
CHUNK_OK_KEY = "chunk_ok"

_KEY_STREAM = "chunk_stream"
_KEY_IDX = "chunk_idx"
_KEY_N = "chunk_n"
_KEY_DATA = "chunk_data"
_KEY_CRC = "chunk_crc"
_KEY_TOTAL = "chunk_total"
_KEY_INNER_TYPE = "chunk_inner_type"

#: params keys whose presence marks a message as payload-bearing (worth
#: serializing to measure); everything else is control traffic
_PAYLOAD_KEYS = (Message.MSG_ARG_KEY_MODEL_PARAMS, "hier_payload")

DEFAULT_CHUNK_WINDOW = 8
DEFAULT_BUFFER_BYTES = 64 << 20
_COMPLETED_LRU = 64
_MAX_STREAM_RESTARTS = 3


class ChunkError(RuntimeError):
    """A chunk failed integrity/admission checks: raised out of dispatch so
    the transport withholds the ack and forgets the msg-id — the sender's
    retransmitter redelivers the frame intact / later."""


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def split_payload(payload: bytes, chunk_bytes: int) -> List[bytes]:
    """Slice ``payload`` into ``chunk_bytes``-sized pieces (last may be
    short; an empty payload still yields one empty slice)."""
    chunk_bytes = max(1, int(chunk_bytes))
    if not payload:
        return [b""]
    return [payload[i:i + chunk_bytes]
            for i in range(0, len(payload), chunk_bytes)]


def is_chunk(msg: Message) -> bool:
    return msg.get_type() == CHUNK_TYPE


def is_chunk_reset(msg: Message) -> bool:
    return msg.get_type() == CHUNK_RESET_TYPE


def truncate_for_fault(msg: Message) -> Optional[Message]:
    """The ``truncated_frame`` fault's mangler: a shallow-COPIED chunk
    message whose slice bytes are torn in half (stale crc kept, so the
    receiver's integrity check rejects it).  Copying matters: the sender's
    retransmitter holds the ORIGINAL object and must re-send it intact.
    Returns None for non-chunk messages (nothing to tear)."""
    if not is_chunk(msg):
        return None
    params = dict(msg.get_params())
    data = params.get(_KEY_DATA) or b""
    params[_KEY_DATA] = bytes(data)[: len(data) // 2]
    torn = Message()
    torn.init(params)
    return torn


def build_chunks(stream_id: str, inner: Message, payload: bytes,
                 chunk_bytes: int) -> List[Message]:
    """Frame ``payload`` (the pickled inner params dict) as a list of
    ``comm_chunk`` messages carrying deterministic
    ``(stream, chunk_idx, chunk_n)`` headers and per-slice crc32."""
    slices = split_payload(payload, chunk_bytes)
    n = len(slices)
    rnd = inner.get("round_idx")
    out: List[Message] = []
    for idx, data in enumerate(slices):
        m = Message(CHUNK_TYPE, inner.get_sender_id(), inner.get_receiver_id())
        m.add_params(_KEY_STREAM, stream_id)
        m.add_params(_KEY_IDX, idx)
        m.add_params(_KEY_N, n)
        m.add_params(_KEY_DATA, data)
        m.add_params(_KEY_CRC, _crc(data))
        m.add_params(_KEY_TOTAL, len(payload))
        m.add_params(_KEY_INNER_TYPE, str(inner.get_type()))
        if rnd is not None:
            m.add_params("round_idx", rnd)
        tp = inner.get(Message.MSG_ARG_KEY_TRACEPARENT)
        if tp is not None:
            m.add_params(Message.MSG_ARG_KEY_TRACEPARENT, tp)
        out.append(m)
    return out


# ---------------------------------------------------------------------------
# sender: windowed stream send over the reliable link
# ---------------------------------------------------------------------------
class _StreamState:
    __slots__ = ("stream_id", "total", "n", "acked", "resent_bytes",
                 "aborted", "failed", "all_sent", "inner", "restarts")

    def __init__(self, stream_id: str, total: int, n: int, inner: Message):
        self.stream_id = stream_id
        self.total = int(total)
        self.n = int(n)
        self.acked = 0
        self.resent_bytes = 0
        self.aborted = False
        self.failed = False
        self.all_sent = False
        self.inner = inner
        self.restarts = 0


class ChunkedSender:
    """Split-and-stream side: at most ``window`` unacked chunks in flight,
    resume accounting per stream, restart on a receiver's shed reset.

    Delivery ownership matches whole-message semantics: ``send`` returns
    once the stream is registered and handed to a pump thread (the
    retransmitter owns each unacked chunk); the window only throttles how
    far ahead of the acks the stream runs, which is exactly what bounds
    the bytes a mid-stream link cut can cost.  The pump MUST be
    off-thread: ``send`` is normally called from the manager's dispatch
    thread, and the acks the window waits on arrive on that same thread —
    pumping inline would deadlock the node against itself."""

    def __init__(self, manager: Any, *, chunk_bytes: int, window: int):
        self._manager = manager
        self._stats = manager._comm_stats
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.window = max(1, int(window))
        self._cond = threading.Condition()
        self._inflight: Dict[str, Tuple[str, int]] = {}  # msg_id -> (stream, nbytes)
        self._streams: Dict[str, _StreamState] = {}
        self._seq = 0
        self._nonce = uuid.uuid4().hex[:8]
        self._closed = False
        link = manager._link
        patience = (link.max_retries + 1) * link.backoff_max_s + 2.0
        self._patience_s = max(5.0, patience)
        # liveness contracts: the pump watchdog proves the per-stream pump
        # threads are making passes; the stall monitor watches the ack
        # stream itself (a live pump draining into a dead peer is a stall,
        # not a wedge — different signal, different reaction)
        self._watchdog = obs.health_watchdog(
            f"chunk.pump.rank{manager.rank}")
        self._stall = obs.health_silence(
            f"chunk.stream_stall.rank{manager.rank}",
            max_age_s=self._patience_s)
        link.add_ack_listener(self._on_ack)

    def _new_stream_id(self) -> str:
        with self._cond:
            self._seq += 1
            return f"c{self._manager.rank}:{self._nonce}:{self._seq}"

    # -- link callback -------------------------------------------------------
    def _on_ack(self, msg_id: str, attempts: int, delivered: bool) -> None:
        self._stall.note()
        finished: Optional[_StreamState] = None
        with self._cond:
            entry = self._inflight.pop(msg_id, None)
            self._cond.notify_all()
            if entry is None:
                return
            stream_id, nbytes = entry
            st = self._streams.get(stream_id)
            if st is None:
                return
            if not delivered:
                st.failed = True
            else:
                st.acked += 1
                if attempts > 0:
                    resent = attempts * nbytes
                    st.resent_bytes += resent
                    self._stats.inc("chunk_bytes_resent", resent)
            if st.all_sent and st.acked >= st.n and not st.failed:
                finished = self._streams.pop(stream_id)
            live = bool(self._streams)
        if finished is not None:
            self._finish_stream(finished)
        if not live:
            self._watchdog.idle()
            self._stall.idle()

    def _finish_stream(self, st: _StreamState) -> None:
        self._stats.inc("streams_completed")
        obs.counter_inc("ingest.streams_completed")
        if st.resent_bytes > 0:
            # the resumability win, in bytes: a whole-message restart would
            # have re-sent the full payload; chunking re-sent only the
            # retransmitted slices
            saved = max(0, st.total - st.resent_bytes)
            self._stats.inc("resume_bytes_saved", saved)
            obs.counter_inc("ingest.resume_bytes_saved", saved)
        obs.span_event("chunk_stream_complete", obs.extract(st.inner),
                       node=self._manager.rank, stream=st.stream_id,
                       n_chunks=st.n, total_bytes=st.total,
                       resent_bytes=st.resent_bytes)

    def on_reset(self, msg: Message) -> None:
        """Receiver shed this stream: abort the in-flight window and replay
        the whole stream from scratch under a FRESH stream id + fresh msg
        ids (the receiver's dedup window would re-ack the old ones without
        delivering).  Restarted off-thread: this runs on the receive path,
        which must stay free to consume the restart's acks."""
        stream_id = str(msg.get(_KEY_STREAM))
        with self._cond:
            st = self._streams.get(stream_id)
            if st is None:
                return
            st.aborted = True
            self._streams.pop(stream_id, None)
            stale = [mid for mid, (sid, _) in self._inflight.items()
                     if sid == stream_id]
            for mid in stale:
                self._inflight.pop(mid, None)
            self._cond.notify_all()
            inner, restarts = st.inner, st.restarts
        if restarts >= _MAX_STREAM_RESTARTS:
            logger.warning("rank %s: stream %s shed %d times; giving up "
                           "(application-level retry owns it now)",
                           self._manager.rank, stream_id, restarts)
            return
        self._stats.inc("streams_restarted")
        t = threading.Thread(
            target=lambda: self.send(inner, restarts=restarts + 1),
            daemon=True, name=f"chunk-restart-rank{self._manager.rank}")
        t.start()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._inflight.clear()
            self._streams.clear()
            self._cond.notify_all()
        self._watchdog.close()
        self._stall.close()

    # -- stream send ---------------------------------------------------------
    def serialize(self, message: Message) -> bytes:
        """The stream payload: the pickled params dict — the same bytes a
        binary transport would have put on the wire for the whole message
        (``CachedPayload`` substitutes its cached blob via ``__reduce__``)."""
        return pickle.dumps(message.get_params(),
                            protocol=pickle.HIGHEST_PROTOCOL)

    def send(self, message: Message, restarts: int = 0,
             payload: Optional[bytes] = None) -> bool:
        """Chunk-stream ``message``; False when it fits one chunk (the
        caller sends it whole)."""
        if payload is None:
            payload = self.serialize(message)
        if len(payload) <= self.chunk_bytes:
            return False
        stream_id = self._new_stream_id()
        chunks = build_chunks(stream_id, message, payload, self.chunk_bytes)
        st = _StreamState(stream_id, len(payload), len(chunks), message)
        st.restarts = restarts
        with self._cond:
            if self._closed:
                return True
            self._streams[stream_id] = st
        obs.span_event("chunk_stream_start", obs.extract(message),
                       node=self._manager.rank, stream=stream_id,
                       n_chunks=len(chunks), total_bytes=len(payload),
                       inner_type=str(message.get_type()), restart=restarts)
        # arm the contracts from the CALLING thread: a pump that dies
        # before its first pass still expires, and an ack that never
        # arrives still reads as a stall
        self._watchdog.beat()
        self._stall.note()
        threading.Thread(
            target=self._pump, args=(st, chunks), daemon=True,
            name=f"chunk-pump-rank{self._manager.rank}").start()
        return True

    def _pump(self, st: _StreamState, chunks: List[Message]) -> None:
        """The windowed loop, on a dedicated thread per stream."""
        link = self._manager._link
        stream_id = st.stream_id
        deadline = time.monotonic() + self._patience_s
        for chunk in chunks:
            self._watchdog.beat()
            with self._cond:
                while (len([1 for sid, _ in self._inflight.values()
                            if sid == stream_id]) >= self.window
                       and not st.aborted and not self._closed):
                    # a window-throttled pump is alive (the stall monitor
                    # owns missing-ack detection); keep the liveness beat
                    self._watchdog.beat()
                    if time.monotonic() > deadline:
                        # a wedged window (dead peer past retransmit
                        # give-up) must not wedge the round thread forever
                        logger.warning(
                            "rank %s: stream %s window stalled %.0fs; "
                            "draining without acks", self._manager.rank,
                            stream_id, self._patience_s)
                        for mid in [m for m, (sid, _) in
                                    self._inflight.items()
                                    if sid == stream_id]:
                            self._inflight.pop(mid, None)
                        st.failed = True
                        break
                    self._cond.wait(timeout=0.05)
                if st.aborted or self._closed:
                    return
                # pre-register under the lock BEFORE the send: the ack can
                # race back on the receive thread the moment the frame is out
                msg_id = link.stamp(chunk)
                self._inflight[msg_id] = (
                    stream_id, len(chunk.get(_KEY_DATA) or b""))
                deadline = time.monotonic() + self._patience_s
            self._stats.inc("chunks_sent")
            obs.counter_inc("ingest.chunks_sent")
            self._manager._send_one(chunk, msg_id=msg_id)
        with self._cond:
            st.all_sent = True
            finished = (st.acked >= st.n and not st.failed
                        and self._streams.pop(stream_id, None) is not None)
            live = bool(self._streams)
        if finished:
            self._finish_stream(st)
        if not live:
            self._watchdog.idle()
            self._stall.idle()


# ---------------------------------------------------------------------------
# receiver: journaled reassembly with pressure shedding
# ---------------------------------------------------------------------------
class _Reassembly:
    __slots__ = ("stream_id", "sender", "n", "total", "chunks", "nbytes",
                 "round_idx", "inner_type", "born")

    def __init__(self, stream_id: str, sender: int, n: int, total: int,
                 round_idx: Any, inner_type: str, born: int):
        self.stream_id = stream_id
        self.sender = int(sender)
        self.n = int(n)
        self.total = int(total)
        self.chunks: Dict[int, bytes] = {}
        self.nbytes = 0
        self.round_idx = round_idx
        self.inner_type = inner_type
        self.born = born  # admission order: shed-oldest victim selection


class ChunkReassembler:
    """Collect chunks per stream, journal each accepted chunk before its
    ack, dispatch ONLY completed inner messages, and shed the oldest
    incomplete stream under buffer pressure (withholding the over-budget
    chunk's ack so its sender retransmits after the shed reset lands)."""

    def __init__(self, manager: Any, *, buffer_bytes: int = DEFAULT_BUFFER_BYTES,
                 resume: bool = True):
        self._manager = manager
        self._stats = manager._comm_stats
        self.buffer_bytes = max(1, int(buffer_bytes))
        self.resume = bool(resume)
        self._lock = threading.RLock()
        self._streams: "OrderedDict[str, _Reassembly]" = OrderedDict()
        # stream_id -> retained payload (None once dispatched); bounds the
        # replay-resume memory and dedups re-deliveries of finished streams
        self._completed: "OrderedDict[str, Optional[bytes]]" = OrderedDict()
        self._born = 0
        self._buffered = 0
        # bound by the recovery owner (ServerRecoveryMixin / EdgeAggregator):
        # fn(round_idx, record) journals one chunk record with the same
        # sink-or-blocking idiom as _journal_upload
        self._journal: Optional[Callable[[int, Dict[str, Any]], None]] = None

    def bind_journal(self, fn: Callable[[int, Dict[str, Any]], None]) -> None:
        self._journal = fn

    # -- admission -----------------------------------------------------------
    def accept(self, msg: Message, dispatch: Callable[[Message], None]) -> None:
        stream_id = str(msg.get(_KEY_STREAM))
        idx = int(msg.get(_KEY_IDX))
        n = int(msg.get(_KEY_N))
        total = int(msg.get(_KEY_TOTAL))
        data = msg.get(_KEY_DATA)
        data = bytes(data) if data is not None else b""
        want_crc = int(msg.get(_KEY_CRC, -1))
        if _crc(data) != want_crc:
            self._stats.inc("chunks_crc_bad")
            obs.counter_inc("ingest.chunks_crc_bad")
            raise ChunkError(
                f"chunk {stream_id}[{idx}] crc mismatch "
                f"({_crc(data):08x} != {want_crc & 0xFFFFFFFF:08x}); "
                "withholding ack for retransmit")
        with self._lock:
            if stream_id in self._completed:
                payload = self._completed[stream_id]
                if payload is None:
                    # finished and dispatched: a late duplicate, re-acked
                    self._stats.inc("chunks_dup")
                    obs.counter_inc("ingest.chunks_dup")
                    return
                # journal-restored stream whose final ack was lost with the
                # dead incarnation: the sender's retransmit is the signal to
                # dispatch it now, exactly once (app-level dedup downstream
                # drops it if the upload record also survived)
                self._completed[stream_id] = None
                inner = self._build_inner(payload)
            else:
                st = self._streams.get(stream_id)
                if st is None:
                    st = self._admit(msg, stream_id, n, total)
                if idx in st.chunks:
                    self._stats.inc("chunks_dup")
                    obs.counter_inc("ingest.chunks_dup")
                    return
                self._shed_for(len(data), keep=stream_id)
                st.chunks[idx] = data
                st.nbytes += len(data)
                self._buffered += len(data)
                self._stats.inc("chunks_received")
                obs.counter_inc("ingest.chunks_received")
                if len(st.chunks) == 1:
                    obs.span_event("chunk_stream_start", obs.extract(msg),
                                   node=self._manager.rank, side="recv",
                                   stream=stream_id, n_chunks=n,
                                   total_bytes=total)
                self._journal_chunk(msg, st, idx, data)
                if len(st.chunks) < st.n:
                    return
                payload = b"".join(st.chunks[i] for i in range(st.n))
                if len(payload) != st.total:
                    # a torn stream header slipped through per-slice crc:
                    # drop the stream, withhold this ack — full restart
                    self._drop_stream(stream_id)
                    self._stats.inc("chunks_crc_bad")
                    obs.counter_inc("ingest.chunks_crc_bad")
                    raise ChunkError(
                        f"stream {stream_id} reassembled {len(payload)} "
                        f"bytes, header said {st.total}")
                inner = self._build_inner(payload)
        # dispatch OUTSIDE the reassembly lock (handlers take round locks);
        # a raise here propagates so the transport withholds the final
        # chunk's ack — on the retransmit the stream is still complete
        try:
            dispatch(inner)
        except BaseException:
            with self._lock:
                st = self._streams.get(stream_id)
                if st is not None and idx in st.chunks:
                    self._buffered -= len(st.chunks.pop(idx))
                    st.nbytes -= len(data)
            raise
        with self._lock:
            self._drop_stream(stream_id)
            self._remember_completed(stream_id, None)
            self._stats.inc("streams_completed")
        obs.span_event("chunk_stream_complete", obs.extract(msg),
                       node=self._manager.rank, side="recv",
                       stream=stream_id, total_bytes=total)

    def _admit(self, msg: Message, stream_id: str, n: int,
               total: int) -> _Reassembly:
        self._born += 1
        st = _Reassembly(stream_id, int(msg.get_sender_id()), n, total,
                         msg.get("round_idx"),
                         str(msg.get(_KEY_INNER_TYPE, "")), self._born)
        self._streams[stream_id] = st
        return st

    def _shed_for(self, incoming: int, keep: str) -> None:
        """Make room for ``incoming`` bytes by dropping oldest-incomplete
        streams (never ``keep``), telling each victim's sender to restart."""
        while (self._buffered + incoming > self.buffer_bytes
               and any(sid != keep for sid in self._streams)):
            victim = min(
                (st for sid, st in self._streams.items() if sid != keep),
                key=lambda st: st.born)
            sender = victim.sender
            self._drop_stream(victim.stream_id)
            self._stats.inc("streams_shed")
            obs.counter_inc("ingest.streams_shed")
            logger.warning(
                "rank %s: reassembly pressure (%d buffered, cap %d); shed "
                "stream %s from %s", self._manager.rank, self._buffered,
                self.buffer_bytes, victim.stream_id, sender)
            reset = Message(CHUNK_RESET_TYPE, self._manager.rank, sender)
            reset.add_params(_KEY_STREAM, victim.stream_id)
            try:
                self._manager._send_one(reset)
            except Exception:
                # best-effort: without the reset the victim's retransmits
                # re-admit the stream chunk by chunk (slower, still correct)
                logger.info("rank %s: shed reset send failed",
                            self._manager.rank, exc_info=True)

    def _drop_stream(self, stream_id: str) -> None:
        st = self._streams.pop(stream_id, None)
        if st is not None:
            self._buffered -= st.nbytes

    def _remember_completed(self, stream_id: str,
                            payload: Optional[bytes]) -> None:
        self._completed[stream_id] = payload
        self._completed.move_to_end(stream_id)
        while len(self._completed) > _COMPLETED_LRU:
            self._completed.popitem(last=False)

    def _build_inner(self, payload: bytes) -> Message:
        inner = Message()
        inner.init(pickle.loads(payload))
        return inner

    # -- durability ----------------------------------------------------------
    def _journal_chunk(self, msg: Message, st: _Reassembly, idx: int,
                       data: bytes) -> None:
        if self._journal is None or not self.resume:
            return
        rnd = st.round_idx
        try:
            rnd = int(rnd) if rnd is not None else 0
        except (TypeError, ValueError):
            rnd = 0
        self._journal(rnd, {
            "kind": "chunk",
            "round_idx": rnd,
            "sender": st.sender,
            _KEY_STREAM: st.stream_id,
            _KEY_IDX: int(idx),
            _KEY_N: st.n,
            _KEY_TOTAL: st.total,
            _KEY_INNER_TYPE: st.inner_type,
            _KEY_DATA: data,
        })

    def restore(self, records: List[Dict[str, Any]]) -> int:
        """Rebuild reassembly state from replayed journal chunk records.
        Completed streams retain their payload but are NOT dispatched — a
        live retransmit of any of their chunks (guaranteed whenever the
        final ack died with the old incarnation) triggers the dispatch,
        and the application-level sender dedup keeps it exactly-once."""
        restored = 0
        with self._lock:
            for rec in records:
                if rec.get("kind") != "chunk":
                    continue
                stream_id = str(rec[_KEY_STREAM])
                if stream_id in self._completed:
                    continue
                st = self._streams.get(stream_id)
                if st is None:
                    self._born += 1
                    st = _Reassembly(
                        stream_id, int(rec.get("sender", 0)),
                        int(rec[_KEY_N]), int(rec[_KEY_TOTAL]),
                        rec.get("round_idx"),
                        str(rec.get(_KEY_INNER_TYPE, "")), self._born)
                    self._streams[stream_id] = st
                idx = int(rec[_KEY_IDX])
                if idx in st.chunks:
                    continue
                data = bytes(rec[_KEY_DATA])
                st.chunks[idx] = data
                st.nbytes += len(data)
                self._buffered += len(data)
                restored += 1
                if len(st.chunks) == st.n:
                    payload = b"".join(st.chunks[i] for i in range(st.n))
                    self._drop_stream(stream_id)
                    if len(payload) == st.total:
                        self._remember_completed(stream_id, payload)
        if restored:
            obs.counter_inc("ingest.chunks_restored", restored)
            logger.info("rank %s: restored %d journaled chunks "
                        "(%d open streams, %d completed-held)",
                        self._manager.rank, restored, len(self._streams),
                        len(self._completed))
        return restored

    def stats_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"open_streams": len(self._streams),
                    "buffered_bytes": self._buffered,
                    "completed_held": len(self._completed)}


# ---------------------------------------------------------------------------
# per-manager facade
# ---------------------------------------------------------------------------
class ChunkingState:
    """One node runtime's chunking plane: capability map + sender +
    reassembler, wired into the comm manager's send/dispatch seams."""

    def __init__(self, manager: Any):
        a = manager.args
        g = (lambda k, d: getattr(a, k, d) if a is not None else d)
        self.chunk_bytes = int(g("upload_chunk_bytes", 0) or 0)
        self.window = int(g("chunk_window", DEFAULT_CHUNK_WINDOW)
                          or DEFAULT_CHUNK_WINDOW)
        self.resume = bool(g("chunk_resume", True))
        self.receive_ok = bool(g("chunk_receive", True))
        buffer_bytes = int(g("chunk_buffer_bytes", DEFAULT_BUFFER_BYTES)
                           or DEFAULT_BUFFER_BYTES)
        self._manager = manager
        self._peer_ok: set = set()
        self._peer_lock = threading.Lock()
        self.sender = (ChunkedSender(manager, chunk_bytes=self.chunk_bytes,
                                     window=self.window)
                       if self.chunk_bytes > 0 else None)
        self.reassembler = (ChunkReassembler(manager, buffer_bytes=buffer_bytes,
                                             resume=self.resume)
                            if self.receive_ok else None)

    @classmethod
    def maybe_create(cls, manager: Any) -> Optional["ChunkingState"]:
        if manager._link is None:
            return None
        return cls(manager)

    # -- negotiation ---------------------------------------------------------
    def advertise(self, msg: Message) -> None:
        """Stamped outbound messages carry the additive capability flag."""
        if self.receive_ok:
            msg.add_params(CHUNK_OK_KEY, 1)

    def observe(self, msg: Message) -> None:
        """Record the peer's advertised capability (inbound seam)."""
        if msg.get(CHUNK_OK_KEY):
            try:
                peer = int(msg.get_sender_id())
            except (TypeError, ValueError):
                return
            with self._peer_lock:
                self._peer_ok.add(peer)

    def peer_supports(self, rank: Any) -> bool:
        try:
            rank = int(rank)
        except (TypeError, ValueError):
            return False
        with self._peer_lock:
            return rank in self._peer_ok

    # -- send seam -----------------------------------------------------------
    def maybe_send_chunked(self, msg: Message) -> bool:
        """True when ``msg`` was consumed as a chunk stream.  Negotiates
        down: non-advertising peers, control traffic, and under-threshold
        payloads all fall back to the whole-message path."""
        if self.sender is None:
            return False
        mtype = msg.get_type()
        if mtype in (CHUNK_TYPE, CHUNK_RESET_TYPE):
            return False
        params = msg.get_params()
        if not any(k in params for k in _PAYLOAD_KEYS):
            return False
        if not self.peer_supports(msg.get_receiver_id()):
            return False
        return self.sender.send(msg)

    # -- dispatch seam -------------------------------------------------------
    def intercepts(self, msg: Message) -> bool:
        t = msg.get_type()
        if t == CHUNK_TYPE:
            return self.reassembler is not None
        if t == CHUNK_RESET_TYPE:
            return self.sender is not None
        return False

    def dispatch_chunk(self, msg: Message,
                       dispatch: Callable[[Message], None]) -> None:
        if is_chunk_reset(msg):
            assert self.sender is not None
            self.sender.on_reset(msg)
            return
        assert self.reassembler is not None
        self.reassembler.accept(msg, dispatch)

    # -- durability wiring ---------------------------------------------------
    def bind_journal(self, fn: Callable[[int, Dict[str, Any]], None]) -> None:
        if self.reassembler is not None:
            self.reassembler.bind_journal(fn)

    def restore(self, records: List[Dict[str, Any]]) -> int:
        if self.reassembler is None:
            return 0
        return self.reassembler.restore(records)

    def close(self) -> None:
        if self.sender is not None:
            self.sender.close()
