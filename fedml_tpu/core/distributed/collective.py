"""Host-plane process-group collectives over TCP.

Parity with the role reference ``cross_silo/client/process_group_manager.py``
+ ``torch.distributed`` (NCCL/GLOO process groups) play for multi-process /
multi-host runs: rendezvous, broadcast, allreduce, allgather, barrier over
pytrees of numpy arrays.

TPU-first split of responsibilities: DEVICE-side gradient/batch collectives
are XLA's job (psum/all_gather compiled over ICI inside the jitted step —
see parallel/mesh.py and the in-mesh simulator); what remains for the host
plane is low-rate model-blob coordination between PROCESSES (intra-silo
slave sync, multi-host bootstrap), which the reference routes through
NCCL/MPI.  That traffic is latency-tolerant and model-sized, so a star
topology over persistent TCP sockets (rank 0 = hub) is the right-sized
transport: reduce-to-hub + rebroadcast is 2 model transfers per allreduce,
and no GPU/TPU interconnect is touched.

Rendezvous: rank 0 listens on ``addr``; other ranks connect and send a
FIXED-FORMAT join preamble (length-prefixed raw token bytes + rank — no
pickle) that the hub verifies BEFORE any unpickling happens on that
connection; post-join frames are pickled, so the token is the admission
boundary (still bind to loopback or a trusted network: the token rides
plaintext TCP).  All ops are collective — every rank must call them in
the same order (the torch.distributed contract).  Collective waits use
``op_timeout`` (large but finite) so a dead peer fails the group instead
of hanging it forever.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, List, Optional

import jax
import numpy as np

logger = logging.getLogger(__name__)

Pytree = Any


def _send_frame(sock: socket.socket, obj: Any) -> None:
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack(">Q", len(blob)) + blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed during frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, n))


def _join_preamble(token: Optional[str], rank: int) -> bytes:
    """Fixed-format join: [u16 token_len][token utf-8][i32 rank] — parseable
    and verifiable WITHOUT pickle, so an unauthenticated peer never reaches
    ``pickle.loads``."""
    tok = (token or "").encode("utf-8")
    if len(tok) > 256:
        raise ValueError("pg token too long (max 256 utf-8 bytes)")
    return struct.pack(">H", len(tok)) + tok + struct.pack(">i", rank)


def _recv_join(sock: socket.socket, token: Optional[str]) -> int:
    """Read + verify a join preamble; raises on token mismatch.  Returns the
    peer's rank.  No pickle is involved."""
    (tok_len,) = struct.unpack(">H", _recv_exact(sock, 2))
    if tok_len > 256:
        raise ValueError("oversized join token")
    tok = _recv_exact(sock, tok_len).decode("utf-8", errors="replace")
    if tok != (token or ""):
        raise ValueError("bad join token")
    (rank,) = struct.unpack(">i", _recv_exact(sock, 4))
    return rank


def _to_host(tree: Pytree) -> Pytree:
    """Device arrays -> numpy before pickling (sockets move host memory)."""
    return jax.tree_util.tree_map(np.asarray, tree)


class ProcessGroup:
    """A star-topology process group; rank 0 is the hub.

    >>> pg = ProcessGroup(rank, world_size, addr=("127.0.0.1", 29500))
    >>> tree = pg.broadcast(tree)          # src=0 by default
    >>> mean = pg.allreduce_mean(grads)
    """

    def __init__(self, rank: int, world_size: int, addr=("127.0.0.1", 29500),
                 timeout: float = 60.0, token: Optional[str] = None,
                 op_timeout: float = 1800.0):
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.addr = (addr[0], int(addr[1]))
        self.timeout = float(timeout)
        self.token = token
        # collective waits: far longer than the rendezvous window (a master
        # legitimately blocks between syncs doing WAN round trips), but
        # finite so a dead peer raises socket.timeout instead of hanging
        # every other rank forever
        self.op_timeout = float(op_timeout)
        self._peers: List[Optional[socket.socket]] = [None] * world_size
        self._server: Optional[socket.socket] = None
        if world_size > 1:
            self._rendezvous()

    # -- bootstrap -----------------------------------------------------------
    def _rendezvous(self) -> None:
        if self.rank == 0:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(self.addr)
            srv.listen(self.world_size)
            srv.settimeout(self.timeout)
            self._server = srv
            deadline = time.time() + self.timeout
            joined = 0
            while joined < self.world_size - 1:
                if time.time() > deadline:
                    raise ConnectionError(
                        f"hub: rendezvous timed out with {joined} of "
                        f"{self.world_size - 1} peers joined")
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    # surface the descriptive diagnostic, not a raw accept
                    # traceback, when no peer ever connects
                    raise ConnectionError(
                        f"hub: rendezvous timed out with {joined} of "
                        f"{self.world_size - 1} peers joined") from None
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(self.timeout)
                try:
                    peer_rank = _recv_join(conn, self.token)
                    if (not 0 < peer_rank < self.world_size
                            or self._peers[peer_rank] is not None):
                        raise ValueError(f"bad join from rank {peer_rank}")
                except Exception:
                    logger.warning("pg hub: rejected a join attempt", exc_info=True)
                    conn.close()
                    continue
                conn.settimeout(self.op_timeout)
                self._peers[peer_rank] = conn
                joined += 1
            logger.info("pg hub up: %d peers joined", self.world_size - 1)
        else:
            deadline = time.time() + self.timeout
            last_err: Optional[Exception] = None
            while time.time() < deadline:
                try:
                    s = socket.create_connection(self.addr, timeout=self.timeout)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    s.sendall(_join_preamble(self.token, self.rank))
                    s.settimeout(self.op_timeout)
                    self._peers[0] = s
                    return
                except OSError as e:  # hub not up yet: retry
                    last_err = e
                    time.sleep(0.1)
            raise ConnectionError(f"rank {self.rank}: rendezvous timed out: {last_err}")

    # -- collectives ---------------------------------------------------------
    def broadcast(self, tree: Pytree = None, src: int = 0) -> Pytree:
        """Every rank returns src's tree.  Non-src ranks may pass None."""
        if self.world_size == 1:
            return tree
        if src != 0:
            # route through the hub: src uploads, hub rebroadcasts
            if self.rank == src:
                _send_frame(self._peers[0], _to_host(tree))
                return tree
            if self.rank == 0:
                tree = _recv_frame(self._peers[src])
        if self.rank == 0:
            payload = _to_host(tree)
            for r, sock in enumerate(self._peers):
                if sock is not None and r != src:
                    _send_frame(sock, payload)
            return tree
        if self.rank == src:
            return tree
        return _recv_frame(self._peers[0])

    def gather(self, tree: Pytree, dst: int = 0) -> Optional[List[Pytree]]:
        """dst returns [tree_rank0, ..., tree_rankN-1]; others return None."""
        if self.world_size == 1:
            return [tree]
        if self.rank == 0:
            out: List[Pytree] = [None] * self.world_size
            out[0] = _to_host(tree)
            for r, sock in enumerate(self._peers):
                if sock is not None:
                    out[r] = _recv_frame(sock)
            if dst == 0:
                return out
            _send_frame(self._peers[dst], out)
            return None
        _send_frame(self._peers[0], _to_host(tree))
        if self.rank == dst:
            return _recv_frame(self._peers[0])
        return None

    def allgather(self, tree: Pytree) -> List[Pytree]:
        gathered = self.gather(tree, dst=0)
        return self.broadcast(gathered, src=0)

    def allreduce_sum(self, tree: Pytree) -> Pytree:
        """Elementwise tree sum across ranks (reduce-to-hub + rebroadcast)."""
        if self.world_size == 1:
            return tree
        gathered = self.gather(tree, dst=0)
        if self.rank == 0:
            # lint_agg: allow — collective allreduce primitive (the comm
            # layer the aggregators sit ON TOP of), not client aggregation
            reduced = jax.tree_util.tree_map(  # lint_agg: allow
                lambda *xs: np.sum(np.stack(xs, 0), axis=0), *gathered
            )
        else:
            reduced = None
        return self.broadcast(reduced, src=0)

    def allreduce_mean(self, tree: Pytree, weight: float = 1.0) -> Pytree:
        """Weighted mean: sum(w_i * x_i) / sum(w_i) across ranks.  The weight
        rides the same gather as the tree — one gather + one broadcast total,
        not two sequential collectives."""
        if self.world_size == 1:
            return tree
        gathered = self.gather((_to_host(tree), float(weight)), dst=0)
        if self.rank == 0:
            trees = [t for t, _ in gathered]
            ws = [w for _, w in gathered]
            den = sum(ws)
            den = den if den > 0 else 1.0
            # lint_agg: allow — weighted allreduce collective primitive
            reduced = jax.tree_util.tree_map(  # lint_agg: allow
                lambda *xs: sum(x * w for x, w in zip(xs, ws)) / den, *trees
            )
        else:
            reduced = None
        return self.broadcast(reduced, src=0)

    def barrier(self) -> None:
        self.allgather(np.zeros(()))

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        for sock in self._peers:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass

    def __enter__(self) -> "ProcessGroup":
        return self

    def __exit__(self, *_) -> None:
        self.close()
