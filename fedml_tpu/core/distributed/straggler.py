"""Round/handshake straggler tolerance — the one copy of the
concurrency-critical timer machinery shared by the cross-silo and
cross-device server managers.

The reference server managers block a round forever on a dead client
(``check_whether_all_receive`` with no timer anywhere).  This mixin bounds
both waits when ``args.round_timeout_s`` is set:

* the per-round collect: on expiry with >= ``round_timeout_min_clients``
  uploads, the round closes with the partial cohort; below the floor the
  timer re-arms (aggregating nothing is worse than waiting);
* the ONLINE handshake: a client that never comes up cannot wedge round 0.

Concurrency contract: the receive loop's handler thread and the timer
thread synchronize on ``self._round_lock``; every phase change (handshake
completes, a round closes) bumps ``self._gen`` so a timer callback that
already fired but lost the lock race no-ops on the generation mismatch
(``threading.Timer.cancel`` cannot stop an in-flight callback).

Host manager requirements (both server managers satisfy them):
``self.args`` (round_idx), ``self.aggregator`` with
``received_indices()``/``consume_received(got)``/partial ``aggregate``,
``self.client_online_status``/``self.client_num``/``self.is_initialized``,
``self.client_id_list_in_this_round``, ``self.send_message``,
``self.finish``, plus ``_finalize_round(indices)`` (lock held; bumps come
from here via ``_finalize_safely``), ``send_init_msg()`` and
``send_finish_msg()``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class RoundTimeoutMixin:
    def init_straggler_tolerance(self, args) -> None:
        """Call from the manager's __init__ (0 = reference wait-forever)."""
        self.round_timeout_s = float(getattr(args, "round_timeout_s", 0) or 0)
        self.round_timeout_min_clients = int(
            getattr(args, "round_timeout_min_clients", 1) or 1
        )
        self._round_lock = threading.Lock()
        self._round_timer: Optional[threading.Timer] = None
        self._handshake_timer: Optional[threading.Timer] = None
        self._gen = 0  # phase generation: stale timer callbacks no-op
        self._finished = False
        # set on the first timeout-close: only from then on can a stale
        # upload exist (every earlier round closed with its full cohort)
        self._had_timeout_close = False
        # client_id -> incarnation epoch from its last ONLINE (None until a
        # client reports one); an epoch CHANGE after init = mid-run rejoin
        self._client_epochs: Dict[int, str] = {}
        self.rejoin_count = 0

    # -- rejoin ---------------------------------------------------------------
    def _note_client_online(self, sender: int, epoch) -> bool:
        """(lock held) Record an ONLINE report; return True when it is a
        mid-run REJOIN that the host manager must answer with a resync of the
        current round (``_resync_rejoined_client``).

        A rejoin is: the run is already initialized AND the client reports an
        incarnation epoch that is new (its pre-crash ONLINE may have predated
        the server, so an unknown epoch after init also counts) or different
        from the one we knew.  The same incarnation re-reporting ONLINE (the
        handshake's double-send, a late CHECK reply) is NOT a rejoin.  Legacy
        epoch-less clients never trigger a resync — the reference wire keeps
        its reference semantics."""
        prev = self._client_epochs.get(int(sender))
        if epoch is not None:
            self._client_epochs[int(sender)] = str(epoch)
        self.client_online_status[int(sender)] = True
        if not self.is_initialized or epoch is None:
            return False
        if prev is not None and str(epoch) == prev:
            return False
        self.rejoin_count += 1
        stats = getattr(self, "_comm_stats", None)
        if stats is not None:
            stats.inc("rejoins")
        from .. import obs

        obs.span_event("rejoin", round_idx=int(self.args.round_idx),
                       node=getattr(self, "rank", 0), client=int(sender),
                       prev_epoch=prev, epoch=str(epoch))
        self._note_population_rejoin(sender)
        logger.warning(
            "client %s REJOINED mid-run (epoch %s -> %s): resyncing round %d",
            sender, prev, epoch, self.args.round_idx,
        )
        return True

    # -- sends ---------------------------------------------------------------
    def _send_safe(self, m) -> None:
        """Fan-out send that survives a dead receiver.  Swallowing is only
        safe when the round timer covers the lost message — with the knob
        off (reference semantics) the error re-raises loudly, EXCEPT on the
        FINISH fan-out where aborting the loop would leave the surviving
        clients (and this server) hanging instead."""
        try:
            self.send_message(m)
        except Exception as e:
            logger.warning("send %s -> %s failed: %s",
                           m.get_type(), m.get_receiver_id(), e)
            if self.round_timeout_s <= 0 and not self._finished:
                raise

    def _is_stale_upload(self, msg_round, sender) -> bool:
        """(lock held) True when an upload's round tag does not match the
        current round — a straggler upload for an already-closed round: the
        client will pick up the current sync next (the reference has no tag
        and would silently fold it into the wrong round).

        Untagged uploads (``msg_round`` None): accepted until the FIRST
        timeout-close — while every round still closes with its full
        cohort, no upload can be stale, so legacy untagged clients keep
        working (dropping them outright would livelock an untagged fleet:
        rounds would never reach the min-client floor).  From the first
        timeout-close on, a round-less late arrival is exactly the
        wrong-round corruption the tag exists to prevent (in cross-silo
        the is_delta path would rebase a stale delta onto the new global),
        so untagged uploads are then dropped loudly.  All in-repo clients
        tag."""
        if msg_round is None:
            if self.round_timeout_s <= 0 or not self._had_timeout_close:
                return False
            logger.warning(
                "dropping UNTAGGED upload from client %s: a round has "
                "already closed by timeout (round_timeout_s=%.1f), so an "
                "upload without a round tag cannot be matched to the "
                "current round %d — upgrade the client to send "
                "MSG_ARG_KEY_ROUND_INDEX",
                sender, self.round_timeout_s, self.args.round_idx,
            )
            self._note_rejected_late(sender)
            return True
        if int(msg_round) == int(self.args.round_idx):
            return False
        logger.warning("dropping stale round-%s upload from client %s "
                       "(current round %d)", msg_round, sender,
                       self.args.round_idx)
        self._note_rejected_late(sender)
        return True

    # -- population hooks ------------------------------------------------------
    # No-op seams the population pacing mixin (core/population/pacing.py)
    # overrides; kept here so this mixin stays usable without a population.
    def _note_rejected_late(self, sender) -> None:
        """(lock held) A late/stale upload was dropped."""

    def _note_population_rejoin(self, sender) -> None:
        """(lock held) A crashed client rejoined mid-run."""

    def _note_round_closing(self, reason: str, got) -> None:
        """(lock held) The round is about to finalize (``reason`` is
        'complete' | 'quorum' | 'deadline'; ``got`` the closing indices)."""

    # -- timers --------------------------------------------------------------
    def _start_phase_timer(self, attr: str, callback,
                           delay: Optional[float] = None) -> None:
        """(lock held) Arm the daemon timer at ``attr``, generation-tagged.
        ``delay`` defaults to ``round_timeout_s``; the async flush deadline
        passes its own (both are *relative* delays — no wall-clock math)."""
        old = getattr(self, attr, None)
        if old is not None:
            old.cancel()
        t = threading.Timer(
            self.round_timeout_s if delay is None else float(delay),
            callback, args=(self._gen,))
        t.daemon = True
        t.start()
        setattr(self, attr, t)

    def _arm_round_timer(self) -> None:
        if self.round_timeout_s <= 0 or self._finished:
            return
        self._start_phase_timer("_round_timer", self._on_round_timeout)

    def _cancel_round_timer(self) -> None:
        if self._round_timer is not None:
            self._round_timer.cancel()
            self._round_timer = None

    def _on_round_timeout(self, gen: int) -> None:
        with self._round_lock:
            if self._finished or gen != self._gen:
                return  # stale callback: its phase already closed
            got = self.aggregator.received_indices()
            if len(got) < max(1, self.round_timeout_min_clients):
                logger.warning(
                    "round %d timeout with %d/%d uploads (< min %d): "
                    "re-arming the timer and waiting for more uploads",
                    self.args.round_idx, len(got),
                    len(self.client_id_list_in_this_round),
                    self.round_timeout_min_clients,
                )
                self._arm_round_timer()
                return
            logger.warning(
                "round %d timeout: closing with %d/%d clients (stragglers dropped)",
                self.args.round_idx, len(got), len(self.client_id_list_in_this_round),
            )
            self._had_timeout_close = True  # stale arrivals now possible
            self._note_round_closing("deadline", got)
            self._finalize_safely(self.aggregator.consume_received(got))

    # -- round close ----------------------------------------------------------
    def _finalize_safely(self, indices: Optional[List[int]]) -> None:
        """(lock held) Finalize with the shared error policy: with tolerance
        on, a finalize failure shuts the run down cleanly (flags are already
        consumed, no timer may be armed — an escaped exception would wedge
        the run this machinery exists to prevent); with the knob off it
        propagates loudly, as the reference semantics would."""
        if self.round_timeout_s <= 0:
            self._finalize_round(indices)
            return
        try:
            self._finalize_round(indices)
        except Exception:
            logger.exception("round finalize failed; shutting down")
            self._finished = True
            self.send_finish_msg()
            self.finish()

    # -- handshake -------------------------------------------------------------
    def _handshake_check(self) -> None:
        """(lock held) Call from the status handler after recording ONLINE:
        starts round 0 when everyone is up, else bounds the wait."""
        if self.is_initialized:
            return
        if all(self.client_online_status.get(cid, False)
               for cid in range(1, self.client_num + 1)):
            self._start_round0()
        elif self.round_timeout_s > 0 and self._handshake_timer is None:
            self._start_phase_timer("_handshake_timer", self._on_handshake_timeout)

    def _start_round0(self) -> None:
        self.is_initialized = True
        self._gen += 1  # the handshake phase closes; its timers go stale
        self.send_init_msg()

    def _on_handshake_timeout(self, gen: int) -> None:
        with self._round_lock:
            if self.is_initialized or self._finished or gen != self._gen:
                return
            online = sum(self.client_online_status.values())
            if online < max(1, self.round_timeout_min_clients):
                logger.warning(
                    "handshake timeout with %d/%d online (< min %d): "
                    "re-arming the timer and waiting for more clients",
                    online, self.client_num, self.round_timeout_min_clients,
                )
                self._start_phase_timer("_handshake_timer", self._on_handshake_timeout)
                return
            logger.warning(
                "handshake timeout: starting round 0 with %d/%d clients online "
                "(the round timer covers their missing uploads)",
                online, self.client_num,
            )
            self._start_round0()
