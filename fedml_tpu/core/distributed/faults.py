"""Deterministic, seedable transport fault injection.

The reference framework has no fault story at all: FedML's server blocks a
round forever on a dead client and its MQTT/gRPC clients have no reconnect
path (SURVEY.md §5).  This module makes transport faults a *tested,
first-class input* — the way Parrot treats client heterogeneity as a
scheduling input (arXiv:2303.01778) and Prime CCL treats link failure as a
normal collective event to retry around (arXiv:2505.14065).

One seam, every backend: :class:`FaultyCommManager` wraps any
:class:`~.communication.base_com_manager.BaseCommunicationManager`
(LOOPBACK / TCP / GRPC / MQTT_S3) and consults a :class:`FaultPlan` on each
send and each delivery.  The node runtime
(:mod:`~fedml_tpu.core.distributed.comm_manager`) installs the wrapper when
``args.fault_plan`` is set, so the four transports are exercised by the
*same* scripted plan — chaos runs differ from clean runs only in config.

Fault-plan schema (dict / YAML ``fault_args`` section)::

    fault_plan:
      seed: 0                      # seeds per-rule probability draws
      rules:
        - kind: drop               # drop|delay|duplicate|reset|partition|
                                   #   server_kill|mesh_shrink|mesh_grow|
                                   #   device_loss|mid_message_disconnect|
                                   #   truncated_frame
          direction: send          # send (default) or recv
          sender: 1                # int or list; omit = any
          receiver: 0              # int or list; omit = any
          msg_type: 3              # compared as str; int or list; omit = any
          round: 1                 # int or [lo, hi]; omit = any (untagged
                                   #   messages only match when omitted)
          after: 0                 # skip the first N scope-matching messages
          times: 1                 # then affect the next N (null = forever;
                                   #   partition defaults to forever)
          p: 1.0                   # probability, seeded & per-rule
          delay_s: 0.05            # kind=delay: deferral; kind=
                                   #   mid_message_disconnect: dead-link
                                   #   window length
          keep: 2                  # mesh_shrink/mesh_grow only: device count
                                   #   to keep (shrink defaults to half,
                                   #   grow to full visibility)
          lose: 1                  # device_loss only: devices lost

Kinds:

* ``drop`` — the message silently vanishes (in-flight loss).
* ``delay`` — delivery is deferred ``delay_s`` on a timer thread (messages
  may reorder, exactly like a congested network path).
* ``duplicate`` — the message goes through twice (the receive-side dedup
  must make this invisible).
* ``reset`` — a send raises :class:`ConnectionError` (peer RST); on the
  recv direction it degrades to a drop (the frame died with the socket).
* ``partition`` — a standing one-way ``drop`` (A can talk to B while B's
  frames to A vanish) — scope it with sender/receiver/round.
* ``server_kill`` — hard-crashes this node: the triggering message dies
  undelivered, the inner receive loop is stopped (the blocking ``run()``
  returns), and every later send/delivery through the seam is silently
  dropped — the process is "dead" until a supervisor builds a fresh
  incarnation.  Scope it ``direction: recv, receiver: <server rank>`` to
  kill the server at an exact point mid-round (e.g. between two uploads);
  ``kill_event`` lets a test harness observe the crash.
* ``mid_message_disconnect`` — the chunked-upload link cut: the triggering
  frame dies AND the whole link goes dark for ``delay_s`` seconds in both
  directions (every frame either way is dropped, like a modem losing
  carrier mid-stream).  Scope it at chunk ``after: K`` to cut an upload at
  exactly K chunks of progress; once the window passes, the sender's
  retransmitter resumes the stream from its last acked chunk — the
  resumability this kind exists to prove.  Flight-recorder dump trigger.
* ``truncated_frame`` — a torn final frame: a *copy* of the triggering
  chunk message with its payload slice cut in half (stale crc) is
  delivered instead of the original, so the receiver's integrity check
  must reject it, withhold the ack, and take the sender's intact
  retransmit.  Non-chunk messages pass unchanged (nothing to tear).
  Flight-recorder dump trigger.
* ``mesh_shrink`` / ``mesh_grow`` / ``device_loss`` — *topology* faults:
  the triggering message is forwarded unchanged, but the deterministic
  device-visibility shim (:func:`fedml_tpu.parallel.mesh.set_visible_devices`)
  is mutated — ``mesh_shrink`` keeps the first ``keep`` live devices
  (default half), ``device_loss`` removes ``lose`` (default 1) from the
  tail, ``mesh_grow`` restores visibility up to ``keep`` (default all).
  The server observes the change at its next round boundary
  (``maybe_remesh``) or when a restarted incarnation rebuilds its mesh;
  ``device_loss`` also triggers a flight-recorder dump.

Determinism: rules match by *occurrence count within their scope*
(``after``/``times``), not wall-clock, so the same plan injects the same
faults on every backend and every run; ``p`` draws come from
``random.Random(f"{seed}:{rank}:{rule_index}")`` so even probabilistic
plans replay exactly.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .. import obs
from .communication.base_com_manager import BaseCommunicationManager, Observer
from .communication.message import Message

logger = logging.getLogger(__name__)

FAULT_KINDS = ("drop", "delay", "duplicate", "reset", "partition",
               "server_kill", "mesh_shrink", "mesh_grow", "device_loss",
               "mid_message_disconnect", "truncated_frame")

#: topology fault kinds: they mutate device visibility, never the message
_TOPOLOGY_KINDS = ("mesh_shrink", "mesh_grow", "device_loss")

# local pseudo-messages a backend synthesizes for itself are never faulted
_EXEMPT_TYPES = ("connection_ready",)


class CommStats:
    """Thread-safe counter bag shared by the reliability layer and the fault
    injector; ``snapshot()`` is what the mlops ``comm_stats`` record carries.

    Every increment is additionally mirrored into the process-global
    :class:`~fedml_tpu.core.obs.MetricsRegistry` as ``comm.<key>`` (labeled
    by ``node`` when the owner identifies itself) — the per-instance
    snapshot keeps the legacy ``comm_stats`` topic byte-compatible while
    the registry makes the same counters joinable across subsystems."""

    _KEYS = (
        "messages_sent", "retries", "retransmits", "delivery_failures",
        "acks_sent", "acks_received", "dup_dropped",
        "faults_dropped", "faults_delayed", "faults_duplicated",
        "faults_reset", "faults_killed", "faults_topology",
        "faults_disconnects", "faults_truncated",
        "reconnects", "rejoins",
        # server crash-recovery counters (core/checkpoint.ServerRecoveryMixin)
        "server_restores", "journal_replays", "epoch_bumps",
        "dup_uploads_discarded",
        # chunked resumable uploads (core/distributed/chunking.py)
        "chunks_sent", "chunks_received", "chunks_dup", "chunks_crc_bad",
        "chunk_bytes_resent", "resume_bytes_saved",
        "streams_completed", "streams_shed", "streams_restarted",
    )

    def __init__(self, node: Optional[int] = None):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {k: 0 for k in self._KEYS}
        self._labels = None if node is None else {"node": int(node)}

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
        obs.counter_inc(f"comm.{key}", n, self._labels)

    def get(self, key: str) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


def _as_set(v: Any) -> Optional[set]:
    if v is None:
        return None
    if isinstance(v, (list, tuple, set)):
        return {str(x) for x in v}
    return {str(v)}


class FaultRule:
    def __init__(self, spec: Dict[str, Any], index: int):
        kind = str(spec.get("kind", "")).lower()
        if kind not in FAULT_KINDS:
            raise ValueError(f"fault rule {index}: unknown kind {kind!r} "
                             f"(one of {FAULT_KINDS})")
        self.kind = kind
        self.index = index
        self.direction = str(spec.get("direction", "send")).lower()
        if self.direction not in ("send", "recv"):
            raise ValueError(f"fault rule {index}: direction must be "
                             f"send|recv, got {self.direction!r}")
        self.sender = _as_set(spec.get("sender"))
        self.receiver = _as_set(spec.get("receiver"))
        self.msg_type = _as_set(spec.get("msg_type"))
        rnd = spec.get("round")
        if rnd is None:
            self.round: Optional[Sequence[int]] = None
        elif isinstance(rnd, (list, tuple)):
            self.round = (int(rnd[0]), int(rnd[1]))
        else:
            self.round = (int(rnd), int(rnd))
        self.after = int(spec.get("after", 0))
        times = spec.get("times", None if kind == "partition" else 1)
        self.times = None if times is None else int(times)
        self.p = float(spec.get("p", 1.0))
        self.delay_s = float(spec.get("delay_s", 0.05))
        keep = spec.get("keep")
        self.keep = None if keep is None else int(keep)
        self.lose = int(spec.get("lose", 1))

    def matches_scope(self, direction: str, msg: Message) -> bool:
        if direction != self.direction:
            return False
        if self.sender is not None and str(msg.get_sender_id()) not in self.sender:
            return False
        if self.receiver is not None and str(msg.get_receiver_id()) not in self.receiver:
            return False
        if self.msg_type is not None and msg.get_type() not in self.msg_type:
            return False
        if self.round is not None:
            tag = msg.get("round_idx")
            if tag is None:
                return False
            lo, hi = self.round
            if not (lo <= int(tag) <= hi):
                return False
        return True


class FaultPlan:
    """Parsed plan; hand each endpoint its own :class:`FaultInjector` (fresh
    occurrence counters + seeded RNG) via :meth:`injector`."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = rules
        self.seed = int(seed)

    @classmethod
    def from_dict(cls, spec: Dict[str, Any]) -> "FaultPlan":
        if isinstance(spec, FaultPlan):
            return spec
        rules = [FaultRule(r, i) for i, r in enumerate(spec.get("rules", []))]
        return cls(rules, seed=int(spec.get("seed", 0)))

    def injector(self, rank: int) -> "FaultInjector":
        return FaultInjector(self, int(rank))


class FaultInjector:
    def __init__(self, plan: FaultPlan, rank: int):
        self.plan = plan
        self.rank = rank
        self._lock = threading.Lock()
        self._match_counts: Dict[int, int] = {r.index: 0 for r in plan.rules}
        self._rngs: Dict[int, random.Random] = {
            r.index: random.Random(f"{plan.seed}:{rank}:{r.index}") for r in plan.rules
        }

    def decide(self, direction: str, msg: Message) -> Optional[FaultRule]:
        """First rule whose scope + occurrence window + probability hit."""
        if msg.get_type() in _EXEMPT_TYPES:
            return None
        for rule in self.plan.rules:
            if not rule.matches_scope(direction, msg):
                continue
            with self._lock:
                n = self._match_counts[rule.index]
                self._match_counts[rule.index] = n + 1
                if n < rule.after:
                    continue
                if rule.times is not None and n >= rule.after + rule.times:
                    continue
                if rule.p < 1.0 and self._rngs[rule.index].random() >= rule.p:
                    continue
            return rule
        return None


class FaultyCommManager(BaseCommunicationManager, Observer):
    """The injection seam: sits between the node runtime and any backend.

    Sends pass :meth:`send_message`; deliveries pass :meth:`receive_message`
    (this wrapper registers itself as the backend's sole observer and
    re-notifies its own observers), so one plan covers both directions of
    all four transports.
    """

    def __init__(self, inner: BaseCommunicationManager, injector: FaultInjector,
                 stats: Optional[CommStats] = None):
        self._inner = inner
        self._injector = injector
        self._stats = stats if stats is not None else CommStats()
        self._observers: List[Observer] = []
        self._killed = False
        # mid_message_disconnect: monotonic deadline while the link is dark
        # in BOTH directions (0.0 = link up); written under the injector's
        # occurrence lock ordering (one triggering frame), read racily —
        # worst case a frame slips through at the window edge, which a real
        # carrier loss also permits
        self._dead_until = 0.0
        # set when a server_kill rule fires; test supervisors wait on this to
        # distinguish "crashed mid-round" from "finished the run"
        self.kill_event = threading.Event()
        inner.add_observer(self)

    # delegate everything the contract doesn't cover (broadcast,
    # broadcast_status, reconnect counters, ...) to the wrapped backend
    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    # -- send path -----------------------------------------------------------
    def _link_dark(self, msg: Message) -> bool:
        if self._dead_until <= 0.0 or msg.get_type() in _EXEMPT_TYPES:
            return False
        if time.monotonic() < self._dead_until:
            self._stats.inc("faults_dropped")
            return True
        self._dead_until = 0.0  # window passed: carrier back
        return False

    def send_message(self, msg: Message) -> None:
        if self._killed:
            return  # dead process: outbound frames go nowhere
        if self._link_dark(msg):
            return
        rule = self._injector.decide("send", msg)
        if rule is None:
            self._inner.send_message(msg)
            return
        self._apply(rule, msg, self._inner.send_message, "send")

    # -- receive path --------------------------------------------------------
    def receive_message(self, msg_type: str, msg: Message) -> None:
        if self._killed:
            return  # dead process: inbound frames are never observed
        if self._link_dark(msg):
            return
        rule = self._injector.decide("recv", msg)
        if rule is None:
            self._notify(msg)
            return
        self._apply(rule, msg, self._notify, "recv")

    def _fault_event(self, name: str, msg: Message, **attrs: Any) -> None:
        """Annotate the injected fault onto the message's span (or the round
        root when the message is traced but unstamped) — events are
        telemetry, they never alter the fault's behavior."""
        try:
            rnd = msg.get("round_idx")
            obs.span_event(
                name, obs.extract(msg),
                round_idx=int(rnd) if rnd is not None else None,
                node=self._injector.rank, msg_type=msg.get_type(),
                sender=msg.get_sender_id(), receiver=msg.get_receiver_id(),
                **attrs)
        except Exception:  # pragma: no cover - observability is non-fatal
            pass

    def _topology_fault(self, kind: str, rule: FaultRule, msg: Message) -> None:
        """Mutate the deterministic device-visibility shim: break hardware,
        not traffic.  The server notices at its next round boundary
        (``maybe_remesh``) or when a restarted incarnation rebuilds its
        round mesh over the surviving devices."""
        import jax

        from ...parallel.mesh import set_visible_devices, visible_devices
        every = list(jax.devices())
        cur = visible_devices(every)
        if kind == "mesh_grow":
            target = every if rule.keep is None else every[:max(1, rule.keep)]
        elif kind == "mesh_shrink":
            keep = rule.keep if rule.keep else max(1, len(cur) // 2)
            target = cur[:max(1, keep)]
        else:  # device_loss
            target = cur[:max(1, len(cur) - max(1, rule.lose))]
        lost = max(0, len(cur) - len(target))
        set_visible_devices([d.id for d in target])
        self._stats.inc("faults_topology")
        if lost:
            obs.counter_inc("mesh.devices_lost_total", lost)
        # "device_loss" is a flight-recorder dump trigger (obs.flight)
        self._fault_event(kind, msg, rule=rule.index,
                          devices_before=len(cur), devices_after=len(target))
        logger.warning(
            "FAULT %s: device visibility %d -> %d (rule %d); triggering "
            "message %s %s->%s forwarded unchanged", kind, len(cur),
            len(target), rule.index, msg.get_type(), msg.get_sender_id(),
            msg.get_receiver_id())

    def _apply(self, rule: FaultRule, msg: Message, forward, direction: str) -> None:
        kind = rule.kind
        if kind in _TOPOLOGY_KINDS:
            self._topology_fault(kind, rule, msg)
            forward(msg)
            return
        if kind == "server_kill":
            self._stats.inc("faults_killed")
            self._fault_event("server_kill", msg, rule=rule.index)
            logger.warning(
                "FAULT server_kill: node dies on %s %s->%s (rule %d); the "
                "triggering message is lost with the process",
                msg.get_type(), msg.get_sender_id(), msg.get_receiver_id(),
                rule.index)
            self._killed = True
            self.kill_event.set()
            try:  # unblock the node's receive loop so run() returns
                self._inner.stop_receive_message()
            except Exception:
                logger.exception("server_kill: inner stop raised")
            return
        if kind == "mid_message_disconnect":
            self._stats.inc("faults_disconnects")
            self._stats.inc("faults_dropped")
            self._dead_until = time.monotonic() + rule.delay_s
            self._fault_event("mid_message_disconnect", msg, rule=rule.index,
                              dark_s=rule.delay_s)
            logger.warning(
                "FAULT mid_message_disconnect: link dark %.3fs from %s "
                "%s->%s (rule %d); triggering frame lost", rule.delay_s,
                msg.get_type(), msg.get_sender_id(), msg.get_receiver_id(),
                rule.index)
            return
        if kind == "truncated_frame":
            from . import chunking

            torn = chunking.truncate_for_fault(msg)
            self._stats.inc("faults_truncated")
            self._fault_event("truncated_frame", msg, rule=rule.index,
                              torn=torn is not None)
            logger.warning(
                "FAULT truncated_frame: %s %s->%s (rule %d)%s",
                msg.get_type(), msg.get_sender_id(), msg.get_receiver_id(),
                rule.index, "" if torn is not None else
                " — not a chunk, forwarded unchanged")
            forward(torn if torn is not None else msg)
            return
        if kind in ("drop", "partition") or (kind == "reset" and direction == "recv"):
            self._stats.inc("faults_dropped")
            self._fault_event("drop", msg, rule=rule.index, fault_kind=kind)
            logger.info("FAULT %s: dropping %s %s->%s", kind, msg.get_type(),
                        msg.get_sender_id(), msg.get_receiver_id())
            return
        if kind == "reset":
            self._stats.inc("faults_reset")
            self._fault_event("reset", msg, rule=rule.index)
            logger.info("FAULT reset: %s %s->%s", msg.get_type(),
                        msg.get_sender_id(), msg.get_receiver_id())
            raise ConnectionError(
                f"fault-injected connection reset (rule {rule.index})"
            )
        if kind == "duplicate":
            self._stats.inc("faults_duplicated")
            self._fault_event("dup", msg, rule=rule.index, side="injected")
            logger.info("FAULT duplicate: %s %s->%s", msg.get_type(),
                        msg.get_sender_id(), msg.get_receiver_id())
            forward(msg)
            forward(msg)
            return
        if kind == "delay":
            self._stats.inc("faults_delayed")
            self._fault_event("delay", msg, rule=rule.index, delay_s=rule.delay_s)
            logger.info("FAULT delay %.3fs: %s %s->%s", rule.delay_s,
                        msg.get_type(), msg.get_sender_id(), msg.get_receiver_id())

            def _later():
                try:
                    forward(msg)
                except Exception:
                    logger.exception("delayed %s forward failed", direction)

            t = threading.Timer(rule.delay_s, _later)
            t.daemon = True
            t.start()
            return
        raise AssertionError(f"unhandled fault kind {kind!r}")  # pragma: no cover

    # -- BaseCommunicationManager --------------------------------------------
    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self._inner.stop_receive_message()

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg)
            except Exception:
                logger.exception("fault seam: observer for %r raised", msg.get_type())
