"""The flow runtime: a linear task chain executed across message-passing nodes.

Behavioral parity with reference ``core/distributed/flow/fedml_flow.py``:

* ``add_flow(name, ExecutorCls.task)`` appends a task; the *class that defined
  the method* decides which nodes run it (every node holds one live executor).
* ``build()`` freezes the chain and computes each entry's successor.
* ``run()`` starts with a neighbor liveness handshake (check/report status,
  reference ``fedml_flow.py:41-52``); once all neighbors are online, the node
  owning flow 0 starts the chain.
* A task returns ``Params`` to advance (shipped to the next task's nodes) or
  ``None`` to hold (e.g. a server aggregation task waiting for more clients).
* After the last entry the flow broadcasts FINISH and all nodes shut down.

Implementation is new: built on this repo's ``FedMLCommManager`` contract, so
it runs over loopback (unit tests), gRPC, or the MQTT-style backend unchanged.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, List, Optional

from ...alg_frame.params import Params
from ..comm_manager import FedMLCommManager
from ..communication.message import Message

logger = logging.getLogger(__name__)


@dataclass
class _FlowEntry:
    idx: int
    name: str
    task: Callable
    owner_cls: str  # class name that defined the task method
    tag: str = "FLOW_TAG_ONCE"


class FedMLAlgorithmFlow(FedMLCommManager):
    ONCE = "FLOW_TAG_ONCE"
    FINISH = "FLOW_TAG_FINISH"
    # Explicit hold sentinel: return this from a task to wait for more inputs.
    # Unlike a bare None it also holds FINISH-tagged tasks (straggler-waiting
    # terminal aggregators).
    HOLD = object()

    MSG_TYPE_FLOW = "flow_execute"
    MSG_TYPE_FINISH = "flow_finish"
    MSG_TYPE_CHECK_STATUS = "flow_check_node_status"
    MSG_TYPE_REPORT_STATUS = "flow_report_node_status"

    ARG_FLOW_IDX = "flow_idx"
    ARG_FLOW_PARAMS = "flow_params"

    def __init__(self, args, executor):
        self.executor = executor
        self.flows: List[_FlowEntry] = []
        self._built = False
        self._ready = threading.Event()
        self._online_neighbors: set = set()
        self._finished = threading.Event()
        rank = executor.get_id()
        size = len(executor.get_neighbor_id_list()) + 1
        backend = str(getattr(args, "backend", "LOOPBACK"))
        super().__init__(args, comm=None, rank=rank, size=size, backend=backend)

    # -- DSL ----------------------------------------------------------------
    def add_flow(self, flow_name: str, executor_task: Callable, flow_tag: str = ONCE) -> None:
        assert not self._built, "add_flow after build()"
        owner = _defining_class_name(executor_task)
        self.flows.append(
            _FlowEntry(len(self.flows), str(flow_name), executor_task, owner, str(flow_tag))
        )

    def build(self) -> None:
        assert self.flows, "empty flow"
        self._built = True
        logger.info(
            "flow built: %s", [(f.idx, f.name, f.owner_cls) for f in self.flows]
        )

    # -- comm wiring ---------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._handle_connection_ready)
        self.register_message_receive_handler(self.MSG_TYPE_CHECK_STATUS, self._handle_check_status)
        self.register_message_receive_handler(self.MSG_TYPE_REPORT_STATUS, self._handle_report_status)
        self.register_message_receive_handler(self.MSG_TYPE_FLOW, self._handle_flow_message)
        self.register_message_receive_handler(self.MSG_TYPE_FINISH, self._handle_finish)

    def _handle_connection_ready(self, _msg: Message) -> None:
        for nid in self.executor.get_neighbor_id_list():
            msg = Message(self.MSG_TYPE_CHECK_STATUS, self.rank, nid)
            self.send_message(msg)

    def _handle_check_status(self, msg: Message) -> None:
        reply = Message(self.MSG_TYPE_REPORT_STATUS, self.rank, msg.get_sender_id())
        self.send_message(reply)
        # a neighbor probing us proves it is alive too
        self._mark_online(msg.get_sender_id())

    def _handle_report_status(self, msg: Message) -> None:
        self._mark_online(msg.get_sender_id())

    def _mark_online(self, neighbor_id: int) -> None:
        self._online_neighbors.add(int(neighbor_id))
        if not self._ready.is_set() and self._online_neighbors >= set(
            self.executor.get_neighbor_id_list()
        ):
            self._ready.set()
            logger.info("rank %s: all neighbors online", self.rank)
            self._on_ready_to_run_flow()

    def _on_ready_to_run_flow(self) -> None:
        if self._owns(self.flows[0]):
            self._execute_chain(0, Params())

    # -- execution -----------------------------------------------------------
    def _owns(self, entry: _FlowEntry) -> bool:
        return any(c.__name__ == entry.owner_cls for c in type(self.executor).__mro__)

    def _handle_flow_message(self, msg: Message) -> None:
        idx = int(msg.get(self.ARG_FLOW_IDX))
        params = Params(**(msg.get(self.ARG_FLOW_PARAMS) or {}))
        entry = self.flows[idx]
        if not self._owns(entry):
            logger.debug("rank %s: ignoring flow %s for %s", self.rank, entry.name, entry.owner_cls)
            return
        self._execute_chain(idx, params)

    def _execute_chain(self, idx: int, params: Params) -> None:
        while True:
            entry = self.flows[idx]
            logger.debug("rank %s executes flow[%d] %s", self.rank, idx, entry.name)
            self.executor.set_params(params)
            result = entry.task(self.executor)
            # Hold contract: HOLD always holds (works on FINISH-tagged tasks —
            # e.g. a terminal aggregator waiting on stragglers); a bare None
            # holds only on untagged tasks, so a FINISH-tagged task with no
            # return value (the common "final_eval" idiom) still finishes.
            hold = result is self.HOLD or (result is None and entry.tag != self.FINISH)
            if hold:
                if idx + 1 >= len(self.flows):
                    logger.debug(
                        "rank %s: final flow %r holding; it finishes once it returns a result",
                        self.rank, entry.name,
                    )
                return
            if entry.tag == self.FINISH:
                self._broadcast_finish()
                return
            nxt = idx + 1
            if nxt >= len(self.flows):
                self._broadcast_finish()
                return
            params = result if isinstance(result, Params) else Params()
            if self._owns(self.flows[nxt]):
                idx = nxt  # local pass (reference _pass_message_locally)
                continue
            payload = params.to_dict()
            for nid in self.executor.get_neighbor_id_list():
                msg = Message(self.MSG_TYPE_FLOW, self.rank, nid)
                msg.add_params(self.ARG_FLOW_IDX, nxt)
                msg.add_params(self.ARG_FLOW_PARAMS, payload)
                self.send_message(msg)
            return

    # -- shutdown ------------------------------------------------------------
    def _broadcast_finish(self) -> None:
        for nid in self.executor.get_neighbor_id_list():
            self.send_message(Message(self.MSG_TYPE_FINISH, self.rank, nid))
        self._shutdown()

    def _handle_finish(self, _msg: Message) -> None:
        self._shutdown()

    def _shutdown(self) -> None:
        if not self._finished.is_set():
            self._finished.set()
            logger.info("rank %s: flow finished", self.rank)
            self.finish()

    def wait_finished(self, timeout: Optional[float] = None) -> bool:
        return self._finished.wait(timeout)


def _defining_class_name(func: Callable) -> str:
    qual = getattr(func, "__qualname__", "")
    if "." in qual:
        owner = qual.rsplit(".", 2)[-2]
        if not owner.startswith("<"):  # reject <locals>/<lambda>
            return owner
    raise ValueError(
        f"flow task {func!r} must be an executor-class method (Cls.method)"
    )
