"""Flow node: owns an id, a neighbor list, and the current task params.

Parity with reference ``core/distributed/flow/fedml_executor.py``."""

from __future__ import annotations

from typing import List, Optional

from ...alg_frame.params import Params


class FedMLExecutor:
    def __init__(self, id: int, neighbor_id_list: List[int]):
        self.id = int(id)
        self.neighbor_id_list = [int(i) for i in neighbor_id_list]
        self._params: Optional[Params] = None

    def get_id(self) -> int:
        return self.id

    def set_id(self, id: int) -> None:
        self.id = int(id)

    def get_neighbor_id_list(self) -> List[int]:
        return self.neighbor_id_list

    def set_neighbor_id_list(self, ids: List[int]) -> None:
        self.neighbor_id_list = [int(i) for i in ids]

    def get_params(self) -> Optional[Params]:
        return self._params

    def set_params(self, params: Optional[Params]) -> None:
        self._params = params
