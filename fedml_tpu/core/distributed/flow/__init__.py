"""FedMLAlgorithmFlow DSL: declarative message-driven algorithm graphs.

Parity with reference ``core/distributed/flow/`` (``fedml_flow.py:20``,
``fedml_executor.py``): users subclass :class:`FedMLExecutor`, register task
methods as a linear flow with :meth:`FedMLAlgorithmFlow.add_flow`, and the
runtime executes the chain across nodes, shipping each task's returned
``Params`` to the node(s) owning the next task.
"""

from .fedml_executor import FedMLExecutor
from .fedml_flow import FedMLAlgorithmFlow

__all__ = ["FedMLExecutor", "FedMLAlgorithmFlow"]
