"""Node runtime: handler registry + backend factory.

Parity with reference ``core/distributed/fedml_comm_manager.py:10-135``
(``FedMLCommManager``): every server/client manager subclasses this, registers
per-message-type handlers, and calls :meth:`run` to enter the transport's
receive loop.  The backend factory dispatches on ``args.backend``; the TPU
rebuild's backends are LOOPBACK (in-process), GRPC (DCN message plane) and an
MQTT+S3 emulation (file-blob data plane) — NCCL/MPI collective traffic has no
backend here because on TPU it is in-program XLA collectives
(see fedml_tpu/simulation/xla/).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from ...constants import (
    FEDML_BACKEND_GRPC,
    FEDML_BACKEND_LOOPBACK,
    FEDML_BACKEND_MQTT_S3,
    FEDML_BACKEND_MQTT_S3_MNN,
    FEDML_BACKEND_TRPC,
)
from .communication.base_com_manager import BaseCommunicationManager, Observer
from .communication.message import Message

logger = logging.getLogger(__name__)


class FedMLCommManager(Observer):
    def __init__(self, args, comm=None, rank: int = 0, size: int = 0, backend: str = "LOOPBACK"):
        self.args = args
        self.size = int(size)
        self.rank = int(rank)
        self.backend = backend
        self.comm = comm
        self.com_manager: Optional[BaseCommunicationManager] = None
        self.message_handler_dict: Dict[str, Callable[[Message], None]] = {}
        self._init_manager()

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        """Enter the receive loop (blocks; reference ``fedml_comm_manager.py:24``)."""
        self.register_message_receive_handlers()
        assert self.com_manager is not None
        self.com_manager.handle_receive_message()
        logger.info("comm manager %s/%s done", self.rank, self.size)

    def run_async(self) -> threading.Thread:
        """Native addition: run the receive loop on a daemon thread so many
        node runtimes can cohabit one test process."""
        t = threading.Thread(target=self.run, daemon=True, name=f"comm-rank{self.rank}")
        t.start()
        return t

    def finish(self) -> None:
        """Stop the transport (reference ``fedml_comm_manager.py:61-76``)."""
        if self.com_manager is not None:
            self.com_manager.stop_receive_message()

    # -- messaging ----------------------------------------------------------
    def get_sender_id(self) -> int:
        return self.rank

    def send_message(self, message: Message) -> None:
        assert self.com_manager is not None
        self.com_manager.send_message(message)

    def register_message_receive_handler(
        self, msg_type: str, handler_callback_func: Callable[[Message], None]
    ) -> None:
        self.message_handler_dict[str(msg_type)] = handler_callback_func

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their per-round handlers here."""

    # Observer
    def receive_message(self, msg_type: str, msg_params: Message) -> None:
        handler = self.message_handler_dict.get(str(msg_type))
        if handler is None:
            logger.debug("rank %s: no handler for msg_type=%s", self.rank, msg_type)
            return
        handler(msg_params)

    # -- backend factory (reference ``fedml_comm_manager.py:78-134``) -------
    def _init_manager(self) -> None:
        backend = (self.backend or FEDML_BACKEND_LOOPBACK).upper()
        run_id = str(getattr(self.args, "run_id", "0"))
        if backend == FEDML_BACKEND_LOOPBACK:
            from .communication.loopback import LoopbackCommManager

            self.com_manager = LoopbackCommManager(channel=run_id, rank=self.rank, size=self.size)
        elif backend == FEDML_BACKEND_GRPC:
            try:
                from .communication.grpc.grpc_comm_manager import GRPCCommManager
            except ImportError as e:
                raise NotImplementedError(
                    "GRPC backend module not available in this build"
                ) from e

            base_port = int(getattr(self.args, "grpc_base_port", 8890))
            ip_config = getattr(self.args, "grpc_ipconfig_path", None)
            self.com_manager = GRPCCommManager(
                host=getattr(self.args, "grpc_host", "127.0.0.1"),
                port=base_port + self.rank,
                ip_config=ip_config,
                client_id=self.rank,
                client_num=self.size,
                base_port=base_port,
            )
        elif backend in (FEDML_BACKEND_MQTT_S3, FEDML_BACKEND_MQTT_S3_MNN):
            try:
                from .communication.mqtt_s3.mqtt_s3_comm_manager import MqttS3CommManager
            except ImportError as e:
                raise NotImplementedError(
                    "MQTT_S3 backend module not available in this build"
                ) from e

            self.com_manager = MqttS3CommManager(
                args=self.args,
                topic=run_id,
                client_rank=self.rank,
                client_num=self.size,
                mnn_mode=(backend == FEDML_BACKEND_MQTT_S3_MNN),
            )
        elif backend == FEDML_BACKEND_TRPC:
            from .communication.tcp.tcp_comm_manager import TCPCommManager

            self.com_manager = TCPCommManager(
                host=getattr(self.args, "trpc_host", "127.0.0.1"),
                base_port=int(getattr(self.args, "trpc_base_port", 9690)),
                rank=self.rank,
                size=self.size,
                ip_table=getattr(self.args, "trpc_ip_table", None),
                bind_host=getattr(self.args, "trpc_bind_host", "0.0.0.0"),
            )
        else:
            raise ValueError(f"unsupported comm backend: {self.backend!r}")
        self.com_manager.add_observer(self)
