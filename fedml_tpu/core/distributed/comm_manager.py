"""Node runtime: handler registry + backend factory + reliability layer.

Parity with reference ``core/distributed/fedml_comm_manager.py:10-135``
(``FedMLCommManager``): every server/client manager subclasses this, registers
per-message-type handlers, and calls :meth:`run` to enter the transport's
receive loop.  The backend factory dispatches on ``args.backend``; the TPU
rebuild's backends are LOOPBACK (in-process), GRPC (DCN message plane) and an
MQTT+S3 emulation (file-blob data plane) — NCCL/MPI collective traffic has no
backend here because on TPU it is in-program XLA collectives
(see fedml_tpu/simulation/xla/).

Beyond-reference: a transport-agnostic **reliability layer** sits between the
application managers and the backend.  Outbound messages are stamped with a
monotonic ``msg_id`` (``rank:nonce:seq``; the nonce is fresh per incarnation
so a rejoined silo never collides with its dead predecessor's ids).  Receivers
dispatch every fresh stamped message and only then ack it (so an ack implies
the handler's durable effects — e.g. the server's update journal — are on
disk), and drop re-deliveries by an LRU dedup window (re-acking them, since
the first ack may have been the lost frame), so retries and duplicate faults
are idempotent end to end.
With ``args.comm_max_retries > 0`` a background retransmitter re-sends
unacked messages with exponential backoff + jitter and synchronous send
errors (connection resets) are retried instead of raised; at the default 0
the legacy synchronous-raise semantics are preserved exactly and no
retransmit thread runs (acks from legacy peers are simply ignored).
Peers that don't stamp ``msg_id`` (the Java/Swift JSON wire) are never acked
or deduped — the layer is wire-compatible in both directions.

When ``args.fault_plan`` is set the backend is wrapped in the
:mod:`~fedml_tpu.core.distributed.faults` injection seam, so chaos runs
differ from clean runs only in config.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import uuid
from collections import OrderedDict
from typing import Callable, Dict, Optional

from ...constants import (
    FEDML_BACKEND_GRPC,
    FEDML_BACKEND_LOOPBACK,
    FEDML_BACKEND_MQTT_S3,
    FEDML_BACKEND_MQTT_S3_MNN,
    FEDML_BACKEND_TRPC,
)
from .. import ingest, obs
from .communication.base_com_manager import BaseCommunicationManager, Observer
from .communication.message import Message
from .faults import CommStats

logger = logging.getLogger(__name__)

# transport-level ack; lives below the application vocabulary (MyMessage) so
# it needs no handler registration and is invisible to the Java/Swift gates
COMM_ACK_TYPE = "comm_ack"

# backend-synthesized local pseudo-messages bypass the reliability layer
_LOCAL_TYPES = ("connection_ready",)


class _Pending:
    __slots__ = ("msg", "attempts", "due")

    def __init__(self, msg: Message, due: float):
        self.msg = msg
        self.attempts = 0
        self.due = due


class _ReliableLink:
    """Per-endpoint stamping + ack + dedup + (optional) retransmission.

    The link never raises into the receive loop: ack sends are best-effort
    (a failed ack just means the peer retransmits) and retransmission gives
    up after ``max_retries`` with a counted ``delivery_failures`` instead of
    an exception on a daemon thread.
    """

    def __init__(self, rank: int, stats: CommStats, *, max_retries: int = 0,
                 backoff_base_s: float = 0.2, backoff_max_s: float = 2.0,
                 jitter: float = 0.25, dedup_window: int = 8192,
                 backoff_seed: Optional[int] = None):
        self.rank = int(rank)
        self.stats = stats
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.dedup_window = int(dedup_window)
        self._nonce = uuid.uuid4().hex[:8]
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._seen_lock = threading.Lock()
        self._cond = threading.Condition()
        self._pending: Dict[str, _Pending] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._send_raw: Optional[Callable[[Message], None]] = None
        # ack listeners (chunked-upload window/resume accounting): called
        # outside the lock with (msg_id, attempts, delivered) for every ack
        # consumed and every retransmit give-up
        self._ack_listeners: list = []  # owned-by: main — bound before run()
        # optional outbound-ack decorator (chunking capability advert):
        # acks are the ONLY reverse traffic on pure fan-in links (leaf ->
        # edge -> root), so they must carry the chunk_ok flag or those
        # links could never negotiate chunking up
        self.ack_decorator: Optional[Callable[[Message], None]] = None  # owned-by: main — bound before run()
        # jitter draws are seeded per (seed, rank): deterministic ACROSS
        # incarnations, so a restarted server's whole cohort doesn't re-draw
        # identical schedules from fresh nonces and synchronize its retry
        # storm; distinct per rank so peers still de-correlate.  With no
        # seed configured the legacy per-(rank, nonce) stream is kept.
        import random

        if backoff_seed is not None:
            self._rng = random.Random(f"{int(backoff_seed)}:{self.rank}")
        else:
            self._rng = random.Random(f"{self.rank}:{self._nonce}")

    # -- wiring --------------------------------------------------------------
    def bind(self, send_raw: Callable[[Message], None]) -> None:
        # owned-by: main — both bound/set before the retransmit thread
        # starts (Thread.start is the happens-before edge); the thread and
        # the ack path only read them afterwards
        self._send_raw = send_raw  # owned-by: main
        if self.max_retries > 0 and self._thread is None:
            self._running = True  # owned-by: main
            self._thread = threading.Thread(
                target=self._retransmit_loop, daemon=True,
                name=f"comm-retx-rank{self.rank}")
            self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._pending.clear()
            self._cond.notify_all()

    def add_ack_listener(
            self, fn: Callable[[str, int, bool], None]) -> None:
        self._ack_listeners.append(fn)

    def _notify_ack(self, msg_id: str, attempts: int, delivered: bool) -> None:
        for fn in self._ack_listeners:
            try:
                fn(msg_id, attempts, delivered)
            except Exception:  # listeners must never poison the link
                logger.exception("rank %s: ack listener failed", self.rank)

    # -- send side -----------------------------------------------------------
    def stamp(self, msg: Message) -> str:
        with self._seq_lock:
            self._seq += 1
            msg_id = f"{self.rank}:{self._nonce}:{self._seq}"
        msg.add_params(Message.MSG_ARG_KEY_MSG_ID, msg_id)
        return msg_id

    def track(self, msg_id: str, msg: Message) -> None:
        if self.max_retries <= 0:
            return
        with self._cond:
            if not self._running:
                return
            self._pending[msg_id] = _Pending(msg, time.monotonic() + self._backoff(0))
            self._cond.notify_all()

    def _backoff(self, attempt: int) -> float:
        base = min(self.backoff_base_s * (2 ** attempt), self.backoff_max_s)
        return base * (1.0 + self.jitter * self._rng.random())

    def _retransmit_loop(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    return
                now = time.monotonic()
                due = [(mid, p) for mid, p in self._pending.items() if p.due <= now]
                if not due:
                    next_due = min((p.due for p in self._pending.values()),
                                   default=now + 1.0)
                    self._cond.wait(timeout=max(0.01, next_due - now))
                    continue
                for mid, p in due:
                    p.attempts += 1
                    if p.attempts > self.max_retries:
                        del self._pending[mid]
                    else:
                        p.due = now + self._backoff(p.attempts)
            for mid, p in due:
                if p.attempts > self.max_retries:
                    self.stats.inc("delivery_failures")
                    logger.warning(
                        "rank %s: giving up on %s (%s) after %d retransmits",
                        self.rank, mid, p.msg.get_type(), self.max_retries)
                    self._notify_ack(mid, p.attempts, False)
                    continue
                self.stats.inc("retransmits")
                logger.info("rank %s: retransmit #%d of %s (%s)",
                            self.rank, p.attempts, mid, p.msg.get_type())
                # each attempt is its own child span under the context the
                # original send carried, so stragglers caused by lossy links
                # are visible in the round tree (NULL_SPAN when untraced)
                tctx = obs.extract(p.msg)
                retx = obs.unique_span(
                    "retransmit", tctx, node=self.rank, attempt=p.attempts,
                    msg_id=mid, msg_type=p.msg.get_type(),
                ) if tctx is not None else obs.NULL_SPAN
                try:
                    assert self._send_raw is not None
                    self._send_raw(p.msg)
                    retx.end()
                except Exception as e:
                    retx.end(error=str(e))
                    logger.info("rank %s: retransmit of %s failed (%s); "
                                "will retry", self.rank, mid, e)

    # -- receive side --------------------------------------------------------
    def on_receive(self, msg: Message,
                   dispatch: Optional[Callable[[Message], None]] = None,
                   pipeline: Optional["_IngestPipeline"] = None) -> bool:
        """Return True iff ``msg`` is (or should be) dispatched to handlers.

        Consumes acks, acks every stamped message (dup or not — the ack may
        have been the frame that was lost), and drops re-deliveries.  When
        ``dispatch`` is given, a fresh message is dispatched *before* its ack
        goes out, so receiver-side durable effects (the server's update
        journal) reach disk before the sender is released from retransmit
        duty — ack implies processed.  A dispatch that raises withholds the
        ack and forgets the msg_id, so the sender's retransmit retries the
        delivery instead of losing it.

        With ``pipeline`` set (the server's staged receive path), this
        method becomes the io stage: ack consumption, dedup and re-acking
        of duplicates stay on the transport thread, but fresh messages are
        handed to the pipeline's bounded queue — the worker dispatches and
        the ack is released once the handler's journal batch is durable,
        so the contract is unchanged, only off-thread.
        """
        if msg.get_type() == COMM_ACK_TYPE:
            acked = msg.get(Message.MSG_ARG_KEY_MSG_ID)
            self.stats.inc("acks_received")
            if acked is not None:
                with self._cond:
                    popped = self._pending.pop(str(acked), None)
                    self._cond.notify_all()
                self._notify_ack(str(acked),
                                 popped.attempts if popped is not None else 0,
                                 True)
            return False
        if msg.get_type() in _LOCAL_TYPES or msg.get(Message.MSG_ARG_KEY_MSG_ID) is None:
            # local pseudo-message or legacy peer: no dedup, no ack — still
            # staged through the pipeline so handler FIFO order is preserved
            if pipeline is not None:
                pipeline.submit(msg, needs_ack=False)
            elif dispatch is not None:
                dispatch(msg)
            return True
        msg_id = msg.get(Message.MSG_ARG_KEY_MSG_ID)
        with self._seen_lock:
            dup = msg_id in self._seen
            if not dup:
                self._seen[msg_id] = None
                while len(self._seen) > self.dedup_window:
                    self._seen.popitem(last=False)
        if dup:
            self.stats.inc("dup_dropped")
            obs.span_event("dup", obs.extract(msg), node=self.rank,
                           side="dedup", msg_id=msg_id,
                           msg_type=msg.get_type())
            logger.info("rank %s: dropping duplicate %s (%s)",
                        self.rank, msg_id, msg.get_type())
            self._send_ack(msg)  # re-ack: the first ack may have been lost
            return False
        if pipeline is not None:
            pipeline.submit(msg, needs_ack=True)
            return True
        if dispatch is not None:
            try:
                dispatch(msg)
            except BaseException:
                with self._seen_lock:
                    self._seen.pop(msg_id, None)
                raise
        self._send_ack(msg)
        return True

    def forget(self, msg: Message) -> None:
        """Drop ``msg`` from the dedup window so the sender's retransmit is
        redelivered instead of re-acked (failed-dispatch recovery)."""
        msg_id = msg.get(Message.MSG_ARG_KEY_MSG_ID)
        if msg_id is not None:
            with self._seen_lock:
                self._seen.pop(msg_id, None)

    def _send_ack(self, msg: Message) -> None:
        ack = Message(COMM_ACK_TYPE, self.rank, msg.get_sender_id())
        ack.add_params(Message.MSG_ARG_KEY_MSG_ID,
                       msg.get(Message.MSG_ARG_KEY_MSG_ID))
        if self.ack_decorator is not None:
            self.ack_decorator(ack)
        try:
            assert self._send_raw is not None
            self._send_raw(ack)
            self.stats.inc("acks_sent")
        except Exception as e:
            # best-effort: a lost ack just means the peer retransmits into
            # the dedup window
            logger.info("rank %s: ack send failed (%s)", self.rank, e)


class _IngestPipeline:
    """Staged server receive path (the PR 10 tentpole's transport stage).

    Splits the per-message work the host path serializes on the transport
    thread across three actors:

    * **io stage** — the transport receive thread runs only
      :meth:`_ReliableLink.on_receive`'s framing/ack/dedup and a bounded
      ``queue.Queue.put`` (backpressure: a full queue stalls the wire
      instead of growing an unbounded handler backlog);
    * **dispatch stage** — ONE worker thread runs the registered handlers,
      preserving the single-threaded-handler invariant every manager's
      round state machine assumes (FIFO per connection is also kept: the io
      stage enqueues in arrival order, including local pseudo-messages);
    * **durability stage** — handlers journal uploads via
      ``append_async``; their tickets are collected by the ambient
      :func:`~fedml_tpu.core.ingest.deferred_ack_scope` and the transport
      ack is released from the group-commit thread once the whole batch is
      fsynced.  "Ack implies journaled" (PR 4) holds exactly; a message
      whose dispatch (or journal batch) fails is forgotten from the dedup
      window and never acked, so the sender retransmits it.

    Observability: ``ingest.queue_depth`` gauge, per-stage
    ``ingest.stage_seconds`` histograms, and one ``ingest.accept`` span per
    traced message nested under the round tree (closed on every path, so
    ``trace_report --assert-closed`` stays green).
    """

    def __init__(self, manager: "FedMLCommManager", link: _ReliableLink,
                 depth: int = 64):
        self._manager = manager
        self._link = link
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._stop_flag = False
        self._watchdog = obs.health_watchdog(f"ingest.worker.rank{manager.rank}")
        self._thread = threading.Thread(
            target=self._worker, daemon=True,
            name=f"ingest-rank{manager.rank}")
        self._thread.start()

    def submit(self, msg: Message, needs_ack: bool) -> None:
        self._queue.put((msg, needs_ack, time.perf_counter()))
        obs.gauge_set("ingest.queue_depth", self._queue.qsize())

    def stop(self) -> None:
        # owned-by: main — monotonic shutdown latch; the worker only reads
        self._stop_flag = True  # owned-by: main
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=10.0)
        self._watchdog.close()

    def _worker(self) -> None:
        # a dying dispatch worker is exactly the crash whose last records
        # matter most — dump the flight ring on the way out, not just on
        # transport-handler exceptions (pre-PR 11 only _dispatch dumped)
        try:
            self._worker_loop()
        except BaseException:
            obs.flight_dump("ingest_worker_died")
            logger.exception("ingest worker: thread died")
            raise

    def _worker_loop(self) -> None:
        while True:
            # the dequeue timeout bounds beat latency, so the watchdog
            # proves liveness even across idle stretches; a wedged
            # _dispatch (the hang this guards against) stops the beats
            self._watchdog.beat()
            try:
                msg, needs_ack, t_enq = self._queue.get(timeout=0.25)
            except queue.Empty:
                if self._stop_flag:
                    return
                continue
            obs.gauge_set("ingest.queue_depth", self._queue.qsize())
            obs.histogram_observe("ingest.stage_seconds",
                                  time.perf_counter() - t_enq,
                                  labels={"stage": "queue"})
            try:
                self._process(msg, needs_ack)
            except Exception:  # the worker must survive any one message
                obs.flight_dump("ingest_worker_exception")
                logger.exception("ingest worker: unexpected failure on %s",
                                 msg.get_type())

    def _process(self, msg: Message, needs_ack: bool) -> None:
        t0 = time.perf_counter()
        ctx = obs.extract(msg)
        span = (obs.unique_span("ingest.accept", ctx,
                                node=self._manager.rank,
                                msg_type=str(msg.get_type()))
                if ctx is not None else obs.NULL_SPAN)
        try:
            with ingest.deferred_ack_scope() as sink:
                self._manager._dispatch(msg)
        except BaseException as e:
            # sync-path parity: withhold the ack and forget the msg_id so
            # the sender's retransmit retries the delivery — but keep the
            # worker alive (the receive loop it replaces would have died)
            self._link.forget(msg)
            span.end(error=str(e))
            logger.exception("ingest worker: dispatch of %s failed",
                             msg.get_type())
            return
        obs.histogram_observe("ingest.stage_seconds",
                              time.perf_counter() - t0,
                              labels={"stage": "dispatch"})
        if not needs_ack:
            span.end()
            return
        if not sink.tickets:
            self._link._send_ack(msg)
            span.end()
            return
        self._ack_when_durable(msg, list(sink.tickets), span)

    def _ack_when_durable(self, msg: Message, tickets, span) -> None:
        """Release the transport ack once every journal ticket the dispatch
        produced is durable (runs on the group-commit thread)."""
        state = {"remaining": len(tickets), "error": None}
        lock = threading.Lock()

        def _done(ticket) -> None:
            with lock:
                if ticket.error is not None and state["error"] is None:
                    state["error"] = ticket.error
                state["remaining"] -= 1
                if state["remaining"]:
                    return
                error = state["error"]
            if error is not None:
                # no ack for a failed batch: forget the msg_id so the
                # sender's retransmit re-journals the upload
                self._link.forget(msg)
                span.end(error=str(error))
                return
            # fedlint: allow[ack-before-journal] — runs from JournalTicket
            # completion callbacks: reaching here means every ticket in the
            # batch resolved, i.e. the uploads ARE durable before this ack
            self._link._send_ack(msg)  # fedlint: allow[ack-before-journal] — all batch tickets durable here
            span.end()

        for t in tickets:
            t.add_done_callback(_done)


class FedMLCommManager(Observer):
    #: subclasses that fan in uploads without being rank 0 (the hierarchy's
    #: edge aggregators) set this True to opt in to the staged ingest path
    wants_ingest_pipeline = False

    def __init__(self, args, comm=None, rank: int = 0, size: int = 0, backend: str = "LOOPBACK"):
        self.args = args
        self.size = int(size)
        self.rank = int(rank)
        self.backend = backend
        self.comm = comm
        # owned-by: main — _init_manager() assigns it before run() spawns /
        # enters the receive loop; the loop thread only reads it
        self.com_manager: Optional[BaseCommunicationManager] = None  # owned-by: main
        self.message_handler_dict: Dict[str, Callable[[Message], None]] = {}
        self._comm_stats = CommStats(node=self.rank)
        self._link = self._init_link()
        self._init_manager()
        if self._link is not None:
            self._link.bind(self._raw_send)
        self._pipeline = self._init_pipeline()
        self._chunking = self._init_chunking()

    def _init_link(self) -> Optional[_ReliableLink]:
        a = self.args
        if a is not None and not getattr(a, "comm_reliability", True):
            return None
        g = (lambda k, d: getattr(a, k, d) if a is not None else d)
        seed = g("comm_backoff_seed", g("random_seed", None))
        return _ReliableLink(
            self.rank, self._comm_stats,
            max_retries=int(g("comm_max_retries", 0)),
            backoff_base_s=float(g("comm_backoff_base_s", 0.2)),
            backoff_max_s=float(g("comm_backoff_max_s", 2.0)),
            jitter=float(g("comm_backoff_jitter", 0.25)),
            dedup_window=int(g("comm_dedup_window", 8192)),
            backoff_seed=int(seed) if seed is not None else None,
        )

    def _init_pipeline(self) -> Optional[_IngestPipeline]:
        """The staged ingest path is a FAN-IN feature: rank 0 absorbs the
        whole cohort's uploads, and hierarchy edge aggregators
        (``wants_ingest_pipeline``) absorb a block's worth; ordinary
        clients keep the synchronous receive loop."""
        a = self.args
        if (self._link is None or a is None
                or (self.rank != 0 and not self.wants_ingest_pipeline)
                or not ingest.pipeline_enabled(a)):
            return None
        depth = int(getattr(a, "ingest_queue_depth", 64))
        return _IngestPipeline(self, self._link, depth=depth)

    def _init_chunking(self):
        """Chunked resumable uploads (see ``core/distributed/chunking.py``).
        Receive capability is on by default (and advertised per link);
        chunked SENDING activates only with ``upload_chunk_bytes > 0``."""
        if self._link is None:
            return None
        from . import chunking

        state = chunking.ChunkingState.maybe_create(self)
        if state is not None:
            # advertise on ack frames too: on pure fan-in links (leaf ->
            # edge -> root) acks are the only reverse traffic, so without
            # this the upward direction could never negotiate chunking
            self._link.ack_decorator = state.advertise
        return state

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        """Enter the receive loop (blocks; reference ``fedml_comm_manager.py:24``)."""
        self.register_message_receive_handlers()
        assert self.com_manager is not None
        self.com_manager.handle_receive_message()
        logger.info("comm manager %s/%s done", self.rank, self.size)

    def run_async(self) -> threading.Thread:
        """Native addition: run the receive loop on a daemon thread so many
        node runtimes can cohabit one test process."""
        t = threading.Thread(target=self.run, daemon=True, name=f"comm-rank{self.rank}")
        t.start()
        return t

    def finish(self) -> None:
        """Stop the transport (reference ``fedml_comm_manager.py:61-76``)."""
        if self._chunking is not None:
            self._chunking.close()
        if self._pipeline is not None:
            self._pipeline.stop()
        if self._link is not None:
            self._link.stop()
        self._report_comm_stats()
        if self.com_manager is not None:
            self.com_manager.stop_receive_message()

    def _report_comm_stats(self) -> None:
        try:
            from ..mlops import log_comm_stats

            log_comm_stats(self.comm_stats_snapshot(), rank=self.rank)
        except Exception:  # observability must never take the run down
            logger.debug("comm stats report failed", exc_info=True)

    def comm_stats_snapshot(self) -> Dict[str, int]:
        """Reliability + fault + backend-reconnect counters for this node."""
        snap = self._comm_stats.snapshot()
        snap["reconnects"] += int(getattr(self.com_manager, "reconnect_count", 0) or 0)
        return snap

    # -- messaging ----------------------------------------------------------
    def get_sender_id(self) -> int:
        return self.rank

    def _raw_send(self, message: Message) -> None:
        assert self.com_manager is not None
        self.com_manager.send_message(message)

    def send_message(self, message: Message) -> None:
        # chunk seam: payload-bearing messages toward chunk-capable peers
        # stream as crc-framed chunks, each riding the reliability layer's
        # per-chunk ack/retransmit (resume-from-last-acked-chunk for free);
        # control traffic, legacy peers and small payloads fall through to
        # the whole-message path below
        if self._chunking is not None and self._chunking.maybe_send_chunked(message):
            return
        self._send_one(message)

    def _send_one(self, message: Message,
                  msg_id: Optional[str] = None) -> Optional[str]:
        """Stamp/track/send ONE frame (a whole message or a single chunk).

        ``msg_id`` is set when the caller (the chunked sender) already
        stamped the frame to pre-register it with its ack bookkeeping
        before the ack can race back on the receive thread."""
        assert self.com_manager is not None
        link = self._link
        if link is None or message.get_type() in _LOCAL_TYPES:
            self._raw_send(message)
            return None
        if msg_id is None:
            msg_id = link.stamp(message)
        if self._chunking is not None:
            self._chunking.advertise(message)
        attempt = 0
        while True:
            try:
                self._raw_send(message)
                self._comm_stats.inc("messages_sent")
                break
            except Exception as e:
                if attempt >= link.max_retries:
                    if link.max_retries > 0:
                        # the retransmitter owns delivery now; surfacing the
                        # exception would kill round threads the layer exists
                        # to protect
                        logger.warning(
                            "rank %s: send of %s failed %d times (%s); "
                            "deferring to retransmitter",
                            self.rank, message.get_type(), attempt + 1, e)
                        break
                    raise
                attempt += 1
                self._comm_stats.inc("retries")
                delay = link._backoff(attempt - 1)
                logger.info("rank %s: send of %s failed (%s); retry %d/%d in %.2fs",
                            self.rank, message.get_type(), e, attempt,
                            link.max_retries, delay)
                time.sleep(delay)
        link.track(msg_id, message)
        return msg_id

    def register_message_receive_handler(
        self, msg_type: str, handler_callback_func: Callable[[Message], None]
    ) -> None:
        self.message_handler_dict[str(msg_type)] = handler_callback_func

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their per-round handlers here."""

    # Observer
    def receive_message(self, msg_type: str, msg_params: Message) -> None:
        if self._chunking is not None:
            # per-link capability map (chunking negotiates DOWN to whole
            # messages for peers that never advertise)
            self._chunking.observe(msg_params)
        if self._link is None:
            self._dispatch(msg_params)
            return
        if self._pipeline is not None:
            # staged path: this thread is the io stage — dedup + enqueue
            # only; dispatch and (post-durability) ack happen downstream
            t0 = time.perf_counter()
            self._link.on_receive(msg_params, self._dispatch,
                                  pipeline=self._pipeline)
            obs.histogram_observe("ingest.stage_seconds",
                                  time.perf_counter() - t0,
                                  labels={"stage": "io"})
            return
        # the link calls _dispatch for fresh messages BEFORE acking them, so
        # handler-side durable effects (update journal) precede the ack
        self._link.on_receive(msg_params, self._dispatch)

    def _dispatch(self, msg_params: Message) -> None:
        if self._chunking is not None and self._chunking.intercepts(msg_params):
            # reassembly seam: chunk frames accumulate (journaled before
            # their acks); only a COMPLETED inner message re-enters here.
            # A ChunkError raise propagates to the normal failed-dispatch
            # routing — ack withheld, msg_id forgotten, sender retransmits.
            self._chunking.dispatch_chunk(msg_params, self._dispatch)
            return
        handler = self.message_handler_dict.get(str(msg_params.get_type()))
        if handler is None:
            logger.debug("rank %s: no handler for msg_type=%s",
                         self.rank, msg_params.get_type())
            return
        try:
            handler(msg_params)
        except Exception:
            # an unhandled handler exception is about to unwind the receive
            # loop — preserve the last telemetry window before it's lost
            try:
                from ..obs import flight_dump

                flight_dump("unhandled_exception")
            except Exception:
                pass
            raise

    # -- backend factory (reference ``fedml_comm_manager.py:78-134``) -------
    def _init_manager(self) -> None:
        backend = (self.backend or FEDML_BACKEND_LOOPBACK).upper()
        run_id = str(getattr(self.args, "run_id", "0"))
        if backend == FEDML_BACKEND_LOOPBACK:
            from .communication.loopback import LoopbackCommManager

            self.com_manager = LoopbackCommManager(channel=run_id, rank=self.rank, size=self.size)
        elif backend == FEDML_BACKEND_GRPC:
            try:
                from .communication.grpc.grpc_comm_manager import GRPCCommManager
            except ImportError as e:
                raise NotImplementedError(
                    "GRPC backend module not available in this build"
                ) from e

            base_port = int(getattr(self.args, "grpc_base_port", 8890))
            ip_config = getattr(self.args, "grpc_ipconfig_path", None)
            self.com_manager = GRPCCommManager(
                host=getattr(self.args, "grpc_host", "127.0.0.1"),
                port=base_port + self.rank,
                ip_config=ip_config,
                client_id=self.rank,
                client_num=self.size,
                base_port=base_port,
                send_retries=int(getattr(self.args, "grpc_send_retries", 30)),
                send_backoff_base_s=float(getattr(self.args, "grpc_send_backoff_base_s", 0.2)),
            )
        elif backend in (FEDML_BACKEND_MQTT_S3, FEDML_BACKEND_MQTT_S3_MNN):
            try:
                from .communication.mqtt_s3.mqtt_s3_comm_manager import MqttS3CommManager
            except ImportError as e:
                raise NotImplementedError(
                    "MQTT_S3 backend module not available in this build"
                ) from e

            self.com_manager = MqttS3CommManager(
                args=self.args,
                topic=run_id,
                client_rank=self.rank,
                client_num=self.size,
                mnn_mode=(backend == FEDML_BACKEND_MQTT_S3_MNN),
            )
        elif backend == FEDML_BACKEND_TRPC:
            from .communication.tcp.tcp_comm_manager import TCPCommManager

            self.com_manager = TCPCommManager(
                host=getattr(self.args, "trpc_host", "127.0.0.1"),
                base_port=int(getattr(self.args, "trpc_base_port", 9690)),
                rank=self.rank,
                size=self.size,
                ip_table=getattr(self.args, "trpc_ip_table", None),
                bind_host=getattr(self.args, "trpc_bind_host", "0.0.0.0"),
                connect_retries=int(getattr(self.args, "trpc_connect_retries", 20)),
                retry_interval_s=float(getattr(self.args, "trpc_retry_interval_s", 0.5)),
            )
        else:
            raise ValueError(f"unsupported comm backend: {self.backend!r}")
        fault_spec = getattr(self.args, "fault_plan", None) if self.args is not None else None
        if fault_spec:
            from .faults import FaultPlan, FaultyCommManager

            plan = FaultPlan.from_dict(fault_spec)
            self.com_manager = FaultyCommManager(
                self.com_manager, plan.injector(self.rank), self._comm_stats
            )
        self.com_manager.add_observer(self)
