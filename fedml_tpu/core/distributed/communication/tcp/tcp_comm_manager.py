"""Raw-TCP tensor-RPC backend ("TRPC" slot).

Role of reference ``core/distributed/communication/trpc/`` (torch.distributed
RPC with optional CUDA-RPC device maps): a point-to-point tensor transport
that skips the broker/blob indirection of MQTT+S3 and the HTTP/2 framing of
gRPC.  Each rank listens on ``base_port + rank``; a send is one
length-prefixed pickled Message over a fresh connection (device arrays are
host-fetched by the shared serializer — the TPU analog of the reference's
GPU-direct device-map config is XLA collectives, not host RPC, so host
transport stays simple).
"""

from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

from ..base_com_manager import BaseCommunicationManager, Observer
from ..message import Message
from ..serialization import dumps, loads

logger = logging.getLogger(__name__)

_STOP = object()
_MAX_FRAME = 1 << 31  # frames must fit the length prefix contract


class TCPCommManager(BaseCommunicationManager):
    """``ip_table`` maps rank -> host for multi-machine runs (the analog of
    the gRPC backend's ip-config CSV); ranks absent from the table fall back
    to ``host``.  The local socket binds ``bind_host`` (default all
    interfaces, so a remote peer can reach it)."""

    def __init__(self, host: str = "127.0.0.1", base_port: int = 9690,
                 rank: int = 0, size: int = 0,
                 ip_table: Optional[Dict[int, str]] = None,
                 bind_host: str = "0.0.0.0",
                 connect_retries: int = 20, retry_interval_s: float = 0.5):
        self.host = host
        self.base_port = int(base_port)
        self.rank = int(rank)
        self.size = int(size)
        self.ip_table = {int(k): str(v) for k, v in (ip_table or {}).items()}
        self.connect_retries = int(connect_retries)
        self.retry_interval_s = float(retry_interval_s)
        self.bind_host = bind_host
        self.reconnect_count = 0  # connect retries + listener rebinds
        # reconnect_count has two writer threads (accept loop rebinds,
        # sender retries) — increments are read-modify-write and must not
        # lose counts under concurrent senders
        self._stats_lock = threading.Lock()
        self._observers: List[Observer] = []
        self._inbox: "queue.Queue" = queue.Queue()
        self._running = False
        self._closed = False

        self._server = self._bind_listener()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True,
                                               name=f"tcp-accept-{self.rank}")
        self._accept_thread.start()

    # -- transport ----------------------------------------------------------
    def _bind_listener(self) -> socket.socket:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.bind_host, self.base_port + self.rank))
        s.listen(16)
        return s

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                if self._closed:
                    return  # deliberate shutdown
                # the listener died under us (not a stop): rebind with a
                # bounded retry so one socket hiccup doesn't deafen the rank
                for attempt in range(self.connect_retries):
                    try:
                        # owned-by: accept_loop — after __init__ publication
                        # only the accept loop rebinds the listener; other
                        # threads just read the handle (close is idempotent)
                        self._server = self._bind_listener()  # owned-by: accept_loop
                        with self._stats_lock:
                            self.reconnect_count += 1
                        logger.warning("tcp rank %s: listener died; rebound "
                                       "after %d attempts", self.rank, attempt + 1)
                        break
                    except OSError:
                        if self._closed:
                            return
                        time.sleep(self.retry_interval_s)
                else:
                    logger.error("tcp rank %s: could not rebind listener; "
                                 "receive path is dead", self.rank)
                    return
                continue
            threading.Thread(target=self._recv_one, args=(conn,), daemon=True).start()

    def _recv_one(self, conn: socket.socket) -> None:
        try:
            header = self._read_exact(conn, 8)
            if header is None:
                return
            (length,) = struct.unpack("<Q", header)
            if length > _MAX_FRAME:
                logger.warning("tcp rank %s: oversized frame %d dropped", self.rank, length)
                return
            payload = self._read_exact(conn, length)
            if payload is None:
                return
            msg = Message()
            msg.init(loads(payload))
            self._inbox.put(msg)
        finally:
            conn.close()

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- BaseCommunicationManager -------------------------------------------
    def send_message(self, msg: Message) -> None:
        receiver = int(msg.get_receiver_id())
        payload = dumps(dict(msg.get_params()))
        if len(payload) > _MAX_FRAME:
            # fail at the SEND site — a receive-side drop would hang the round
            raise ValueError(
                f"message of {len(payload)} bytes exceeds the {_MAX_FRAME}-byte "
                "frame limit; ship weights via the MQTT_S3 blob plane instead"
            )
        addr = (self.ip_table.get(receiver, self.host), self.base_port + receiver)
        last_err: Optional[Exception] = None
        for attempt in range(self.connect_retries):
            try:
                with socket.create_connection(addr, timeout=30) as s:
                    s.sendall(struct.pack("<Q", len(payload)) + payload)
                if attempt > 0:
                    with self._stats_lock:
                        self.reconnect_count += 1
                return
            except (ConnectionRefusedError, socket.timeout, OSError) as e:
                # peer process may not have bound its port yet (startup race),
                # or died and is rejoining — fresh-connection-per-send means
                # every retry IS a reconnect
                last_err = e
                time.sleep(self.retry_interval_s)
        raise ConnectionError(f"tcp rank {self.rank}: cannot reach rank {receiver} at {addr}") from last_err

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        ready = Message(type="connection_ready", sender_id=self.rank, receiver_id=self.rank)
        self._notify(ready)
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(item)
        # owned-by: main — shutdown latch written by the receive/stop path;
        # the accept loop only reads it to tell stop from socket death
        self._closed = True  # owned-by: main
        try:
            self._server.close()
        except OSError:
            pass

    def stop_receive_message(self) -> None:
        self._running = False
        self._closed = True
        self._inbox.put(_STOP)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg)
            except Exception:
                logger.exception("tcp rank %s: handler for %r raised", self.rank, msg.get_type())
