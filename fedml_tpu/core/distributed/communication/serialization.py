"""Host-side payload serialization helpers shared by the wire backends.

The reference pickles torch state dicts straight onto the wire (grpc backend
``grpc_comm_manager.py:78-108``, mqtt_s3 S3 pickle).  Here payloads are jax
pytrees whose leaves may be live device buffers; ``device_get_tree`` converts
them to host numpy before pickling so (a) no device handle is ever serialized
and (b) transfers happen once, explicitly.
"""

from __future__ import annotations

import pickle
from typing import Any


def device_get_tree(obj: Any) -> Any:
    """Return ``obj`` with every jax.Array leaf replaced by host numpy."""
    import jax

    def _leaf(x):
        if isinstance(x, jax.Array):
            return jax.device_get(x)
        return x

    return jax.tree_util.tree_map(_leaf, obj)


def dumps(obj: Any) -> bytes:
    return pickle.dumps(device_get_tree(obj), protocol=pickle.HIGHEST_PROTOCOL)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
