"""Host-side payload serialization helpers shared by the wire backends.

The reference pickles torch state dicts straight onto the wire (grpc backend
``grpc_comm_manager.py:78-108``, mqtt_s3 S3 pickle).  Here payloads are jax
pytrees whose leaves may be live device buffers; ``device_get_tree`` converts
them to host numpy before pickling so (a) no device handle is ever serialized
and (b) transfers happen once, explicitly.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any


def device_get_tree(obj: Any) -> Any:
    """Return ``obj`` with every jax.Array leaf replaced by host numpy."""
    import jax

    def _leaf(x):
        if isinstance(x, jax.Array):
            return jax.device_get(x)
        return x

    return jax.tree_util.tree_map(_leaf, obj)


def dumps(obj: Any) -> bytes:
    return pickle.dumps(device_get_tree(obj), protocol=pickle.HIGHEST_PROTOCOL)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def _load_cached(blob: bytes) -> Any:
    return pickle.loads(blob)


class CachedPayload:
    """A pytree wrapper whose wire serialization is computed once and reused.

    The server broadcast path serializes the identical global model once per
    invited client (and once more per retransmit).  Wrapping the tree in
    ``CachedPayload`` makes every wire backend reuse ONE precomputed pickle
    blob: the wrapper is an unregistered pytree node, so ``tree_map`` /
    ``device_get_tree`` pass it through as a leaf, and ``pickle`` hits
    :meth:`__reduce__`, which substitutes the cached bytes.  The blob is
    built lazily under a lock on first pickle — a loopback run (pass by
    reference) never pays for serialization at all; receivers (and the
    loopback in-process path via ``Message.get``) unwrap through
    ``__fedml_unwrap__``.
    """

    __slots__ = ("_tree", "_blob", "_lock")

    def __init__(self, tree: Any):
        self._tree = tree
        self._blob: bytes = b""
        self._lock = threading.Lock()

    def __fedml_unwrap__(self) -> Any:
        return self._tree

    def wire_bytes(self) -> bytes:
        from ... import obs

        with self._lock:
            if not self._blob:
                self._blob = pickle.dumps(device_get_tree(self._tree),
                                          protocol=pickle.HIGHEST_PROTOCOL)
                obs.counter_inc("broadcast.payload_builds")
            else:
                obs.counter_inc("broadcast.payload_cache_hits")
        return self._blob

    def __reduce__(self):
        return (_load_cached, (self.wire_bytes(),))
