"""Minimal MQTT-style pub/sub broker over TCP (control plane).

The reference's production transport is a hosted MQTT broker
(``core/distributed/communication/mqtt/mqtt_manager.py``).  paho-mqtt is not in
this image, and a hosted broker is an external dependency anyway — so the
rebuild ships its own tiny broker implementing the slice of MQTT the FL
protocol actually uses:

* topic publish/subscribe with trailing-``#`` prefix wildcards,
* QoS0 delivery,
* last-will messages published when a client's socket dies without a clean
  DISCONNECT (liveness parity with the reference's last-will/active-status
  topics, ``mqtt_s3_multi_clients_comm_manager.py:325-352``).

Wire format: 4-byte big-endian length + dict frames, in one of TWO
encodings sniffed per connection: pickle (Python peers, the default) or
UTF-8 JSON (first body byte ``{`` — the interop encoding the Java edge SDK
``android/sdk`` speaks; pickle is not implementable from a phone runtime).
The broker remembers each connection's encoding from its first frame and
delivers every frame to a client in that client's own encoding, so Python
silos and JSON devices share one broker.  The broker is a plain threaded
TCP server so true multi-process cross-silo runs work on one host or
across hosts.
"""

from __future__ import annotations

import json
import logging
import pickle
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")

try:
    import numpy as _np
except ImportError:  # broker is usable without the array stack
    _np = None


def _np_json_default(o):
    """Coerce numpy scalars for JSON subscribers: a Python silo that computes
    a status field as ``np.int64``/``np.float32`` must not silently lose the
    whole frame for a Java-wire peer.  Non-finite floats still fail via
    ``allow_nan=False`` after coercion; everything else stays unserializable."""
    if _np is not None:
        if isinstance(o, _np.bool_):
            return bool(o)
        if isinstance(o, _np.integer):
            return int(o)
        if isinstance(o, _np.floating):
            return float(o)
    raise TypeError(f"Object of type {type(o).__name__} is not JSON serializable")


def _encode_frame(obj: dict, enc: str) -> bytes:
    if enc == "json":
        # allow_nan=False: the token 'NaN' is not JSON and would poison a
        # Java peer's parser mid-stream; non-finite payloads must hit the
        # caller's drop path instead
        data = json.dumps(obj, allow_nan=False, default=_np_json_default).encode("utf-8")
    else:
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(data)) + data


def _send_frame(sock: socket.socket, obj: dict, enc: str = "pickle") -> None:
    sock.sendall(_encode_frame(obj, enc))


def _recv_frame(sock: socket.socket) -> Optional[Tuple[dict, str]]:
    """-> (frame, encoding) — encoding sniffed from the first body byte
    (every pickle protocol >= 2 starts with 0x80; JSON objects with '{').
    An undecodable body is treated as connection death (None), NOT raised:
    an exception here would kill the broker's client thread before its
    cleanup block, leaving a zombie subscriber whose last will never
    fires."""
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    try:
        if body[:1] == b"{":
            return json.loads(body.decode("utf-8")), "json"
        return pickle.loads(body), "pickle"
    except Exception:
        logger.warning("undecodable %d-byte frame: dropping the connection", n)
        return None


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def topic_matches(pattern: str, topic: str) -> bool:
    if pattern.endswith("#"):
        return topic.startswith(pattern[:-1])
    return pattern == topic


class LocalBroker:
    """Threaded TCP pub/sub broker. ``LocalBroker().start()`` → ``.port``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server_sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # conn -> (subscriptions, last_will)
        self._clients: Dict[socket.socket, Tuple[List[str], Optional[dict]]] = {}
        # conn -> wire encoding ("pickle"/"json"), learned from its frames
        self._enc: Dict[socket.socket, str] = {}
        # conn -> send lock: concurrent _publish calls (one per publishing
        # client thread) must not interleave a shared subscriber's frames
        self._send_locks: Dict[socket.socket, threading.Lock] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LocalBroker":
        # owned-by: main — bound/configured before the accept thread starts;
        # the loops only read (accept on) it afterwards
        self._server_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # owned-by: main
        self._server_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server_sock.bind((self.host, self.port))
        self.port = self._server_sock.getsockname()[1]
        self._server_sock.listen(128)
        # owned-by: main — start/stop latch; accept/client loops only read
        self._running = True  # owned-by: main
        self._thread = threading.Thread(target=self._accept_loop, daemon=True, name="broker-accept")
        self._thread.start()
        logger.info("local broker on %s:%s", self.host, self.port)
        return self

    def stop(self) -> None:
        self._running = False
        if self._server_sock is not None:
            try:
                self._server_sock.close()
            except OSError:
                pass
        with self._lock:
            for conn in list(self._clients):
                try:
                    conn.close()
                except OSError:
                    pass
            self._clients.clear()

    # -- internals ----------------------------------------------------------
    def _accept_loop(self) -> None:
        assert self._server_sock is not None
        while self._running:
            try:
                conn, _ = self._server_sock.accept()
            except OSError:
                break
            with self._lock:
                self._clients[conn] = ([], None)
                self._send_locks[conn] = threading.Lock()
            threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True, name="broker-client"
            ).start()

    def _client_loop(self, conn: socket.socket) -> None:
        clean = False
        # try/finally: a publish-path exception (broken peer socket racing
        # removal, bad payload) must not kill this thread BEFORE the cleanup
        # block — that would leave a zombie registration holding the dead
        # socket in every future fan-out and a last will that never fires
        try:
            while self._running:
                got = _recv_frame(conn)
                if got is None:
                    break
                frame, enc = got
                self._enc[conn] = enc
                op = frame.get("op")
                if op == "SUB":
                    with self._lock:
                        subs, will = self._clients.get(conn, ([], None))
                        subs.append(str(frame["topic"]))
                        self._clients[conn] = (subs, will)
                elif op == "UNSUB":
                    with self._lock:
                        subs, will = self._clients.get(conn, ([], None))
                        subs = [s for s in subs if s != str(frame["topic"])]
                        self._clients[conn] = (subs, will)
                elif op == "PUB":
                    self._publish(str(frame["topic"]), frame.get("payload"))
                elif op == "WILL":
                    with self._lock:
                        subs, _ = self._clients.get(conn, ([], None))
                        self._clients[conn] = (subs, {"topic": str(frame["topic"]), "payload": frame.get("payload")})
                elif op == "DISCONNECT":
                    clean = True
                    break
        except Exception:
            # protocol error (malformed frame, publish-path failure): drop
            # THIS connection, loudly but locally — the finally below still
            # unregisters it and fires its last will
            logger.warning("broker client loop error: dropping connection",
                           exc_info=True)
        finally:
            # fire last will on unclean death (MQTT parity)
            with self._lock:
                _, will = self._clients.pop(conn, ([], None))
                self._enc.pop(conn, None)
                self._send_locks.pop(conn, None)
            try:
                conn.close()
            except OSError:
                pass
            if not clean and will is not None and self._running:
                try:
                    self._publish(will["topic"], will["payload"])
                except Exception:
                    logger.exception("last-will publish for dead client failed")

    def _publish(self, topic: str, payload) -> None:
        with self._lock:
            targets = [
                (c, self._enc.get(c, "pickle"), self._send_locks.get(c))
                for c, (subs, _) in self._clients.items()
                if any(topic_matches(p, topic) for p in subs)
            ]
        # serialize ONCE per encoding (not per subscriber); a payload that
        # cannot be JSON-encoded (tensors, non-finite floats) is dropped for
        # JSON subscribers ONLY — control-plane messages are JSON-safe by
        # design (the MNN flow ships models as FILE references), so this is
        # a misrouted data-plane frame.  Pickle failures stay loud.
        frames: Dict[str, Optional[bytes]] = {}
        for enc in {e for _, e, _ in targets}:
            try:
                frames[enc] = _encode_frame(
                    {"op": "MSG", "topic": topic, "payload": payload}, enc
                )
            except (TypeError, ValueError):
                if enc != "json":
                    raise
                logger.warning(
                    "dropping non-JSON payload on %s for JSON subscribers", topic
                )
                frames[enc] = None
        logger.debug("PUB %s -> %d subscriber(s)", topic, len(targets))
        dead = []
        for c, enc, slock in targets:
            data = frames.get(enc)
            if data is None or slock is None:
                logger.debug("PUB %s: skipping fd=%s (no frame/lock)", topic,
                             c.fileno() if c.fileno() >= 0 else "?")
                continue
            try:
                with slock:  # frames to one subscriber must never interleave
                    c.sendall(data)
            except OSError as e:
                logger.debug("PUB %s: fd=%s dead (%s)", topic, c.fileno(), e)
                dead.append(c)
        for c in dead:
            with self._lock:
                self._clients.pop(c, None)
                self._enc.pop(c, None)
                self._send_locks.pop(c, None)


class BrokerClient:
    """Client for :class:`LocalBroker` with paho-like callback semantics.

    ``encoding="json"`` speaks the interop wire the Java edge SDK uses —
    handy for driving/validating that protocol from Python tests.

    Auto-reconnect (paho parity the first cut lacked): when the broker drops
    the connection mid-run — broker restart, transient network path — the
    recv thread redials with exponential backoff and replays the session
    state (last will, then every subscription), so QoS0 delivery resumes
    without the owner noticing beyond a gap.  Frames published by others
    while disconnected are lost (QoS0 semantics); the node runtime's
    ack/retransmit layer is what papers over that gap end to end.
    ``reconnects`` counts successful redials for the mlops comm-stats sink.
    """

    def __init__(self, host: str, port: int, on_message: Callable[[str, object], None],
                 encoding: str = "pickle",
                 reconnect_retries: int = 20, reconnect_base_s: float = 0.1,
                 reconnect_max_s: float = 2.0):
        self.host = host
        self.port = int(port)
        self._sock = socket.create_connection((host, port), timeout=30)
        self._sock.settimeout(None)
        self.on_message = on_message
        self.encoding = encoding
        self.reconnect_retries = int(reconnect_retries)
        self.reconnect_base_s = float(reconnect_base_s)
        self.reconnect_max_s = float(reconnect_max_s)
        self.reconnects = 0
        self._subs: List[str] = []
        self._will: Optional[Tuple[str, object]] = None
        self._running = True
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._recv_loop, daemon=True, name="broker-recv")
        self._thread.start()

    def subscribe(self, topic: str) -> None:
        with self._lock:
            if topic not in self._subs:
                self._subs.append(topic)
            _send_frame(self._sock, {"op": "SUB", "topic": topic}, self.encoding)

    def unsubscribe(self, topic: str) -> None:
        with self._lock:
            self._subs = [s for s in self._subs if s != topic]
            _send_frame(self._sock, {"op": "UNSUB", "topic": topic}, self.encoding)

    def publish(self, topic: str, payload) -> None:
        with self._lock:
            _send_frame(self._sock, {"op": "PUB", "topic": topic, "payload": payload},
                        self.encoding)

    def set_last_will(self, topic: str, payload) -> None:
        with self._lock:
            self._will = (topic, payload)
            _send_frame(self._sock, {"op": "WILL", "topic": topic, "payload": payload},
                        self.encoding)

    def _reconnect(self) -> bool:
        """Redial and replay session state. Returns False when retries are
        exhausted or the client was stopped meanwhile."""
        for attempt in range(self.reconnect_retries):
            if not self._running:
                return False
            try:
                sock = socket.create_connection((self.host, self.port), timeout=30)
                sock.settimeout(None)
            except OSError:
                delay = min(self.reconnect_base_s * (2 ** attempt), self.reconnect_max_s)
                time.sleep(delay)
                continue
            with self._lock:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = sock
                try:
                    # will FIRST so a death mid-replay still announces itself
                    if self._will is not None:
                        _send_frame(sock, {"op": "WILL", "topic": self._will[0],
                                           "payload": self._will[1]}, self.encoding)
                    for topic in self._subs:
                        _send_frame(sock, {"op": "SUB", "topic": topic}, self.encoding)
                except OSError:
                    continue  # broker died again mid-replay; keep trying
            self.reconnects += 1
            logger.info("broker client reconnected to %s:%s (attempt %d)",
                        self.host, self.port, attempt + 1)
            return True
        logger.warning("broker client gave up reconnecting to %s:%s after %d attempts",
                       self.host, self.port, self.reconnect_retries)
        return False

    def disconnect(self) -> None:
        """Graceful close: DISCONNECT, half-close (FIN), DRAIN inbound to
        EOF, then close.  An immediate ``close()`` here can send a TCP RST
        (this side always has undrained wildcard deliveries in its receive
        buffer), and an RST DISCARDS our still-unread frames at the broker —
        observed losing the tail of a FINISH fan-out, wedging a client
        forever.  shutdown(SHUT_WR) sends FIN instead; the recv thread keeps
        draining until the broker processes our DISCONNECT and closes."""
        # owned-by: main — connect/disconnect latch; the recv loop only reads
        self._running = False  # owned-by: main
        try:
            with self._lock:
                # the half-close must be fenced with the sends: a publish
                # slipping between DISCONNECT and FIN would make the broker
                # break at DISCONNECT with unread data -> RST right back
                _send_frame(self._sock, {"op": "DISCONNECT"}, self.encoding)
                self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        if threading.current_thread() is self._thread:
            # called from on_message: the recv loop (this thread) resumes
            # draining when the handler returns and closes the socket at EOF
            return
        self._thread.join(timeout=5)
        try:
            self._sock.close()
        except OSError:
            pass

    def _recv_loop(self) -> None:
        # reads to EOF even after disconnect() flips _running: draining the
        # inbound stream is what keeps the close RST-free (see disconnect)
        while True:
            got = _recv_frame(self._sock)
            if got is None:
                # EOF with the client still live = the broker went away, not
                # us: redial and resume instead of going deaf
                if self._running and self._reconnect():
                    continue
                break
            frame, _ = got
            if frame.get("op") == "MSG":
                try:
                    self.on_message(str(frame["topic"]), frame.get("payload"))
                except Exception:
                    logger.exception("broker client on_message raised")
        # EOF: close here too — the owner of the close when disconnect()
        # was issued from this thread (idempotent otherwise)
        try:
            self._sock.close()
        except OSError:
            pass
