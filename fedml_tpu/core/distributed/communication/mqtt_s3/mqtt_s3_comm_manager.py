"""MQTT+S3 transport: control plane over the pub/sub broker, tensor payloads
over the blob store.

Parity with reference ``mqtt_s3_multi_clients_comm_manager.py:20-352``:

* per-pair topics ``fedml_{run_id}_{sender}_{receiver}``; each rank subscribes
  to ``fedml_{run_id}_*_{rank}`` (prefix wildcard),
* any ``model_params`` value in an outbound message is swapped for a
  ``model_params_url`` blob reference before publish (control/data split,
  reference ``:214-284``); inbound messages hydrate the blob back so handlers
  always see in-memory pytrees (reference ``:182-208``),
* last-will + active-status topics for liveness (reference ``:325-352``).

MNN mode (``mnn_mode=True``) keeps the blob as a *file path* in the message
(``model_params_file``) instead of hydrating it — matching the reference's
mqtt_s3_mnn variant where the payload is a serialized model file consumed by
the mobile runtime.
"""

from __future__ import annotations

import json
import logging
import queue
from typing import List

from ..base_com_manager import BaseCommunicationManager, Observer
from ..message import Message
from .adapters import create_blob_store, create_broker_client

logger = logging.getLogger(__name__)

_STOP = object()


class MqttS3CommManager(BaseCommunicationManager):
    def __init__(
        self,
        args=None,
        topic: str = "fedml",
        client_rank: int = 0,
        client_num: int = 0,
        mnn_mode: bool = False,
    ):
        self.run_id = str(topic)
        self.rank = int(client_rank)
        self.client_num = int(client_num)
        self.mnn_mode = bool(mnn_mode)
        host = str(getattr(args, "mqtt_host", "127.0.0.1"))
        port = int(getattr(args, "mqtt_port", 0))
        if port == 0:
            raise ValueError(
                "MQTT_S3 backend needs args.mqtt_port (start a "
                "fedml_tpu...mqtt_s3.broker.LocalBroker and pass its port)"
            )
        blob_root = getattr(args, "s3_blob_root", None)
        # adapter seams: s3:// root + boto3 -> real S3; mqtt_transport=paho
        # (or auto with paho installed) -> real MQTT broker
        self.blob_store = create_blob_store(blob_root)
        self._observers: List[Observer] = []
        self._inbox: "queue.Queue" = queue.Queue()
        self._running = False

        self._client = create_broker_client(
            host, port, self._on_broker_message,
            transport=getattr(args, "mqtt_transport", None),
            client_id=f"fedml_{self.run_id}_r{self.rank}",
            reconnect_retries=getattr(args, "mqtt_reconnect_retries", None),
            reconnect_base_s=getattr(args, "mqtt_reconnect_base_s", None),
        )
        # liveness parity: last-will marks this rank offline if the socket dies
        self._client.set_last_will(
            self._status_topic(), json.dumps({"rank": self.rank, "status": "OFFLINE"})
        )
        self._client.subscribe(self._recv_pattern())

    # -- topics -------------------------------------------------------------
    # '/'-separated levels so the subscribe pattern is a VALID MQTT topic
    # filter ('#' must occupy a whole level — a real broker rejects
    # 'prefix_#'); the in-repo broker treats trailing-# as a prefix
    # wildcard, which coincides with MQTT's multi-level wildcard for these
    # level-aligned patterns
    def _topic(self, sender: int, receiver: int) -> str:
        return f"fedml/{self.run_id}/{sender}/{receiver}"

    def _recv_pattern(self) -> str:
        # precise receiver filtering happens in _on_broker_message
        return f"fedml/{self.run_id}/#"

    def _status_topic(self) -> str:
        return f"fedml/{self.run_id}/status"

    @property
    def reconnect_count(self) -> int:
        """Broker redials since start (in-repo client; paho reconnects inside
        its own network loop and reports none here)."""
        return int(getattr(self._client, "reconnects", 0) or 0)

    # -- BaseCommunicationManager -------------------------------------------
    def send_message(self, msg: Message) -> None:
        params = dict(msg.get_params())
        model_params = params.pop(Message.MSG_ARG_KEY_MODEL_PARAMS, None)
        if model_params is not None:
            key = f"{self.run_id}-r{self.rank}-{msg.get_type()}"
            url = self.blob_store.write_model(key, model_params)
            params[Message.MSG_ARG_KEY_MODEL_PARAMS_URL] = url
        topic = self._topic(int(msg.get_sender_id()), int(msg.get_receiver_id()))
        self._client.publish(topic, params)

    def broadcast_status(self, status: str) -> None:
        self._client.publish(
            self._status_topic(), json.dumps({"rank": self.rank, "status": status})
        )

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        ready = Message(type="connection_ready", sender_id=self.rank, receiver_id=self.rank)
        self._notify(ready)
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(item)
        self._client.disconnect()

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)

    # -- internals ----------------------------------------------------------
    def _on_broker_message(self, topic: str, payload) -> None:
        if topic == self._status_topic():
            return  # status topic is observed by managers via their own sub
        # topic = fedml/{run_id}/{sender}/{receiver}
        parts = topic.split("/")
        if len(parts) != 4:
            return
        try:
            receiver = int(parts[3])
        except ValueError:
            return
        if receiver != self.rank:
            return
        params = dict(payload)
        url = params.get(Message.MSG_ARG_KEY_MODEL_PARAMS_URL)
        if url is not None and not self.mnn_mode:
            # hydrate data plane (reference mqtt_s3...:182-208)
            params[Message.MSG_ARG_KEY_MODEL_PARAMS] = self.blob_store.read_model(url)
        msg = Message()
        msg.init(params)
        self._inbox.put(msg)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg)
            except Exception:
                logger.exception(
                    "mqtt_s3 rank %s: handler for msg_type=%r raised", self.rank, msg.get_type()
                )
