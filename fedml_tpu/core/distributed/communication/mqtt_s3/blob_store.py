"""File-backed blob store — the S3 data plane of the MQTT+S3 transport.

Parity with reference ``core/distributed/communication/s3/remote_storage.py``
(``S3Storage.write_model``/``read_model``): model pytrees never ride the
control plane; they are written as blobs and the control message carries
``model_params_url``.  Backed by a shared directory (NFS/local disk); the URL
scheme is ``file://``.  A real S3 backend would slot in behind the same two
methods (boto3 is deliberately not a dependency).
"""

from __future__ import annotations

import os
import pickle
import tempfile
import uuid
from typing import Any

from ..serialization import device_get_tree


class BlobStore:
    def __init__(self, root: str | None = None):
        self.root = root or os.path.join(tempfile.gettempdir(), "fedml_tpu_blobs")
        os.makedirs(self.root, exist_ok=True)

    def write_model(self, key: str, pytree: Any) -> str:
        """Write and return a ``file://`` URL (reference ``remote_storage.py:42``)."""
        name = f"{key}-{uuid.uuid4().hex}.pkl"
        path = os.path.join(self.root, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(device_get_tree(pytree), f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)  # atomic publish
        return f"file://{path}"

    def read_model(self, url: str) -> Any:
        """Read back a blob by URL (reference ``remote_storage.py:63``)."""
        assert url.startswith("file://"), f"unsupported blob url {url!r}"
        with open(url[len("file://"):], "rb") as f:
            return pickle.load(f)
