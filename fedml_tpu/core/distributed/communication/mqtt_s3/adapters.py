"""Real-transport adapters: paho-mqtt client + boto3 S3 blob store.

The in-repo :class:`~.broker.LocalBroker`/:class:`~.broker.BrokerClient` pair
is the zero-dependency transport; production deployments of the reference
speak real MQTT (paho) to a hosted broker and real S3 (boto3) for blobs
(``mqtt_s3_multi_clients_comm_manager.py:214-284``,
``s3/remote_storage.py``).  Neither library ships in this image, so this
module provides the SEAM: two factories that return the in-repo
implementations by default and drop in the real clients — behind the exact
same surface — when the libraries are importable and the config asks for
them.

Surface contract (what :class:`~.mqtt_s3_comm_manager.MqttS3CommManager`,
the edge daemon, and the mlops sink consume):

* client: ``subscribe(topic) / unsubscribe(topic) / publish(topic, payload)
  / set_last_will(topic, payload) / disconnect()`` + an ``on_message(topic,
  payload)`` callback, where payload is an arbitrary python object
  (pickled to bytes on the MQTT wire) and ``#`` works as a trailing prefix
  wildcard (MQTT's multi-level wildcard is a superset);
* blob store: ``write_model(key, pytree) -> url`` / ``read_model(url)``.
"""

from __future__ import annotations

import logging
import pickle
import threading
from typing import Any, Callable, Optional

from .blob_store import BlobStore
from .broker import BrokerClient

logger = logging.getLogger(__name__)


def _paho():
    try:
        import paho.mqtt.client as mqtt  # type: ignore

        return mqtt
    except ImportError:
        return None


def _boto3():
    try:
        import boto3  # type: ignore

        return boto3
    except ImportError:
        return None


class PahoBrokerClient:
    """paho-mqtt behind the BrokerClient surface.

    Connection is LAZY (first subscribe/publish): paho's ``will_set`` must
    precede ``connect``, while the in-repo surface sets the will after
    construction — deferring the connect lets both orders work.  Payloads are
    pickled to bytes on publish and unpickled on receive, so handlers see the
    same python objects the in-repo broker delivers.  Only unpickle from a
    broker you trust (same trust model as the reference's pickled S3 blobs).
    """

    def __init__(self, host: str, port: int,
                 on_message: Callable[[str, object], None],
                 client_id: str = "", keepalive: int = 180, mqtt_module=None):
        self._mqtt = mqtt_module if mqtt_module is not None else _paho()
        if self._mqtt is None:
            raise ImportError("paho-mqtt is not installed")
        self.host, self.port, self.keepalive = host, int(port), int(keepalive)
        self.on_message = on_message
        self._connected = False
        self._subs: set = set()  # re-armed after any reconnect
        self._lock = threading.Lock()
        self._client = self._make_client(client_id)
        self._client.on_message = self._handle

    def _make_client(self, client_id: str):
        mqtt = self._mqtt
        try:  # paho >= 2.0 requires an api-version argument
            return mqtt.Client(mqtt.CallbackAPIVersion.VERSION1, client_id=client_id)
        except (AttributeError, TypeError):
            return mqtt.Client(client_id=client_id)

    def _handle(self, client, userdata, msg) -> None:
        try:
            payload = pickle.loads(msg.payload)
        except Exception:
            payload = msg.payload  # non-pickle producer (foreign publisher)
        try:
            self.on_message(str(msg.topic), payload)
        except Exception:
            logger.exception("paho client on_message raised")

    def _ensure_connected(self) -> None:
        with self._lock:
            if self._connected:
                return
            self._client.connect(self.host, self.port, keepalive=self.keepalive)
            self._client.loop_start()
            self._connected = True
            # a reconnect (e.g. set_last_will re-arm) starts a clean session:
            # restore every tracked subscription or handlers silently go deaf
            for t in sorted(self._subs):
                self._client.subscribe(t)

    # -- BrokerClient surface ------------------------------------------------
    def subscribe(self, topic: str) -> None:
        self._subs.add(str(topic))
        self._ensure_connected()
        self._client.subscribe(str(topic))

    def unsubscribe(self, topic: str) -> None:
        self._subs.discard(str(topic))
        self._ensure_connected()
        self._client.unsubscribe(str(topic))

    def publish(self, topic: str, payload) -> None:
        self._ensure_connected()
        self._client.publish(
            str(topic), pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        )

    def set_last_will(self, topic: str, payload) -> None:
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            if self._connected:
                # paho cannot change the will mid-session: re-arm by
                # reconnecting with the will installed
                logger.warning("re-connecting to install last will on %s", topic)
                self._client.loop_stop()
                self._client.disconnect()
                self._connected = False
            self._client.will_set(str(topic), blob)

    def disconnect(self) -> None:
        with self._lock:
            if self._connected:
                self._client.loop_stop()
                self._client.disconnect()
                self._connected = False


class S3BlobStore:
    """boto3-backed blob store behind the BlobStore surface: ``s3://`` URLs,
    pickled pytrees (reference ``s3/remote_storage.py:42,63``)."""

    def __init__(self, root: str, boto3_module=None):
        b3 = boto3_module if boto3_module is not None else _boto3()
        if b3 is None:
            raise ImportError("boto3 is not installed")
        assert root.startswith("s3://"), root
        rest = root[len("s3://"):]
        self.bucket, _, self.prefix = rest.partition("/")
        self._s3 = b3.client("s3")

    def write_model(self, key: str, pytree: Any) -> str:
        import uuid

        from ..serialization import device_get_tree

        name = f"{self.prefix.rstrip('/')}/{key}-{uuid.uuid4().hex}.pkl".lstrip("/")
        blob = pickle.dumps(device_get_tree(pytree), protocol=pickle.HIGHEST_PROTOCOL)
        self._s3.put_object(Bucket=self.bucket, Key=name, Body=blob)
        return f"s3://{self.bucket}/{name}"

    def read_model(self, url: str) -> Any:
        assert url.startswith("s3://"), url
        bucket, _, key = url[len("s3://"):].partition("/")
        body = self._s3.get_object(Bucket=bucket, Key=key)["Body"].read()
        return pickle.loads(body)


# -- factories ---------------------------------------------------------------
def create_broker_client(host: str, port: int,
                         on_message: Callable[[str, object], None],
                         transport: Optional[str] = None,
                         client_id: str = "",
                         reconnect_retries: Optional[int] = None,
                         reconnect_base_s: Optional[float] = None):
    """One constructor for both transports.

    ``transport``: ``"paho"`` speaks real MQTT via paho-mqtt (raises if the
    library is missing); anything else — including the default — uses the
    in-repo broker client.  Selection is EXPLICIT config, never import
    availability: the host:port in a config points at a specific kind of
    broker, and silently switching wire protocols because paho-mqtt appeared
    in the environment would hang both sides against a LocalBroker.

    ``reconnect_retries``/``reconnect_base_s`` tune the in-repo client's
    auto-reconnect (paho manages its own reconnect in its network loop)."""
    if (transport or "").lower() == "paho":
        return PahoBrokerClient(host, port, on_message, client_id=client_id)
    kw = {}
    if reconnect_retries is not None:
        kw["reconnect_retries"] = int(reconnect_retries)
    if reconnect_base_s is not None:
        kw["reconnect_base_s"] = float(reconnect_base_s)
    return BrokerClient(host, port, on_message, **kw)


def create_blob_store(root: Optional[str] = None):
    """``s3://bucket/prefix`` + boto3 available -> S3; else file-backed."""
    if root and str(root).startswith("s3://"):
        return S3BlobStore(str(root))
    return BlobStore(root)
