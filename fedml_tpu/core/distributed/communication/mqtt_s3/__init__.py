from .broker import LocalBroker, BrokerClient
from .blob_store import BlobStore
from .mqtt_s3_comm_manager import MqttS3CommManager

__all__ = ["LocalBroker", "BrokerClient", "BlobStore", "MqttS3CommManager"]
