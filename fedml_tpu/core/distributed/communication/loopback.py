"""In-process loopback transport.

The reference has no fake transport — its unit layer is e2e smoke runs over
real MQTT/gRPC/MPI (SURVEY.md §4).  This backend is the native improvement: a
process-global hub of per-rank queues implementing the
:class:`BaseCommunicationManager` contract, so every server/client state
machine (cross-silo, cross-device, flow DSL) is unit-testable in one process
with zero sockets.  Semantics mirror the MPI backend's dedicated receive
thread + poll loop (reference ``mpi/com_manager.py:90-108``).
"""

from __future__ import annotations

import queue
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import logging

from .base_com_manager import BaseCommunicationManager, Observer
from .message import Message

logger = logging.getLogger(__name__)

_STOP = object()


class LoopbackHub:
    """Process-global registry of per-(channel, rank) mailboxes."""

    _lock = threading.Lock()
    _queues: Dict[Tuple[str, int], "queue.Queue"] = {}

    @classmethod
    def mailbox(cls, channel: str, rank: int) -> "queue.Queue":
        with cls._lock:
            key = (str(channel), int(rank))
            if key not in cls._queues:
                cls._queues[key] = queue.Queue()
            return cls._queues[key]

    @classmethod
    def reset(cls, channel: Optional[str] = None) -> None:
        with cls._lock:
            if channel is None:
                cls._queues.clear()
            else:
                for key in [k for k in cls._queues if k[0] == str(channel)]:
                    del cls._queues[key]

    @classmethod
    def sever(cls, channel: str, rank: int) -> None:
        """Kill one rank's mailbox: in-flight frames are lost and a rejoined
        incarnation gets a fresh queue (no stale ``_STOP`` sentinel from the
        dead one) — the loopback analog of a silo process crash."""
        with cls._lock:
            cls._queues.pop((str(channel), int(rank)), None)


class LoopbackCommManager(BaseCommunicationManager):
    """Queue-backed transport for rank ``rank`` of ``size`` nodes on ``channel``."""

    def __init__(self, channel: str = "0", rank: int = 0, size: int = 1):
        self.channel = str(channel)
        self.rank = int(rank)
        self.size = int(size)
        self._observers: List[Observer] = []
        self._inbox = LoopbackHub.mailbox(self.channel, self.rank)
        self._running = False

    # -- BaseCommunicationManager -------------------------------------------
    def send_message(self, msg: Message) -> None:
        receiver = int(msg.get_receiver_id())
        LoopbackHub.mailbox(self.channel, receiver).put(msg)

    def broadcast(self, msg: Message) -> None:
        for r in range(self.size):
            if r != self.rank:
                LoopbackHub.mailbox(self.channel, r).put(msg)

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        # Notify "connection ready" like the production transports do on
        # connect (reference mqtt_s3 manager CONNECTION_READY passthrough).
        ready = Message(type="connection_ready", sender_id=self.rank, receiver_id=self.rank)
        self._notify(ready)
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(item)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)

    # -- internals ----------------------------------------------------------
    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg)
            except Exception:
                # A handler error must not silently kill the receive loop —
                # surface it and keep serving (the reference's MPI poll loop
                # has the same silent-death failure mode; this is deliberate
                # hardening over it).
                logger.exception(
                    "rank %s: handler for msg_type=%r raised", self.rank, msg.get_type()
                )
