"""gRPC message-plane backend (DCN path for cross-silo FL).

Parity with the reference gRPC backend
(``core/distributed/communication/grpc/grpc_comm_manager.py:30-170``): each
rank runs its own gRPC server on ``base_port + rank``; ``send_message``
serializes the :class:`Message` and calls the receiver's ``sendMessage`` RPC,
resolving the receiver's host from an ip table (CSV file path or in-memory
dict); received messages land in a queue drained by a poll loop that notifies
observers.

Native deviations from the reference:

* No generated protobuf stubs — the wire format is a single
  ``unary_unary`` bytes RPC registered with a ``GenericRpcHandler``.  One
  fewer build step, identical semantics (the reference pickles the whole
  Message into ``CommRequest.message`` anyway).
* Tensor payloads are converted to host numpy before pickling
  (``jax.device_get``) so device buffers never hit the wire.
* The 1 GB message cap of the reference is kept (grpc options).
"""

from __future__ import annotations

import csv
import logging
import os
import pickle
import queue
import threading
import time
from typing import Dict, List, Optional

import grpc

from ..base_com_manager import BaseCommunicationManager, Observer
from ..message import Message
from ..serialization import device_get_tree

logger = logging.getLogger(__name__)

_SERVICE = "fedml.tpu.CommService"
_METHOD = "sendMessage"
_FULL_METHOD = f"/{_SERVICE}/{_METHOD}"

_GRPC_OPTIONS = [
    ("grpc.max_send_message_length", 1024 * 1024 * 1024),
    ("grpc.max_receive_message_length", 1024 * 1024 * 1024),
    ("grpc.enable_http_proxy", 0),
]

_STOP = object()


class _Servicer(grpc.GenericRpcHandler):
    """Pushes every inbound pickled Message into the owner's queue."""

    def __init__(self, inbox: "queue.Queue"):
        self._inbox = inbox
        self._handler = grpc.unary_unary_rpc_method_handler(
            self._send_message,
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )

    def _send_message(self, request: bytes, context) -> bytes:
        self._inbox.put(request)
        return b"ack"

    def service(self, handler_call_details):
        if handler_call_details.method == _FULL_METHOD:
            return self._handler
        return None


def _read_ip_table(path: str) -> Dict[int, str]:
    """CSV ``receiver_id,ip`` rows (reference ``_build_ip_table`` :167)."""
    table: Dict[int, str] = {}
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if not row or row[0].strip().lower() in ("receiver_id", "rank"):
                continue
            table[int(row[0])] = row[1].strip()
    return table


class GRPCCommManager(BaseCommunicationManager):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8890,
        ip_config: Optional[object] = None,
        client_id: int = 0,
        client_num: int = 0,
        base_port: int = 8890,
        send_retries: int = 30,
        send_backoff_base_s: float = 0.2,
        send_backoff_max_s: float = 0.5,
    ):
        self.host = host
        self.port = int(port)
        self.client_id = int(client_id)
        self.client_num = int(client_num)
        self.base_port = int(base_port)
        self.send_retries = int(send_retries)
        self.send_backoff_base_s = float(send_backoff_base_s)
        self.send_backoff_max_s = float(send_backoff_max_s)
        self.reconnect_count = 0  # channels dropped + redialed after RpcError
        self._rng = __import__("random").Random(f"grpc-backoff:{int(client_id)}")
        if ip_config is None:
            self.ip_table: Dict[int, str] = {}
        elif isinstance(ip_config, dict):
            self.ip_table = {int(k): str(v) for k, v in ip_config.items()}
        else:
            self.ip_table = _read_ip_table(str(ip_config))
        self._observers: List[Observer] = []
        self._inbox: "queue.Queue" = queue.Queue()
        self._running = False
        self._channels: Dict[str, grpc.Channel] = {}
        self._lock = threading.Lock()

        self._server = grpc.server(
            thread_pool=__import__("concurrent.futures", fromlist=["ThreadPoolExecutor"]).ThreadPoolExecutor(
                max_workers=max(4, client_num + 1)
            ),
            options=_GRPC_OPTIONS,
        )
        self._server.add_generic_rpc_handlers((_Servicer(self._inbox),))
        bind_addr = f"0.0.0.0:{self.port}"
        bound = self._server.add_insecure_port(bind_addr)
        if bound == 0:
            raise OSError(f"gRPC could not bind {bind_addr}")
        self._server.start()
        logger.info("grpc rank %s serving on %s", self.client_id, bind_addr)

    # -- addressing ---------------------------------------------------------
    def _addr_of(self, receiver_id: int) -> str:
        ip = self.ip_table.get(int(receiver_id), "127.0.0.1")
        return f"{ip}:{self.base_port + int(receiver_id)}"

    def _channel(self, addr: str) -> grpc.Channel:
        with self._lock:
            ch = self._channels.get(addr)
            if ch is None:
                ch = grpc.insecure_channel(addr, options=_GRPC_OPTIONS)
                self._channels[addr] = ch
            return ch

    def _drop_channel(self, addr: str) -> None:
        """A failed RPC may mean a dead cached channel (peer restarted):
        close and forget it so the next attempt dials fresh."""
        with self._lock:
            ch = self._channels.pop(addr, None)
        if ch is not None:
            try:
                ch.close()
            except Exception:
                pass
            self.reconnect_count += 1

    # -- BaseCommunicationManager -------------------------------------------
    def send_message(self, msg: Message) -> None:
        payload = pickle.dumps(device_get_tree(msg.get_params()), protocol=pickle.HIGHEST_PROTOCOL)
        addr = self._addr_of(msg.get_receiver_id())
        t0 = time.time()
        for attempt in range(self.send_retries):
            stub = self._channel(addr).unary_unary(_FULL_METHOD)
            try:
                stub(payload, timeout=60.0)
                break
            except grpc.RpcError:  # receiver not up yet, or stale channel
                self._drop_channel(addr)
                if attempt == self.send_retries - 1:
                    raise
                backoff = min(self.send_backoff_base_s * (2 ** attempt),
                              self.send_backoff_max_s)
                time.sleep(backoff * (1.0 + 0.25 * self._rng.random()))
        logger.debug(
            "grpc rank %s -> %s (%s) %.1f KB in %.3fs",
            self.client_id, msg.get_receiver_id(), msg.get_type(),
            len(payload) / 1024, time.time() - t0,
        )

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        ready = Message(type="connection_ready", sender_id=self.client_id, receiver_id=self.client_id)
        self._notify_message(ready)
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            msg = Message()
            msg.init(pickle.loads(item))
            self._notify_message(msg)
        self._server.stop(grace=None)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)

    # -- internals ----------------------------------------------------------
    def _notify_message(self, msg: Message) -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg)
            except Exception:
                logger.exception(
                    "grpc rank %s: handler for msg_type=%r raised", self.client_id, msg.get_type()
                )
