"""Typed key-value message envelope.

Contract parity with the reference ``core/distributed/communication/message.py:5-83``:
``msg_type`` / ``sender`` / ``receiver`` header keys plus an open params dict
carrying ``model_params`` (an in-memory pytree) or ``model_params_url`` (a blob
reference for the control/data-split transports).  JSON serialization excludes
tensor payloads; binary transports pickle the whole params dict instead.
"""

from __future__ import annotations

import json
from typing import Any, Dict


class Message:
    MSG_ARG_KEY_OPERATION = "operation"
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"
    # reliability header (additive wire change): a per-incarnation monotonic
    # id "rank:nonce:seq" stamped by the node runtime; receivers ack by id and
    # drop re-deliveries, making retries and duplicate faults idempotent.
    # Clients that omit it (legacy Java/Swift wire) are never acked or deduped.
    MSG_ARG_KEY_MSG_ID = "msg_id"
    # tracing header (additive, opt-in via obs_trace): a W3C-style
    # "00-<trace>-<span>-01" string stamped by core.obs.inject; a plain
    # string survives both the JSON control plane and the pickled binary
    # transports, so one header propagates span context on all backends.
    # Peers that omit it simply start parentless spans.
    MSG_ARG_KEY_TRACEPARENT = "traceparent"

    MSG_OPERATION_SEND = "send"
    MSG_OPERATION_RECEIVE = "receive"
    MSG_OPERATION_BROADCAST = "broadcast"
    MSG_OPERATION_REDUCE = "reduce"

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url"

    def __init__(self, type: str = "default", sender_id: int = 0, receiver_id: int = 0):
        self.type = str(type)
        self.sender_id = sender_id
        self.receiver_id = receiver_id
        self.msg_params: Dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: str(type),
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # -- construction -------------------------------------------------------
    def init(self, msg_params: Dict[str, Any]) -> None:
        self.msg_params = msg_params
        self._sync_header()

    def init_from_json_string(self, json_string: str) -> None:
        self.init(json.loads(json_string))

    def init_from_json_object(self, json_object: Dict[str, Any]) -> None:
        self.init(json_object)

    def _sync_header(self) -> None:
        self.type = str(self.msg_params.get(Message.MSG_ARG_KEY_TYPE, self.type))
        self.sender_id = self.msg_params.get(Message.MSG_ARG_KEY_SENDER, self.sender_id)
        self.receiver_id = self.msg_params.get(Message.MSG_ARG_KEY_RECEIVER, self.receiver_id)

    # -- accessors ----------------------------------------------------------
    def get_sender_id(self) -> int:
        return self.sender_id

    def get_receiver_id(self) -> int:
        return self.receiver_id

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    add = add_params

    def get_params(self) -> Dict[str, Any]:
        return self.msg_params

    def get(self, key: str, default: Any = None) -> Any:
        value = self.msg_params.get(key, default)
        # duck-typed unwrap of serialization.CachedPayload (imported lazily
        # by name to avoid a cycle): loopback passes the wrapper by
        # reference, so the in-process receiver unwraps here; wire backends
        # already unwrapped via pickle __reduce__
        unwrap = getattr(value, "__fedml_unwrap__", None)
        if unwrap is not None:
            return unwrap()
        return value

    def get_type(self) -> str:
        return str(self.msg_params[Message.MSG_ARG_KEY_TYPE])

    # -- serialization ------------------------------------------------------
    def to_json(self) -> str:
        """JSON for control-plane transports; tensor payloads must ride the
        data plane (cf. reference MQTT+S3 split, SURVEY.md §2.2)."""
        safe = {}
        for k, v in self.msg_params.items():
            try:
                json.dumps(v)
            except (TypeError, ValueError):
                continue
            safe[k] = v
        return json.dumps(safe)

    def get_content(self) -> str:
        return f"{self.get_type()}: {self.msg_params}"

    def __repr__(self) -> str:  # pragma: no cover
        keys = list(self.msg_params.keys())
        return (
            f"Message(type={self.type!r}, sender={self.sender_id}, "
            f"receiver={self.receiver_id}, keys={keys})"
        )
