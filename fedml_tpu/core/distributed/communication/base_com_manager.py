"""Transport interface + observer callback.

Parity with reference ``core/distributed/communication/base_com_manager.py:7-26``
and ``observer.py``.  Every backend (loopback / gRPC / MQTT-emu / ...) implements
``BaseCommunicationManager``; node runtimes register an ``Observer`` whose
``receive_message`` is invoked on the receive loop's thread.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from .message import Message


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type: str, msg_params: Message) -> None:
        ...


class BaseCommunicationManager(ABC):
    @abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    @abstractmethod
    def add_observer(self, observer: Observer) -> None:
        ...

    @abstractmethod
    def remove_observer(self, observer: Observer) -> None:
        ...

    @abstractmethod
    def handle_receive_message(self) -> None:
        """Enter the receive loop (blocks until stopped)."""

    @abstractmethod
    def stop_receive_message(self) -> None:
        ...
