"""Server aggregator ABC with security/DP hooks.

Parity with reference ``core/alg_frame/server_aggregator.py:11-67``:
``on_before_aggregation`` runs attacker injection (Byzantine simulation) and
defense filtering; ``aggregate`` delegates to the defender (if active) or the
pytree :class:`FedMLAggOperator`; ``on_after_aggregation`` adds CENTRAL DP
noise when enabled.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List, Tuple

from ..aggregate import FedMLAggOperator, ServerRoundUpdater, server_state_mode


class ServerAggregator(ABC):
    def __init__(self, model: Any, args: Any):
        self.model = model
        self.id = 0
        self.args = args
        # sharded server state: the round updater owns the resident
        # model-sharded params + optimizer state (built lazily; replicated
        # runs never construct the plane)
        self.round_updater = (ServerRoundUpdater(args)
                              if server_state_mode(args) == "sharded"
                              else None)

    def set_id(self, aggregator_id: int) -> None:
        self.id = aggregator_id

    def is_main_process(self) -> bool:
        return True

    @abstractmethod
    def get_model_params(self) -> Any:
        ...

    @abstractmethod
    def set_model_params(self, model_parameters: Any) -> None:
        ...

    def on_before_aggregation(
        self, raw_client_model_or_grad_list: List[Tuple[float, Any]]
    ) -> List[Tuple[float, Any]]:
        from ..security.fedml_attacker import FedMLAttacker
        from ..security.fedml_defender import FedMLDefender

        attacker = FedMLAttacker.get_instance()
        if attacker.is_model_attack():
            raw_client_model_or_grad_list = attacker.attack_model(
                raw_client_grad_list=raw_client_model_or_grad_list,
                extra_auxiliary_info=self.get_model_params(),
            )
        defender = FedMLDefender.get_instance()
        if defender.is_defense_enabled() and not self._plane_has_defense():
            raw_client_model_or_grad_list = defender.defend_before_aggregation(
                raw_client_grad_list=raw_client_model_or_grad_list,
                extra_auxiliary_info=self.get_model_params(),
            )
        return raw_client_model_or_grad_list

    def _plane_has_defense(self) -> bool:
        """True when the sharded round plane carries the compiled defense
        stage (``defense_plane=compiled``): the host defender hooks step
        aside, or the defense would apply twice.  Resolved from args (not
        the plane object) so the check never forces the lazy plane build."""
        if self.round_updater is None:
            return False
        from ...parallel.sec_plane import defense_spec, stage_plane
        return (stage_plane(self.args, "defense_plane") == "compiled"
                and defense_spec(self.args) is not None)

    def _plane_has_dp(self) -> bool:
        if self.round_updater is None:
            return False
        from ...parallel.sec_plane import dp_spec, stage_plane
        return (stage_plane(self.args, "dp_plane") == "compiled"
                and dp_spec(self.args) is not None)

    def aggregate(self, raw_client_model_or_grad_list: List[Tuple[float, Any]]) -> Any:
        from ..security.fedml_defender import FedMLDefender

        defender = FedMLDefender.get_instance()
        if defender.is_defense_enabled() and not self._plane_has_defense():
            # host-plane defended rounds stay on the replicated path: the
            # defender's base_aggregation_func contract is plain
            # aggregation, not the stateful server-optimizer round tail
            return defender.defend_on_aggregation(
                raw_client_grad_list=raw_client_model_or_grad_list,
                base_aggregation_func=FedMLAggOperator.agg,
                extra_auxiliary_info=self.get_model_params(),
            )
        if self.round_updater is not None:
            return self.round_updater.round_update(
                self.get_model_params(), raw_client_model_or_grad_list)
        return FedMLAggOperator.agg(self.args, raw_client_model_or_grad_list)

    def on_after_aggregation(self, aggregated_model_or_grad: Any) -> Any:
        from ..dp.fedml_differential_privacy import FedMLDifferentialPrivacy
        from ..security.fedml_defender import FedMLDefender

        defender = FedMLDefender.get_instance()
        if defender.is_defense_enabled() and not self._plane_has_defense():
            aggregated_model_or_grad = defender.defend_after_aggregation(aggregated_model_or_grad)
        dp = FedMLDifferentialPrivacy.get_instance()
        if dp.is_global_dp_enabled() and not self._plane_has_dp():
            aggregated_model_or_grad = dp.add_global_noise(aggregated_model_or_grad)
        return aggregated_model_or_grad

    @abstractmethod
    def test(self, test_data, device, args) -> Any:
        ...

    def test_all(self, train_data_local_dict, test_data_local_dict, device, args) -> bool:
        return True
