"""Engine-agnostic client trainer ABC.

Parity with reference ``core/alg_frame/client_trainer.py:6-45``: stateless
operator with ``get/set_model_params`` + ``train`` and before/after hooks; the
after-hook applies local DP noise when enabled.  In this framework the model
parameters are a JAX pytree and concrete trainers are thin shells over pure
jitted train functions (see fedml_tpu/ml/trainer/).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any


class ClientTrainer(ABC):
    def __init__(self, model: Any, args: Any):
        self.model = model
        self.id = 0
        self.args = args
        self.local_train_dataset = None
        self.local_test_dataset = None
        self.local_sample_number = 0
        self.rng = None

    def set_id(self, trainer_id: int) -> None:
        self.id = trainer_id

    def is_main_process(self) -> bool:
        return True

    @abstractmethod
    def get_model_params(self) -> Any:
        ...

    @abstractmethod
    def set_model_params(self, model_parameters: Any) -> None:
        ...

    def update_dataset(self, local_train_dataset, local_test_dataset, local_sample_number) -> None:
        self.local_train_dataset = local_train_dataset
        self.local_test_dataset = local_test_dataset
        self.local_sample_number = local_sample_number

    def on_before_local_training(self, train_data, device, args) -> None:
        """Hook: runs before local epochs (reference :34-36)."""

    @abstractmethod
    def train(self, train_data, device, args) -> Any:
        ...

    def on_after_local_training(self, train_data, device, args) -> None:
        """Hook: applies LOCAL DP noise when enabled (reference :38-42)."""
        from ..dp.fedml_differential_privacy import FedMLDifferentialPrivacy

        dp = FedMLDifferentialPrivacy.get_instance()
        if dp.is_local_dp_enabled():
            self.set_model_params(dp.add_local_noise(self.get_model_params()))

    def test(self, test_data, device, args) -> Any:
        return None
