"""Open parameter bag used by the flow DSL and trainer/aggregator hooks.

Parity with reference ``core/alg_frame/params.py``: attribute- and key-style
access over one dict.
"""

from __future__ import annotations

from typing import Any, Dict


class Params:
    KEY_MODEL_PARAMS = "model_params"

    def __init__(self, **kwargs: Any):
        self.__dict__["_store"]: Dict[str, Any] = dict(kwargs)

    def add(self, name: str, value: Any) -> "Params":
        self._store[name] = value
        return self

    def get(self, name: str, default: Any = None) -> Any:
        return self._store.get(name, default)

    def __getattr__(self, name: str) -> Any:
        try:
            return self.__dict__["_store"][name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self._store[name] = value

    def __contains__(self, name: str) -> bool:
        return name in self._store

    def keys(self):
        return self._store.keys()

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._store)
