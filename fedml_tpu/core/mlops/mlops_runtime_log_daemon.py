"""Log-shipping daemon: tail the run log file and push chunks to a sink.

Parity with reference ``core/mlops/mlops_runtime_log_daemon.py:14,276``
(``MLOpsRuntimeLogProcessor`` tailing the log file and POSTing chunks to the
platform log server): same tail/chunk/ship loop, with the HTTP POST replaced
by the pluggable sink bus (offline-first; a broker sink gives live remote
tailing)."""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from .sinks import FanoutSink


class MLOpsRuntimeLogDaemon:
    def __init__(
        self,
        log_path: str,
        sink: Optional[FanoutSink] = None,
        run_id: str = "0",
        rank: int = 0,
        chunk_lines: int = 100,
        poll_interval_s: float = 1.0,
    ):
        self.log_path = log_path
        self.sink = sink if sink is not None else FanoutSink()
        self.run_id = str(run_id)
        self.rank = int(rank)
        self.chunk_lines = int(chunk_lines)
        self.poll_interval_s = float(poll_interval_s)
        self.lines_shipped = 0
        self._offset = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MLOpsRuntimeLogDaemon":
        # owned-by: main — start/stop latch; the shipping loop only reads
        self._running = True  # owned-by: main
        self._thread = threading.Thread(target=self._loop, daemon=True, name="mlops-log-daemon")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush()

    def flush(self) -> None:
        for chunk in iter(self._read_chunk, None):
            self._ship(chunk)

    def _loop(self) -> None:
        while self._running:
            chunk = self._read_chunk()
            if chunk:
                self._ship(chunk)
            else:
                time.sleep(self.poll_interval_s)

    def _read_chunk(self) -> Optional[List[str]]:
        if not os.path.exists(self.log_path):
            return None
        with open(self.log_path, "r", errors="replace") as f:
            f.seek(self._offset)
            lines: List[str] = []
            while len(lines) < self.chunk_lines:
                pos = f.tell()
                line = f.readline()
                if not line or not line.endswith("\n"):
                    # Partial line: rewind to before it so the next poll
                    # re-reads the whole line once the writer finishes it
                    # (f.tell() here is already past the partial bytes).
                    f.seek(pos)
                    break
                lines.append(line.rstrip("\n"))
            self._offset = f.tell()
        return lines or None

    def _ship(self, lines: List[str]) -> None:
        self.sink.emit(
            "log_chunk",
            {
                "run_id": self.run_id,
                "rank": self.rank,
                "first_line": self.lines_shipped,
                "lines": lines,
            },
        )
        self.lines_shipped += len(lines)
