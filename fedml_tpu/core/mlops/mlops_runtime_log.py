"""Structured run logging: prefix every record with run/role/rank.

Parity with reference ``core/mlops/mlops_runtime_log.py`` (``MLOpsRuntimeLog``
formatter + excepthook install); writes to stderr and, when
``tracking_args.log_file_dir`` is set, to ``fedml_run_<run_id>_<rank>.log``
— the file the log daemon tails."""

from __future__ import annotations

import logging
import os
import sys
from typing import Any, Optional


class MLOpsFormatter(logging.Formatter):
    def __init__(self, run_id: str = "0", rank: int = 0, role: str = "client"):
        super().__init__(
            fmt="[FedML-{role} run:{run} rank:{rank}] %(asctime)s "
            "[%(levelname)s] [%(filename)s:%(lineno)d] %(message)s".format(
                role=role, run=run_id, rank=rank
            )
        )


class MLOpsRuntimeLog:
    _instance: Optional["MLOpsRuntimeLog"] = None

    def __init__(self, args: Any = None):
        self.args = args
        self.run_id = str(getattr(args, "run_id", "0"))
        self.rank = int(getattr(args, "rank", 0) or 0)
        self.role = str(getattr(args, "role", "client"))
        self.log_path: Optional[str] = None

    @classmethod
    def get_instance(cls, args: Any = None) -> "MLOpsRuntimeLog":
        if cls._instance is None:
            cls._instance = cls(args)
        return cls._instance

    def init_logs(self, level: int = logging.INFO) -> None:
        fmt = MLOpsFormatter(self.run_id, self.rank, self.role)
        root = logging.getLogger()
        root.setLevel(level)
        stream = logging.StreamHandler(sys.stderr)
        stream.setFormatter(fmt)
        root.addHandler(stream)
        log_dir = getattr(self.args, "log_file_dir", None)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self.log_path = os.path.join(
                log_dir, f"fedml_run_{self.run_id}_{self.rank}.log"
            )
            fh = logging.FileHandler(self.log_path)
            fh.setFormatter(fmt)
            root.addHandler(fh)
        sys.excepthook = self._excepthook

    @staticmethod
    def _excepthook(exc_type, exc_value, exc_tb) -> None:
        if issubclass(exc_type, KeyboardInterrupt):
            sys.__excepthook__(exc_type, exc_value, exc_tb)
            return
        logging.getLogger().critical(
            "uncaught exception", exc_info=(exc_type, exc_value, exc_tb)
        )
