"""MLOps facade: the ``fedml_tpu.core.mlops`` one-stop API.

Parity with the reference's 834-line facade ``core/mlops/__init__.py``
(``event`` :134, ``log`` :152, ``log_round_info`` :410, status reporters,
``log_sys_perf`` :400): module-level functions backed by a process-global
context configured by ``init(args)``.  Everything is a no-op until
``init`` runs, so library code can call these unconditionally (same
contract as the reference's ``using_mlops`` gating)."""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from .mlops_metrics import MLOpsMetrics
from .mlops_profiler_event import MLOpsProfilerEvent
from .mlops_runtime_log import MLOpsRuntimeLog
from .mlops_runtime_log_daemon import MLOpsRuntimeLogDaemon
from .mlops_status import ClientStatus, MLOpsStatus, ServerStatus
from .sinks import BrokerSink, FanoutSink, InMemorySink, JsonlFileSink, WandbSink
from .system_stats import SysStats

__all__ = [
    "MLOpsMetrics", "MLOpsProfilerEvent", "MLOpsRuntimeLog",
    "MLOpsRuntimeLogDaemon", "MLOpsStatus", "ClientStatus", "ServerStatus",
    "SysStats", "FanoutSink", "InMemorySink", "JsonlFileSink", "BrokerSink",
    "WandbSink",
    "pre_setup", "init", "finish", "event", "log", "log_round_info",
    "log_training_status", "log_aggregation_status", "log_sys_perf",
    "log_aggregated_model_info", "log_client_model_info", "log_comm_stats",
    "log_cohort_stats", "enabled", "sink",
]

_lock = threading.Lock()
_ctx: Dict[str, Any] = {"enabled": False}


def enabled() -> bool:
    return bool(_ctx.get("enabled"))


def sink() -> Optional[FanoutSink]:
    return _ctx.get("sink")


def pre_setup(args: Any) -> None:
    """Stage args before transports exist (mirrors reference pre_setup)."""
    _ctx["args"] = args


def init(args: Any, sink_obj: Optional[FanoutSink] = None) -> None:
    """Enable the bus. Sinks: always JSONL under ``log_file_dir`` (when set);
    a broker sink when ``args.mlops_broker_host/port`` are set; plus any
    caller-provided sink (tests use InMemorySink)."""
    with _lock:
        old = _ctx.get("sink")
        if old is not None:  # re-entrant init: release the previous fan's
            try:  # file handle / broker connection before replacing it
                old.close()
            except Exception:
                pass
        run_id = str(getattr(args, "run_id", "0"))
        edge_id = int(getattr(args, "rank", 0) or 0)
        fan = sink_obj if sink_obj is not None else FanoutSink()
        log_dir = getattr(args, "log_file_dir", None)
        if log_dir:
            fan.add(JsonlFileSink(os.path.join(log_dir, f"mlops_{run_id}_{edge_id}.jsonl")))
        host = getattr(args, "mlops_broker_host", None)
        port = getattr(args, "mlops_broker_port", None)
        if host and port:
            fan.add(BrokerSink(host, int(port), run_id))
        if getattr(args, "enable_wandb", False):
            # never a silent dead flag: either the sink attaches or the
            # operator is told exactly why their wandb dashboards are empty
            try:
                fan.add(WandbSink(args))
            except Exception as e:
                import logging

                logging.getLogger(__name__).warning(
                    "enable_wandb is set but the wandb sink could not start "
                    "(%s): metrics go to the local sinks only — install the "
                    "'wandb' package (WANDB_MODE=offline works without "
                    "egress) to activate this leg", e,
                )
        _ctx.update(
            enabled=True,
            args=args,
            run_id=run_id,
            edge_id=edge_id,
            sink=fan,
            metrics=MLOpsMetrics(run_id, edge_id, fan),
            profiler=MLOpsProfilerEvent(run_id, edge_id, fan),
            log_daemon=None,
        )
    if getattr(args, "obs_trace", False):
        # the obs layer rides the same sink fan; opt-in so the disabled
        # wire/flow stays bit-identical to the pre-obs framework
        from .. import obs

        obs.configure(args, fan.emit)


def start_log_daemon(log_path: str) -> Optional[MLOpsRuntimeLogDaemon]:
    if not enabled():
        return None
    daemon = MLOpsRuntimeLogDaemon(
        log_path, _ctx["sink"], _ctx["run_id"], _ctx["edge_id"]
    ).start()
    _ctx["log_daemon"] = daemon
    return daemon


def finish() -> None:
    from .. import obs

    if obs.enabled():
        obs.shutdown()  # final metrics flush rides the fan before it closes
    with _lock:
        daemon = _ctx.get("log_daemon")
        if daemon is not None:
            daemon.stop()
        fan = _ctx.get("sink")
        if fan is not None:
            fan.close()
        MLOpsStatus.get_instance().reset()  # terminal states must not leak into the next run
        _ctx.clear()
        _ctx["enabled"] = False


# -- facade calls (no-ops until init) --------------------------------------

def event(event_name: str, event_started: bool = True, event_value: Any = None) -> None:
    if not enabled():
        return
    prof: MLOpsProfilerEvent = _ctx["profiler"]
    if event_started:
        prof.log_event_started(event_name, event_value)
    else:
        prof.log_event_ended(event_name, event_value)


def log(metrics: Dict[str, Any]) -> None:
    if not enabled():
        return
    _ctx["metrics"].report_train_metrics(metrics)


def log_round_info(total_rounds: int, round_idx: int) -> None:
    if not enabled():
        return
    _ctx["metrics"].report_round_info(total_rounds, round_idx)


def log_training_status(status: str, edge_id: Optional[int] = None) -> None:
    if not enabled():
        return
    _ctx["metrics"].report_client_training_status(
        edge_id if edge_id is not None else _ctx["edge_id"], status
    )


def log_aggregation_status(status: str) -> None:
    if not enabled():
        return
    _ctx["metrics"].report_server_training_status(status)


def log_comm_stats(stats: Dict[str, Any], rank: Optional[int] = None) -> None:
    """Transport reliability counters (retries, retransmits, dup_dropped,
    reconnects, rejoins, fault injections) — emitted by every node runtime's
    ``finish()`` so chaos runs are observable, not just green."""
    if not enabled():
        return
    _ctx["metrics"].report_comm_stats(stats, rank=rank)


def log_cohort_stats(stats: Dict[str, Any], rank: Optional[int] = None) -> None:
    """Per-round population counters (invited, reported, rejected-late,
    strata sizes) — emitted by ``core/population`` at every round close so
    pacing behavior is observable alongside ``comm_stats``."""
    if not enabled():
        return
    _ctx["metrics"].report_cohort_stats(stats, rank=rank)


def log_sys_perf(stats: Optional[Dict[str, Any]] = None) -> None:
    if not enabled():
        return
    _ctx["metrics"].report_sys_perf(stats)


def log_aggregated_model_info(round_idx: int, model_url: str) -> None:
    if not enabled():
        return
    _ctx["metrics"].report_aggregated_model_info(round_idx, model_url)


def log_client_model_info(round_idx: int, model_url: str) -> None:
    if not enabled():
        return
    _ctx["metrics"].report_client_model_info(round_idx, model_url)
