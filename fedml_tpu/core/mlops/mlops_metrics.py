"""Run telemetry reporter: training/aggregation status, round progress,
model artifacts, metrics.

Parity with reference ``core/mlops/mlops_metrics.py`` (``MLOpsMetrics``
publishing to platform MQTT topics): same report surface, records routed to
the configured sinks and mirrored into the status FSM."""

from __future__ import annotations

from typing import Any, Dict, Optional

from .mlops_status import MLOpsStatus
from .sinks import FanoutSink


class MLOpsMetrics:
    def __init__(self, run_id: str = "0", edge_id: int = 0, sink: Optional[FanoutSink] = None):
        self.run_id = str(run_id)
        self.edge_id = int(edge_id)
        self.sink = sink if sink is not None else FanoutSink()

    def _emit(self, topic: str, payload: Dict[str, Any]) -> None:
        self.sink.emit(topic, {"run_id": self.run_id, "edge_id": self.edge_id, **payload})

    # -- status ------------------------------------------------------------
    def report_client_training_status(self, edge_id: int, status: str) -> None:
        MLOpsStatus.get_instance().set_client_status(edge_id, status)
        self._emit("client_status", {"edge_id": edge_id, "status": status})

    def report_server_training_status(self, status: str) -> None:
        MLOpsStatus.get_instance().set_server_status(self.edge_id, status)
        self._emit("server_status", {"status": status})

    # -- round progress ----------------------------------------------------
    def report_round_info(self, total_rounds: int, round_idx: int) -> None:
        self._emit("round_info", {"total_rounds": total_rounds, "round_idx": round_idx})

    # -- metrics -----------------------------------------------------------
    def report_train_metrics(self, metrics: Dict[str, Any]) -> None:
        self._emit("train_metric", dict(metrics))

    def report_aggregation_metrics(self, metrics: Dict[str, Any]) -> None:
        self._emit("agg_metric", dict(metrics))

    # -- artifacts ---------------------------------------------------------
    def report_aggregated_model_info(self, round_idx: int, model_url: str) -> None:
        self._emit("aggregated_model", {"round_idx": round_idx, "model_url": model_url})

    def report_client_model_info(self, round_idx: int, model_url: str) -> None:
        self._emit("client_model", {"round_idx": round_idx, "model_url": model_url})

    # -- transport reliability ---------------------------------------------
    def report_comm_stats(self, stats: Dict[str, Any], rank: Optional[int] = None) -> None:
        """Retry/retransmit/dedup/reconnect/rejoin counters from the node
        runtime's reliability layer — what makes a chaos run observable
        rather than just green."""
        self._emit("comm_stats", {"rank": self.edge_id if rank is None else int(rank),
                                  **dict(stats)})

    # -- population --------------------------------------------------------
    def report_cohort_stats(self, stats: Dict[str, Any], rank: Optional[int] = None) -> None:
        """Per-round cohort counters from ``core/population`` (invited,
        reported, rejected-late, strata sizes, close reason) — the
        selection/pacing analogue of ``comm_stats``."""
        self._emit("cohort_stats", {"rank": self.edge_id if rank is None else int(rank),
                                    **dict(stats)})

    # -- system ------------------------------------------------------------
    def report_sys_perf(self, stats: Optional[Dict[str, Any]] = None) -> None:
        if stats is None:
            from .system_stats import SysStats

            stats = SysStats().produce_info()
        self._emit("sys_perf", stats)
