"""Host + accelerator utilization snapshots.

Parity with reference ``core/mlops/system_stats.py`` (``SysStats`` via
psutil/gpustat): CPU, memory, disk, network, process stats — plus the TPU
twist: per-device HBM usage from ``jax`` memory stats instead of gpustat."""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List

try:
    import psutil  # optional in this image
except ImportError:  # pragma: no cover
    psutil = None


def _proc_meminfo() -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, v = line.split(":", 1)
                out[k.strip()] = int(v.strip().split()[0]) * 1024
    except OSError:
        pass
    return out


class SysStats:
    def __init__(self, process_id: int = None):
        self.process_id = process_id if process_id is not None else os.getpid()
        self._proc = psutil.Process(self.process_id) if psutil else None

    def produce_info(self) -> Dict[str, Any]:
        info: Dict[str, Any] = {"ts": round(time.time(), 3), "pid": self.process_id}
        if psutil:
            vm = psutil.virtual_memory()
            info.update(
                cpu_utilization=psutil.cpu_percent(interval=None),
                system_memory_total=vm.total,
                system_memory_used=vm.used,
                system_memory_utilization=vm.percent,
                process_memory_in_use=self._proc.memory_info().rss,
                process_cpu_threads_in_use=self._proc.num_threads(),
            )
            try:
                du = psutil.disk_usage("/")
                info.update(disk_utilization=du.percent)
            except OSError:
                pass
        else:  # /proc fallback keeps the schema populated without psutil
            mi = _proc_meminfo()
            total = mi.get("MemTotal", 0)
            avail = mi.get("MemAvailable", 0)
            info.update(
                system_memory_total=total,
                system_memory_used=max(total - avail, 0),
                system_memory_utilization=round(100.0 * (total - avail) / total, 2) if total else 0.0,
                cpu_utilization=_loadavg_percent(),
            )
        info["devices"] = self.device_stats()
        return info

    @staticmethod
    def device_stats() -> List[Dict[str, Any]]:
        """Per-accelerator HBM stats (jax memory_stats; empty on CPU)."""
        out: List[Dict[str, Any]] = []
        try:
            import jax

            for d in jax.devices():
                ms = d.memory_stats() if hasattr(d, "memory_stats") else None
                if ms:
                    out.append(
                        {
                            "device": str(d),
                            "bytes_in_use": ms.get("bytes_in_use", 0),
                            "bytes_limit": ms.get("bytes_limit", 0),
                            "peak_bytes_in_use": ms.get("peak_bytes_in_use", 0),
                        }
                    )
        except Exception:  # pragma: no cover - no jax / no backend
            pass
        return out


def _loadavg_percent() -> float:
    try:
        return round(100.0 * os.getloadavg()[0] / max(os.cpu_count() or 1, 1), 2)
    except OSError:  # pragma: no cover
        return 0.0
