"""Loopback MLOps platform server — proves the hosted-platform wire protocol.

The reference's daemons speak two HTTP endpoints to the hosted MLOps
platform: a config-fetch RPC that hands devices their transport credentials
(``core/mlops/mlops_configs.py`` — POST ``/fedmlOpsServer/configs/fetch``
with ``{"config_name": [...]}``) and a log-upload RPC the runtime log
processor batches into (``mlops_runtime_log_daemon.py:276-346`` — POST
``/fedmlLogsServer/logs/update``).  The hosted platform is unreachable in a
zero-egress build, so this module ships a localhost fake implementing both
endpoints — the same role the fake-device harness plays for the Beehive
cross-device stack: the PROTOCOL is tested, the hosted peer is swapped in by
changing one URL.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional


class MLOpsPlatformFake:
    """``MLOpsPlatformFake().start()`` -> ``.url``; records every upload."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 mqtt_port: int = 1883, s3_root: str = ""):
        self.host, self.port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.log_uploads: List[Dict[str, Any]] = []
        self.config_fetches: List[List[str]] = []
        self.projects: List[Dict[str, Any]] = []   # createSim registrations
        self.runs: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        # what the fetch endpoint hands out (reference: MQTT + S3 credentials
        # and the log-server address)
        self.configs: Dict[str, Any] = {
            "mqtt_config": {"BROKER_HOST": host, "BROKER_PORT": int(mqtt_port),
                            "MQTT_USER": "fedml", "MQTT_PWD": "", "MQTT_KEEPALIVE": 180},
            "s3_config": {"BUCKET_NAME": s3_root or "fedml-local",
                          "CN_S3_AKI": "", "CN_S3_SAK": "", "CN_REGION_NAME": "local"},
            "ml_ops_config": {},  # LOG_SERVER_URL filled in start()
            "docker_config": {},
        }

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MLOpsPlatformFake":
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj: Dict[str, Any]) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    return self._json(400, {"code": "FAILED", "message": "bad json"})
                if self.path == "/fedmlOpsServer/configs/fetch":
                    names = list(req.get("config_name", []))
                    with fake._lock:
                        fake.config_fetches.append(names)
                    data = {k: fake.configs[k] for k in names if k in fake.configs}
                    return self._json(200, {"code": "SUCCESS", "data": data})
                if self.path == "/fedmlLogsServer/logs/update":
                    with fake._lock:
                        fake.log_uploads.append(req)
                    return self._json(200, {"code": "SUCCESS"})
                if self.path == "/fedmlOpsServer/projects/createSim":
                    # simulation project registration (reference
                    # core/mlops/__init__.py:440): echo back a project id
                    with fake._lock:
                        fake.projects.append(req)
                        pid = len(fake.projects)
                    return self._json(200, {"code": "SUCCESS", "data": pid})
                if self.path == "/fedmlOpsServer/runs/createSim":
                    # simulation run registration (reference :469)
                    with fake._lock:
                        fake.runs.append(req)
                        rid = len(fake.runs)
                    return self._json(200, {"code": "SUCCESS", "data": rid})
                return self._json(404, {"code": "FAILED", "message": "unknown path"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self.configs["ml_ops_config"]["LOG_SERVER_URL"] = (
            f"{self.url}/fedmlLogsServer/logs/update"
        )
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="mlops-platform-fake"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    def logs_for_run(self, run_id) -> List[str]:
        with self._lock:
            out: List[str] = []
            for up in self.log_uploads:
                if str(up.get("run_id")) == str(run_id):
                    out.extend(up.get("logs", []))
            return out
