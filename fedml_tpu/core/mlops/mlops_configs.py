"""Platform config-fetch client (the device side of the MLOps wire protocol).

Parity with reference ``core/mlops/mlops_configs.py`` (``MLOpsConfigs``):
devices bootstrap by POSTing ``{"config_name": [...]}`` to the platform's
``/fedmlOpsServer/configs/fetch`` and receive their transport credentials
(MQTT broker, S3 bucket, log-server URL).  stdlib urllib only (zero extra
deps); point ``url`` at :class:`.platform_fake.MLOpsPlatformFake` locally or
at the hosted platform in production.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Dict, List, Optional, Sequence


class MLOpsConfigs:
    FETCH_PATH = "/fedmlOpsServer/configs/fetch"
    ALL = ("mqtt_config", "s3_config", "ml_ops_config", "docker_config")

    def __init__(self, url: str, timeout_s: float = 10.0):
        self.base_url = url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def _post(self, path: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        req = urllib.request.Request(
            self.base_url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            out = json.loads(resp.read())
        if out.get("code") != "SUCCESS":
            raise RuntimeError(f"config fetch failed: {out!r}")
        return out

    def fetch_configs(self, names: Optional[Sequence[str]] = None) -> Dict[str, Any]:
        names = list(names if names is not None else self.ALL)
        return self._post(self.FETCH_PATH, {"config_name": names})["data"]

    def fetch_mqtt_config(self) -> Dict[str, Any]:
        return self.fetch_configs(["mqtt_config"])["mqtt_config"]

    def fetch_s3_config(self) -> Dict[str, Any]:
        return self.fetch_configs(["s3_config"])["s3_config"]

    # -- simulation-run registration (reference core/mlops/__init__.py
    # create_project :438 / create_run :466 — the RPCs a simulation makes
    # before streaming metrics so the platform UI has a project/run row)
    PROJECT_PATH = "/fedmlOpsServer/projects/createSim"
    RUN_PATH = "/fedmlOpsServer/runs/createSim"

    def create_project(self, project_name: str, api_key: str = "") -> Any:
        """-> platform project id."""
        out = self._post(self.PROJECT_PATH, {
            "name": str(project_name), "userids": api_key,
            "platform_type": "simulation",
        })
        return out["data"]

    def create_run(self, project_id, api_key: str = "",
                   edge_ids: Optional[List[int]] = None,
                   run_name: Optional[str] = None) -> Any:
        """-> platform run id."""
        payload: Dict[str, Any] = {
            "userids": api_key, "projectid": str(project_id),
            "edgeids": list(edge_ids or []),
        }
        if run_name is not None:
            payload["name"] = run_name
        return self._post(self.RUN_PATH, payload)["data"]


def post_log_chunk(log_server_url: str, run_id, rank: int, lines: List[str],
                   timeout_s: float = 10.0) -> None:
    """Log-upload RPC (reference ``mlops_runtime_log_daemon.py:276-346``)."""
    import time

    req = urllib.request.Request(
        log_server_url,
        data=json.dumps({
            "run_id": str(run_id), "edge_id": int(rank), "logs": list(lines),
            "create_time": time.time(),
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        out = json.loads(resp.read())
    if out.get("code") != "SUCCESS":
        raise RuntimeError(f"log upload failed: {out!r}")
