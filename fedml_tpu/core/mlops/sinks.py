"""Pluggable event/metric sinks for the MLOps bus.

The reference publishes metrics/events/status over MQTT to the hosted
platform and logs to wandb (``core/mlops/mlops_metrics.py``,
``mlops_profiler_event.py``).  This rebuild is offline-first: every record
goes to one or more local sinks; a broker-backed sink provides the same
"live telemetry over pub/sub" shape using the in-tree broker when a run
configures one (zero external dependencies)."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class Sink:
    def emit(self, topic: str, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemorySink(Sink):
    """Test/introspection sink: keeps (topic, record) tuples in memory."""

    def __init__(self):
        self.records: List[tuple] = []
        self._lock = threading.Lock()

    def emit(self, topic: str, record: Dict[str, Any]) -> None:
        with self._lock:
            self.records.append((topic, dict(record)))

    def by_topic(self, topic: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [r for t, r in self.records if t == topic]


class JsonlFileSink(Sink):
    """Append-only JSONL file, one stream per run (the durable sink)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._fh = open(path, "a")
        self._closed = False
        self._lock = threading.Lock()

    def emit(self, topic: str, record: Dict[str, Any]) -> None:
        line = json.dumps({"topic": topic, **record})
        with self._lock:
            # late emitters (daemon flush racing mlops.finish) must not
            # crash on a closed handle — their record is simply dropped
            if self._closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._fh.close()


class HttpLogSink(Sink):
    """Ships ``log_chunk`` records to a platform log server over HTTP — the
    reference's log-POST leg (``mlops_runtime_log_daemon.py:276-346``).
    Plug into the log daemon's fanout; point at
    :class:`..mlops.platform_fake.MLOpsPlatformFake` locally or the hosted
    platform's LOG_SERVER_URL in production.  Ship failures are counted,
    logged once per streak, and never take the training process down."""

    def __init__(self, log_server_url: str, timeout_s: float = 10.0):
        self.url = str(log_server_url)
        self.timeout_s = float(timeout_s)
        self.ship_failures = 0
        self._failing = False

    def emit(self, topic: str, record: Dict[str, Any]) -> None:
        if topic != "log_chunk":
            return
        from .mlops_configs import post_log_chunk

        try:
            post_log_chunk(
                self.url, record.get("run_id", "0"), int(record.get("rank", 0)),
                list(record.get("lines", [])), timeout_s=self.timeout_s,
            )
            self._failing = False
        except Exception:
            self.ship_failures += 1
            if not self._failing:
                import logging

                logging.getLogger(__name__).exception(
                    "log upload to %s failing (telemetry only; run continues)",
                    self.url,
                )
                self._failing = True


class BrokerSink(Sink):
    """Publishes records to the in-tree pub/sub broker (MQTT-reporting
    parity): topic ``fedml_mlops/<run_id>/<kind>``."""

    def __init__(self, host: str, port: int, run_id: str):
        from ..distributed.communication.mqtt_s3.adapters import create_broker_client

        self.run_id = str(run_id)
        self._client = create_broker_client(
            host, int(port), on_message=lambda t, p: None,
            client_id=f"fedml_mlops_{run_id}",
        )

    def emit(self, topic: str, record: Dict[str, Any]) -> None:
        self._client.publish(f"fedml_mlops/{self.run_id}/{topic}", dict(record))

    def close(self) -> None:
        self._client.disconnect()


class WandbSink(Sink):
    """wandb-reporting leg (reference ``mlops_profiler_event.py:30``
    ``log_to_wandb``, ``simulation/sp/fedavg/fedavg_api.py:218-232``
    ``wandb.log``): numeric metric topics become ``wandb.log`` rows, events
    become prefixed keys.  Constructing this sink requires the ``wandb``
    package and raises ImportError otherwise — the mlops ``init`` wiring
    catches that and downgrades ``enable_wandb`` to a LOUD warning, so the
    flag is never a silent no-op.  In a zero-egress environment run with
    ``WANDB_MODE=offline`` (wandb then journals locally)."""

    _METRIC_TOPICS = ("train_metric", "agg_metric", "round_info", "sys_perf")

    def __init__(self, args: Any):
        import wandb  # optional dep: ImportError -> caller warns loudly

        self._wandb = wandb
        # adopt a run the USER already opened without closing it at
        # mlops.finish(); only a run this sink started is ours to finish
        self._owns_run = wandb.run is None
        if wandb.run is None:
            wandb.init(
                project=str(getattr(args, "wandb_project", "fedml_tpu")),
                name=str(getattr(args, "run_name", None)
                         or f"run_{getattr(args, 'run_id', '0')}"),
                config={k: v for k, v in vars(args).items()
                        if isinstance(v, (int, float, str, bool))},
                mode=os.environ.get("WANDB_MODE",
                                    str(getattr(args, "wandb_mode", "offline"))),
            )

    def emit(self, topic: str, record: Dict[str, Any]) -> None:
        if topic in self._METRIC_TOPICS:
            row = {k: v for k, v in record.items()
                   if isinstance(v, (int, float)) and k not in ("ts", "edge_id")}
            if "round_idx" in record:
                row["round_idx"] = record["round_idx"]
            if row:
                self._wandb.log(row)
        elif topic == "event":
            name = record.get("event", "event")
            row = {}
            if isinstance(record.get("value"), (int, float)):
                row[f"event/{name}"] = record["value"]
            if isinstance(record.get("duration_s"), (int, float)):
                # the reference's log_to_wandb posts span durations
                # (mlops_profiler_event.py:30)
                row[f"event/{name}/duration_s"] = record["duration_s"]
            if row:
                self._wandb.log(row)

    def close(self) -> None:
        try:
            if self._owns_run and self._wandb.run is not None:
                self._wandb.finish()
        except Exception:
            pass


class FanoutSink(Sink):
    def __init__(self, sinks: Optional[List[Sink]] = None):
        self.sinks = list(sinks or [])

    def add(self, sink: Sink) -> None:
        self.sinks.append(sink)

    def emit(self, topic: str, record: Dict[str, Any]) -> None:
        rec = dict(record)
        rec.setdefault("ts", round(time.time(), 3))
        for s in self.sinks:
            s.emit(topic, rec)

    def close(self) -> None:
        for s in self.sinks:
            s.close()
