"""Client/server run-status finite state machine.

Parity with reference ``core/mlops/mlops_status.py`` + the status constants
in ``cli/*/constants.py``: a run moves through a fixed lifecycle; illegal
transitions raise so protocol bugs surface in tests instead of dashboards."""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple


class ClientStatus:
    IDLE = "IDLE"
    INITIALIZING = "INITIALIZING"
    TRAINING = "TRAINING"
    STOPPING = "STOPPING"
    KILLED = "KILLED"
    FAILED = "FAILED"
    FINISHED = "FINISHED"


class ServerStatus:
    IDLE = "IDLE"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    KILLED = "KILLED"
    FAILED = "FAILED"
    FINISHED = "FINISHED"


_CLIENT_EDGES = {
    ClientStatus.IDLE: {ClientStatus.INITIALIZING, ClientStatus.KILLED, ClientStatus.FAILED},
    ClientStatus.INITIALIZING: {ClientStatus.TRAINING, ClientStatus.STOPPING, ClientStatus.KILLED, ClientStatus.FAILED},
    ClientStatus.TRAINING: {ClientStatus.TRAINING, ClientStatus.STOPPING, ClientStatus.FINISHED, ClientStatus.KILLED, ClientStatus.FAILED},
    ClientStatus.STOPPING: {ClientStatus.KILLED, ClientStatus.FINISHED, ClientStatus.FAILED},
    ClientStatus.KILLED: set(),
    ClientStatus.FAILED: set(),
    ClientStatus.FINISHED: set(),
}

_SERVER_EDGES = {
    ServerStatus.IDLE: {ServerStatus.STARTING, ServerStatus.KILLED, ServerStatus.FAILED},
    ServerStatus.STARTING: {ServerStatus.RUNNING, ServerStatus.STOPPING, ServerStatus.KILLED, ServerStatus.FAILED},
    ServerStatus.RUNNING: {ServerStatus.RUNNING, ServerStatus.STOPPING, ServerStatus.FINISHED, ServerStatus.KILLED, ServerStatus.FAILED},
    ServerStatus.STOPPING: {ServerStatus.KILLED, ServerStatus.FINISHED, ServerStatus.FAILED},
    ServerStatus.KILLED: set(),
    ServerStatus.FAILED: set(),
    ServerStatus.FINISHED: set(),
}


class MLOpsStatus:
    """Singleton registry of the latest reported status per (role, id)."""

    _instance: Optional["MLOpsStatus"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._status: Dict[Tuple[str, int], str] = {}

    @classmethod
    def get_instance(cls) -> "MLOpsStatus":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _set(self, role: str, node_id: int, status: str, edges, initial: str) -> None:
        with self._lock:
            cur = self._status.get((role, node_id), initial)
            if status != cur and status not in edges[cur]:
                raise ValueError(f"illegal {role} status transition {cur} -> {status}")
            self._status[(role, node_id)] = status

    def set_client_status(self, client_id: int, status: str) -> None:
        self._set("client", client_id, status, _CLIENT_EDGES, ClientStatus.IDLE)

    def set_server_status(self, server_id: int, status: str) -> None:
        self._set("server", server_id, status, _SERVER_EDGES, ServerStatus.IDLE)

    def get_client_status(self, client_id: int) -> str:
        with self._lock:
            return self._status.get(("client", client_id), ClientStatus.IDLE)

    def get_server_status(self, server_id: int) -> str:
        with self._lock:
            return self._status.get(("server", server_id), ServerStatus.IDLE)

    def reset(self) -> None:
        with self._lock:
            self._status.clear()
