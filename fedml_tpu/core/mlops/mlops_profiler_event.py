"""Span-style profiler events for the run timeline.

Parity with reference ``core/mlops/mlops_profiler_event.py:9``
(``MLOpsProfilerEvent``: start/end events with run/edge ids to MQTT + wandb):
start/end pairs go to the sinks with wall-clock durations; on-device time is
the domain of ``jax.profiler``, so ``trace()`` additionally opens a
``jax.profiler.TraceAnnotation`` making FL-protocol spans visible inside
XLA/TensorBoard traces."""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Optional

from .sinks import FanoutSink


class MLOpsProfilerEvent:
    def __init__(self, run_id: str = "0", edge_id: int = 0, sink: Optional[FanoutSink] = None):
        self.run_id = str(run_id)
        self.edge_id = int(edge_id)
        self.sink = sink if sink is not None else FanoutSink()
        self._open: Dict[str, float] = {}

    def log_event_started(self, event_name: str, event_value: Any = None) -> None:
        # durations come from the monotonic clock — an NTP step mid-event
        # must not yield negative/garbage spans; wall time stays available
        # as record metadata (the FanoutSink stamps "ts")
        self._open[event_name] = time.monotonic()
        self.sink.emit(
            "event",
            {
                "run_id": self.run_id,
                "edge_id": self.edge_id,
                "event": event_name,
                "phase": "started",
                "value": event_value,
            },
        )

    def log_event_ended(self, event_name: str, event_value: Any = None) -> None:
        t0 = self._open.pop(event_name, None)
        self.sink.emit(
            "event",
            {
                "run_id": self.run_id,
                "edge_id": self.edge_id,
                "event": event_name,
                "phase": "ended",
                "value": event_value,
                "duration_s": round(time.monotonic() - t0, 6) if t0 is not None else None,
            },
        )

    @contextlib.contextmanager
    def trace(self, event_name: str):
        """Span context: sink event pair + XLA trace annotation."""
        ann = None
        try:
            import jax.profiler

            ann = jax.profiler.TraceAnnotation(event_name)
            ann.__enter__()
        except Exception:  # pragma: no cover - profiler unavailable
            ann = None
        self.log_event_started(event_name)
        try:
            yield self
        finally:
            self.log_event_ended(event_name)
            if ann is not None:
                ann.__exit__(None, None, None)
