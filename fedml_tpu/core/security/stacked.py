"""Stacked (client-axis) attack & defense math for the compiled round.

The sp backend's security hooks walk Python lists of ``(n_i, pytree)`` —
fine for a host round loop, wrong for the XLA simulator, whose round
RETURNS the per-client update stack as ONE sharded array per leaf
(``fed_sim.py``: out_specs ``P('client')``).  This module restates every
attack/defense as a jax-pure function over that stacked representation:

* ``stack_to_mat``: the stacked update pytree -> one ``[n, D]`` fp32
  matrix (same coordinate order as ``jax.flatten_util.ravel_pytree`` of a
  single tree, so the defense math in :mod:`defense_funcs` transfers 1:1);
* ``build_stacked_attack``: model-side attacks (byzantine, model
  replacement, ALIE, edge-case projection — reference
  ``core/security/attack/*.py``) as ``[n, D]`` row edits gated by a
  malicious-slot mask;
* ``build_stacked_defense``: all robust-aggregation rules (reference
  ``core/security/defense/*.py``) as one function
  ``(stack, w, global, key, state) -> (aggregate, state)``.

Everything here is built once per simulator and traced into ONE jitted
program (``fed_sim._build_security_fn``) that consumes the round's sharded
outputs directly — no host materialization of the update stack, which also
keeps the path correct under multi-host ``jax.distributed`` (host-side
slicing of non-addressable ``P('client')`` leaves would fail pod-scale).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from . import defense_funcs as F
from .constants import (
    ATTACK_METHOD_BACKDOOR,
    ATTACK_METHOD_BYZANTINE_ATTACK,
    ATTACK_METHOD_EDGE_CASE_BACKDOOR,
    ATTACK_METHOD_MODEL_REPLACEMENT,
    DEFENSE_BULYAN,
    DEFENSE_CCLIP,
    DEFENSE_COORDINATE_WISE_MEDIAN,
    DEFENSE_COORDINATE_WISE_TRIMMED_MEAN,
    DEFENSE_FOOLSGOLD,
    DEFENSE_GEO_MEDIAN,
    DEFENSE_KRUM,
    DEFENSE_MULTI_KRUM,
    DEFENSE_NORM_DIFF_CLIPPING,
    DEFENSE_RFA,
    DEFENSE_ROBUST_LEARNING_RATE,
    DEFENSE_SLSGD,
    DEFENSE_SOTERIA,
    DEFENSE_THREE_SIGMA,
    DEFENSE_WBC,
    DEFENSE_WEAK_DP,
)

Pytree = Any
State = Dict[str, jnp.ndarray]


def stack_to_mat(stack: Pytree) -> jnp.ndarray:
    """Stacked pytree (leaves ``[n, ...]``) -> ``[n, D]`` fp32 matrix in
    ``ravel_pytree`` coordinate order (both use ``tree_flatten`` order)."""
    leaves = jax.tree_util.tree_leaves(stack)
    n = leaves[0].shape[0]
    return jnp.concatenate(
        [l.reshape(n, -1).astype(jnp.float32) for l in leaves], axis=1
    )


def flat_dim(tree: Pytree) -> int:
    return sum(int(jnp.size(l)) for l in jax.tree_util.tree_leaves(tree))


def _wmean(mat: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    return (w @ mat) / jnp.maximum(jnp.sum(w), 1e-9)


# ---------------------------------------------------------------------------
# attacks
# ---------------------------------------------------------------------------
def build_stacked_attack(args, attack_type: str) -> Callable:
    """-> ``attack(mat, w, g_vec, mal, key) -> mat'`` where ``mal`` is the
    ``[n]`` 0/1 malicious-slot mask (drawn host-side over the population so
    it matches the data-poisoning targets — ``FedMLAttacker._malicious_slots``
    semantics)."""
    mode = str(getattr(args, "attack_mode", "random"))
    scale = float(getattr(args, "attack_scale", 10.0))
    num_std = float(getattr(args, "attack_num_std", 1.5))
    alie_mode = str(getattr(args, "attack_mode", "craft"))
    eps = float(getattr(args, "attack_norm_bound", 5.0))

    def attack(mat, w, g_vec, mal, key):
        m = mal[:, None]
        if attack_type == ATTACK_METHOD_BYZANTINE_ATTACK:
            if mode == "zero":
                bad = jnp.zeros_like(mat)
            elif mode == "random":
                bad = jax.random.normal(key, mat.shape, mat.dtype)
            elif mode == "flip":
                bad = 2.0 * g_vec[None, :] - mat
            else:
                raise ValueError(f"unknown byzantine mode {mode!r}")
            return jnp.where(m > 0, bad, mat)
        if attack_type == ATTACK_METHOD_MODEL_REPLACEMENT:
            pushed = g_vec[None, :] + scale * (mat - g_vec[None, :])
            return jnp.where(m > 0, pushed, mat)
        if attack_type == ATTACK_METHOD_BACKDOOR:
            # ALIE in-range evasion over the BENIGN rows' (unweighted) statistics
            den = jnp.maximum(jnp.sum(1.0 - mal), 1.0)
            mean = jnp.sum(mat * (1.0 - mal)[:, None], 0) / den
            var = jnp.sum(((mat - mean[None, :]) ** 2) * (1.0 - mal)[:, None], 0) / den
            std = jnp.sqrt(var)
            if alie_mode == "clip":
                bad = jnp.clip(mat, (mean - num_std * std)[None, :],
                               (mean + num_std * std)[None, :])
            else:  # craft
                bad = jnp.broadcast_to((mean - num_std * std)[None, :], mat.shape)
            return jnp.where(m > 0, bad, mat)
        if attack_type == ATTACK_METHOD_EDGE_CASE_BACKDOOR:
            delta = scale * (mat - g_vec[None, :])
            nrm = jnp.linalg.norm(delta, axis=1, keepdims=True)
            delta = delta * jnp.minimum(1.0, eps / jnp.maximum(nrm, 1e-12))
            return jnp.where(m > 0, g_vec[None, :] + delta, mat)
        raise NotImplementedError(
            f"attack {attack_type!r} has no stacked (XLA-backend) form"
        )

    return attack


# ---------------------------------------------------------------------------
# defenses
# ---------------------------------------------------------------------------
def init_defense_state(defense_type: Optional[str], n: int, d: int) -> State:
    """Cross-round defense state as device arrays (replaces the host
    dispatcher's ``_history`` / ``_wbc_prev`` attributes)."""
    if defense_type == DEFENSE_FOOLSGOLD:
        return {"fg_hist": jnp.zeros((n, d), jnp.float32)}
    if defense_type == DEFENSE_WBC:
        return {"wbc_prev": jnp.zeros((n, d), jnp.float32),
                "wbc_has": jnp.zeros((), jnp.float32)}
    return {}


def build_stacked_defense(args, defense_type: str,
                          probe_mask: Optional[jnp.ndarray] = None,
                          rows: bool = False) -> Callable:
    """-> ``defend(stack, w, global_vars, key, state) -> (agg_tree, state)``.

    ``stack``: update pytree with a leading ``[n]`` client axis (n real
    clients, every ``w > 0``); ``agg_tree`` replaces the round's weighted
    mean (fp32, global-tree structure).  Semantics mirror the list-based
    hooks in :class:`fedml_defender.FedMLDefender` rule for rule.

    ``rows=True`` returns ``defend_rows(stack, w, global_vars, key, state)
    -> (mat', w', state)`` instead: the defended per-client ROW SPACE —
    every rule restated as a transform of (rows, weights), with
    aggregate-replacing rules broadcasting their robust aggregate to all
    rows (so ``_wmean(mat', w') == agg_tree`` always).  Strategies that
    aggregate through ``ext`` (FedNova, async — ``aggregates_via_acc``
    False) recompute their per-client contributions from this defended row
    space (``InMeshAlgorithm.ext_from_rows``), which matches the sp
    composition exactly for the before-aggregation defenses (selection /
    row transforms) and extends aggregate-replacing defenses as "every
    client reported the robust consensus row".
    """
    a = args
    byz = int(getattr(a, "byzantine_client_num", 1))
    t = defense_type

    def matrix_defense(mat, w, g_vec, key, state, rows_mode=False):
        """[n, D] robust aggregation -> (mat', w', state) row space; the
        aggregate is always ``_wmean(mat', w')``.  ``rows_mode``: the
        output feeds an ext-aggregator's per-client recomputation, so
        returned weights must keep the ORIGINAL sample-count scale (only
        foolsgold differs: its trust weights are normalized to sum 1, so
        rows mode broadcasts its aggregate instead — it is an
        on-aggregation rule, same treatment as median/bulyan)."""
        n = mat.shape[0]
        bcast = lambda vec: jnp.broadcast_to(vec[None, :], mat.shape)
        if t in (DEFENSE_KRUM, DEFENSE_MULTI_KRUM):
            multi = (t == DEFENSE_MULTI_KRUM) or bool(getattr(a, "multi", False))
            m = max(int(getattr(a, "krum_param_m", 1)), 1) if multi else 1
            scores = F.krum_scores(mat, byz)
            chosen = jnp.argsort(scores)[:m]
            sel = jnp.zeros((n,), jnp.float32).at[chosen].set(1.0)
            return mat, w * sel, state
        if t == DEFENSE_NORM_DIFF_CLIPPING:
            bound = float(getattr(a, "norm_bound", 5.0))
            diff = mat - g_vec[None, :]
            nrm = jnp.linalg.norm(diff, axis=1, keepdims=True)
            clipped = g_vec[None, :] + diff * jnp.minimum(
                1.0, bound / jnp.maximum(nrm, 1e-12)
            )
            return clipped, w, state
        if t == DEFENSE_THREE_SIGMA:
            arr = jnp.linalg.norm(mat - g_vec[None, :], axis=1)
            mu, sigma = jnp.mean(arr), jnp.std(arr)
            keep = (jnp.abs(arr - mu) <= 3.0 * sigma + 1e-12).astype(jnp.float32)
            w2 = jnp.where(jnp.sum(keep) > 0, w * keep, w)  # all-outlier fallback
            return mat, w2, state
        if t == DEFENSE_WBC:
            strength = float(getattr(a, "wbc_strength", 1.0))
            lr = float(getattr(a, "wbc_lr", 0.1))
            noise = strength * F._laplace(key, mat.shape)
            diff = mat - state["wbc_prev"]
            noise = jnp.where(jnp.abs(diff) > jnp.abs(noise), 0.0, noise)
            pert = mat + lr * noise * state["wbc_has"]  # first round: no prev
            new_state = {"wbc_prev": mat, "wbc_has": jnp.ones((), jnp.float32)}
            return pert, w, new_state
        if t in (DEFENSE_GEO_MEDIAN, DEFENSE_RFA):
            max_iter = int(getattr(a, "geo_median_max_iter", 10))
            wn = w / jnp.sum(w)

            def body(_, z):
                dist = jnp.linalg.norm(mat - z[None, :], axis=1)
                inv = wn / jnp.maximum(dist, 1e-8)
                return (inv[:, None] * mat).sum(0) / jnp.sum(inv)

            z = jax.lax.fori_loop(0, max_iter, body, wn @ mat)
            return bcast(z), w, state
        if t == DEFENSE_CCLIP:
            tau = float(getattr(a, "tau", 10.0))
            n_iter = int(getattr(a, "bucket_iter", 1))
            wn = w / jnp.sum(w)

            def body(_, v):
                diff = mat - v[None, :]
                nrm = jnp.linalg.norm(diff, axis=1, keepdims=True)
                s = jnp.minimum(1.0, tau / jnp.maximum(nrm, 1e-12))
                return v + jnp.sum(wn[:, None] * diff * s, 0)

            return bcast(jax.lax.fori_loop(0, n_iter, body, g_vec)), w, state
        if t == DEFENSE_SLSGD:
            b = max(0, min(int(getattr(a, "trim_param_b", 1)), (n - 1) // 2))
            alpha = float(getattr(a, "alpha", 0.5))
            srt = jnp.sort(mat, axis=0)
            agg = jnp.mean(srt[b : n - b], axis=0)
            return bcast((1.0 - alpha) * g_vec + alpha * agg), w, state
        if t == DEFENSE_FOOLSGOLD:
            hist = state["fg_hist"] + (mat - g_vec[None, :])
            wv = F.foolsgold_weights(hist)
            wv = wv / jnp.maximum(jnp.sum(wv), 1e-12)
            if rows_mode:
                return bcast(wv @ mat), w, {"fg_hist": hist}
            return mat, wv, {"fg_hist": hist}
        if t == DEFENSE_ROBUST_LEARNING_RATE:
            threshold = int(getattr(a, "robust_threshold", 4))
            deltas = mat - g_vec[None, :]
            wn = w / jnp.sum(w)
            agree = jnp.abs(jnp.sum(jnp.sign(deltas), axis=0))
            lr = jnp.where(agree >= threshold, 1.0, -1.0)
            return bcast(g_vec + lr * (wn @ deltas)), w, state
        if t == DEFENSE_COORDINATE_WISE_MEDIAN:
            return bcast(jnp.median(mat, axis=0)), w, state
        if t == DEFENSE_COORDINATE_WISE_TRIMMED_MEAN:
            k = int(n * float(getattr(a, "beta", 0.1)))
            k = max(0, min(k, (n - 1) // 2))
            srt = jnp.sort(mat, axis=0)
            return bcast(jnp.mean(srt[k : n - k], axis=0)), w, state
        if t == DEFENSE_BULYAN:
            theta = max(n - 2 * byz, 1)
            scores = F.krum_scores(mat, byz)
            sel = jnp.argsort(scores)[:theta]
            sel_mat = mat[sel]
            beta = max(theta - 2 * byz, 1)
            med = jnp.median(sel_mat, axis=0)
            order = jnp.argsort(jnp.abs(sel_mat - med[None, :]), axis=0)[:beta]
            return bcast(jnp.mean(jnp.take_along_axis(sel_mat, order, axis=0), 0)), w, state
        if t == DEFENSE_WEAK_DP:
            agg = _wmean(mat, w)
            stddev = float(getattr(a, "stddev", 0.025))
            return bcast(agg + stddev * jax.random.normal(key, agg.shape)), w, state
        raise NotImplementedError(
            f"defense {t!r} has no stacked (XLA-backend) form"
        )

    def _rows(stack, w, global_vars, key, state):
        if t == DEFENSE_SOTERIA:
            layer_path = list(getattr(a, "soteria_layer", ("classifier", "kernel")))
            pct = float(getattr(a, "soteria_percentile", 10.0))
            pruned = _soteria_stacked(stack, global_vars, layer_path, pct, probe_mask)
            return stack_to_mat(pruned), w, state
        g_vec, _ = ravel_pytree(
            jax.tree_util.tree_map(lambda v: v.astype(jnp.float32), global_vars)
        )
        return matrix_defense(stack_to_mat(stack), w, g_vec, key, state,
                              rows_mode=True)

    def defend(stack, w, global_vars, key, state):
        if t == DEFENSE_SOTERIA:
            # tree-level: prune low-sensitivity features of the defended
            # layer's delta per client, then take the weighted mean
            layer_path = list(getattr(a, "soteria_layer", ("classifier", "kernel")))
            pct = float(getattr(a, "soteria_percentile", 10.0))
            pruned = _soteria_stacked(stack, global_vars, layer_path, pct, probe_mask)
            agg = jax.tree_util.tree_map(
                lambda s: jnp.tensordot(w, s.astype(jnp.float32), axes=1)
                / jnp.maximum(jnp.sum(w), 1e-9),
                pruned,
            )
            return agg, state
        g_vec, unravel = ravel_pytree(
            jax.tree_util.tree_map(lambda v: v.astype(jnp.float32), global_vars)
        )
        mat2, w2, state = matrix_defense(stack_to_mat(stack), w, g_vec, key, state)
        return unravel(_wmean(mat2, w2)), state

    return _rows if rows else defend


def _soteria_stacked(stack: Pytree, global_vars: Pytree, layer_path,
                     pct: float, probe_mask: Optional[jnp.ndarray]) -> Pytree:
    """Stacked :func:`defense_funcs.soteria_apply`: leaves carry a leading
    client axis; the per-feature mask comes from the registered probe when
    available, else from each client's per-feature delta magnitude."""
    node, gnode = stack["params"], global_vars["params"]
    for kpath in layer_path:
        node, gnode = node[kpath], gnode[kpath]
    n = node.shape[0]
    if probe_mask is not None:
        mask = jnp.broadcast_to(probe_mask[None, :], (n, probe_mask.shape[0]))
    else:
        delta = node.astype(jnp.float32) - gnode[None].astype(jnp.float32)
        mag = jnp.sqrt(jnp.sum(delta.reshape(n, -1, delta.shape[-1]) ** 2, axis=1))
        mask = jax.vmap(lambda s: F.soteria_mask(s, pct))(mag)

    def walk(tree, gtree, path):
        if not path:
            m = mask.reshape((n,) + (1,) * (tree.ndim - 2) + (-1,))
            return gtree[None] + (tree - gtree[None]) * m
        out = dict(tree)
        out[path[0]] = walk(tree[path[0]], gtree[path[0]], path[1:])
        return out

    out = dict(stack)
    out["params"] = walk(stack["params"], global_vars["params"], list(layer_path))
    return out
