"""Robust-aggregation defense math as pure functions over client updates.

Each defense takes ``updates: List[(sample_num, params_pytree)]`` and either
filters the list (before-aggregation defenses) or replaces the aggregation
rule (on-aggregation defenses).  Distance-based rules ravel each pytree to one
vector (``jax.flatten_util.ravel_pytree``) and compute the full pairwise
distance matrix in one XLA call — the TPU-friendly restatement of the
reference's per-layer Python loops (``core/security/defense/*.py``).

Implemented rules and their reference counterparts (SURVEY.md §2.3):
Krum / multi-Krum (krum_defense.py), coordinate-wise median + trimmed mean
(coordinate_wise_median_defense.py, coordinate_wise_trimmed_mean_defense.py),
geometric median a.k.a. RFA (geometric_median_defense.py), norm-difference
clipping (norm_diff_clipping_defense.py), centered clip / CClip
(cclip_defense.py), weak DP (weak_dp_defense.py), SLSGD (slsgd_defense.py),
FoolsGold (foolsgold_defense.py), robust learning rate (robust_learning_rate_defense.py),
Bulyan (bulyan_defense.py), three-sigma outlier removal, Soteria
representation-gradient pruning (soteria_defense.py) and FL-WBC client-side
perturbation (wbc_defense.py).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from ..aggregate import tree_add, tree_scale, tree_stack, tree_sub, weighted_mean

Pytree = Any
Updates = List[Tuple[float, Pytree]]


def _ravel_all(updates: Sequence[Tuple[float, Pytree]]):
    """-> (matrix [n_clients, dim], unravel_fn, sample_nums)."""
    vecs, unravel = [], None
    for _, p in updates:
        v, unravel = ravel_pytree(p)
        vecs.append(v)
    return jnp.stack(vecs, axis=0), unravel, jnp.asarray([float(n) for n, _ in updates])


def pairwise_sq_dists(mat: jnp.ndarray) -> jnp.ndarray:
    """[n, d] -> [n, n] squared euclidean distances, one fused XLA matmul."""
    sq = jnp.sum(mat * mat, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (mat @ mat.T)
    return jnp.maximum(d2, 0.0)


# ---------------------------------------------------------------------------
# Krum / multi-Krum
# ---------------------------------------------------------------------------
def krum_scores(mat: jnp.ndarray, byzantine_num: int) -> jnp.ndarray:
    n = mat.shape[0]
    d2 = pairwise_sq_dists(mat)
    d2 = d2 + jnp.diag(jnp.full((n,), jnp.inf))
    k = max(n - byzantine_num - 2, 1)
    nearest = jnp.sort(d2, axis=1)[:, :k]
    return jnp.sum(nearest, axis=1)


def krum(updates: Updates, byzantine_num: int, multi: bool = False, krum_param_m: int = 1) -> Updates:
    mat, _, _ = _ravel_all(updates)
    scores = krum_scores(mat, byzantine_num)
    m = max(int(krum_param_m), 1) if multi else 1
    chosen = jnp.argsort(scores)[:m]
    return [updates[int(i)] for i in chosen]


# ---------------------------------------------------------------------------
# Coordinate-wise median / trimmed mean
# ---------------------------------------------------------------------------
def coordinate_wise_median(updates: Updates) -> Pytree:
    stacked = tree_stack([p for _, p in updates])
    return jax.tree_util.tree_map(lambda x: jnp.median(x, axis=0), stacked)


def coordinate_wise_trimmed_mean(updates: Updates, trim_ratio: float) -> Pytree:
    n = len(updates)
    k = int(n * float(trim_ratio))
    return _trimmed_mean_count(updates, k)


def _trimmed_mean_count(updates: Updates, k: int) -> Pytree:
    """Trim ``k`` updates per coordinate per end, then average the rest."""
    n = len(updates)
    k = max(0, min(int(k), (n - 1) // 2))
    stacked = tree_stack([p for _, p in updates])

    def _leaf(x):
        x = jnp.sort(x, axis=0)
        return jnp.mean(x[k : n - k], axis=0)

    return jax.tree_util.tree_map(_leaf, stacked)  # fedlint: allow[sec-host-fallback] — retained host oracle for the compiled trimmed-mean stage


# ---------------------------------------------------------------------------
# Geometric median (RFA) via Weiszfeld iterations
# ---------------------------------------------------------------------------
def geometric_median(updates: Updates, max_iter: int = 10, eps: float = 1e-8) -> Pytree:
    mat, unravel, nums = _ravel_all(updates)
    w = nums / jnp.sum(nums)

    def body(_, z):
        dist = jnp.linalg.norm(mat - z[None, :], axis=1)
        inv = w / jnp.maximum(dist, eps)
        return (inv[:, None] * mat).sum(axis=0) / jnp.sum(inv)

    z = jax.lax.fori_loop(0, max_iter, body, (w[:, None] * mat).sum(axis=0))
    return unravel(z)


# ---------------------------------------------------------------------------
# Clipping family
# ---------------------------------------------------------------------------
def norm_diff_clipping(updates: Updates, global_params: Pytree, norm_bound: float) -> Updates:
    """Clip each client's delta from the global model to norm <= bound
    (reference norm_diff_clipping_defense.py)."""
    g_vec, unravel = ravel_pytree(global_params)
    out: Updates = []
    for n, p in updates:  # fedlint: allow[sec-host-fallback] — retained host oracle for the compiled norm-clip stage
        v, _ = ravel_pytree(p)
        diff = v - g_vec
        nrm = jnp.linalg.norm(diff)
        scale = jnp.minimum(1.0, norm_bound / jnp.maximum(nrm, 1e-12))
        out.append((n, unravel(g_vec + diff * scale)))
    return out


def cclip(updates: Updates, global_params: Pytree, tau: float = 10.0, n_iter: int = 1) -> Pytree:
    """Centered clipping (Karimireddy et al.): iterate v <- v + mean(clip(x_i - v, tau))."""
    mat, unravel, nums = _ravel_all(updates)
    w = nums / jnp.sum(nums)
    v, _ = ravel_pytree(global_params)

    def body(_, v):
        diff = mat - v[None, :]
        nrm = jnp.linalg.norm(diff, axis=1, keepdims=True)
        scale = jnp.minimum(1.0, tau / jnp.maximum(nrm, 1e-12))
        return v + jnp.sum(w[:, None] * diff * scale, axis=0)

    return unravel(jax.lax.fori_loop(0, n_iter, body, v))


def weak_dp(aggregated: Pytree, stddev: float, key: jax.Array) -> Pytree:
    from ..dp.mechanisms import _add_noise_tree

    return _add_noise_tree(
        aggregated, key, lambda k, shape: stddev * jax.random.normal(k, shape)
    )


# ---------------------------------------------------------------------------
# SLSGD: trimmed-mean + momentum toward current global model
# ---------------------------------------------------------------------------
def slsgd(updates: Updates, global_params: Pytree, trim_count: int, alpha: float) -> Pytree:
    """``trim_count`` is an integer count of gradients trimmed per end
    (reference slsgd_defense.py's ``b``), NOT a fraction."""
    agg = _trimmed_mean_count(updates, trim_count)
    return tree_add(tree_scale(global_params, 1.0 - alpha), tree_scale(agg, alpha))


# ---------------------------------------------------------------------------
# FoolsGold: contribution-similarity re-weighting
# ---------------------------------------------------------------------------
def foolsgold_weights(history_mat: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """[n, d] aggregate historical updates -> per-client learning weights."""
    norms = jnp.linalg.norm(history_mat, axis=1, keepdims=True)
    normed = history_mat / jnp.maximum(norms, eps)
    cs = normed @ normed.T - jnp.eye(history_mat.shape[0])
    maxcs = jnp.max(cs, axis=1)
    # pardoning: when maxcs[i] < maxcs[j], rescale cs[i, j] by maxcs[i]/maxcs[j]
    # so honest clients (low max-similarity) are pardoned, sybils are not
    scaled = cs * jnp.minimum(1.0, (maxcs[:, None] / jnp.maximum(maxcs[None, :], eps)))
    wv = 1.0 - jnp.max(scaled, axis=1)
    wv = jnp.clip(wv, 0.0, 1.0)
    wv = wv / jnp.maximum(jnp.max(wv), eps)
    wv = jnp.where(wv == 1.0, 0.99, wv)
    logits = jnp.log(jnp.clip(wv / jnp.maximum(1.0 - wv, eps), eps, None)) + 0.5
    return jnp.clip(logits, 0.0, 1.0)


def foolsgold(updates: Updates, history_mat: jnp.ndarray) -> Pytree:
    mat, unravel, _ = _ravel_all(updates)
    wv = foolsgold_weights(history_mat)
    wv = wv / jnp.maximum(jnp.sum(wv), 1e-12)
    return unravel(jnp.sum(wv[:, None] * mat, axis=0))


# ---------------------------------------------------------------------------
# Robust learning rate (sign-agreement threshold)
# ---------------------------------------------------------------------------
def robust_learning_rate(updates: Updates, global_params: Pytree, threshold: int) -> Pytree:
    g_vec, unravel = ravel_pytree(global_params)
    deltas = []
    nums = []
    for n, p in updates:  # fedlint: allow[sec-host-fallback] — host-only defense, no compiled counterpart yet
        v, _ = ravel_pytree(p)
        deltas.append(v - g_vec)
        nums.append(float(n))
    dmat = jnp.stack(deltas, 0)
    w = jnp.asarray(nums)
    w = w / jnp.sum(w)
    sign_agreement = jnp.abs(jnp.sum(jnp.sign(dmat), axis=0))
    lr = jnp.where(sign_agreement >= threshold, 1.0, -1.0)
    avg_delta = jnp.sum(w[:, None] * dmat, axis=0)
    return unravel(g_vec + lr * avg_delta)


# ---------------------------------------------------------------------------
# Bulyan: multi-Krum selection + trimmed aggregation
# ---------------------------------------------------------------------------
def bulyan(updates: Updates, byzantine_num: int) -> Pytree:
    n = len(updates)
    theta = max(n - 2 * byzantine_num, 1)
    mat, unravel, _ = _ravel_all(updates)
    scores = krum_scores(mat, byzantine_num)
    sel = jnp.argsort(scores)[:theta]
    sel_mat = mat[sel]
    beta = max(theta - 2 * byzantine_num, 1)
    med = jnp.median(sel_mat, axis=0)
    dist = jnp.abs(sel_mat - med[None, :])
    order = jnp.argsort(dist, axis=0)[:beta]
    closest = jnp.take_along_axis(sel_mat, order, axis=0)
    return unravel(jnp.mean(closest, axis=0))


# ---------------------------------------------------------------------------
# Three-sigma / norm-outlier filtering (used by several wrappers)
# ---------------------------------------------------------------------------
def three_sigma_filter(updates: Updates, global_params: Pytree) -> Updates:
    mat, _, _ = _ravel_all(updates)
    g_vec, _ = ravel_pytree(global_params)
    arr = jnp.linalg.norm(mat - g_vec[None, :], axis=1)
    mu, sigma = jnp.mean(arr), jnp.std(arr)
    mask = jnp.abs(arr - mu) <= 3.0 * sigma + 1e-12
    keep = [i for i, ok in enumerate(mask.tolist()) if ok]
    return [updates[i] for i in keep] or updates


# ---------------------------------------------------------------------------
# Soteria: representation-gradient pruning (Sun et al., arXiv:2012.06043;
# reference soteria_defense.py)
# ---------------------------------------------------------------------------
def soteria_scores(feature_fn, xs: jnp.ndarray) -> jnp.ndarray:
    """Per-feature sensitivity ||dr_f/dx|| / |r_f| summed over a probe batch.

    The reference loops a backward pass per feature
    (soteria_defense.py:60-71); here one ``jax.jacrev`` per sample (vmapped)
    computes the whole Jacobian on-device.  ``feature_fn``: single input ->
    representation vector [F] (the layer whose gradient the client shares)."""

    def per_sample(x):
        r = feature_fn(x)
        J = jax.jacrev(feature_fn)(x)  # [F, *x.shape]
        Jn = jnp.sqrt(jnp.sum(J.reshape(J.shape[0], -1) ** 2, axis=1))
        return Jn / jnp.maximum(jnp.abs(r), 1e-8)

    return jnp.sum(jax.vmap(per_sample)(xs), axis=0)


def soteria_mask(scores: jnp.ndarray, percentile: float = 1.0) -> jnp.ndarray:
    """0/1 mask zeroing the features BELOW the given percentile of
    sensitivity — low ||dr/dx||/|r| features leak the most under gradient
    inversion (the paper's pruning rule, reference soteria_defense.py:74-78)."""
    thresh = jnp.percentile(scores, percentile)
    return (scores >= thresh).astype(jnp.float32)


def soteria_apply(update: Pytree, global_params: Pytree, mask: jnp.ndarray,
                  layer_path: Sequence[str]) -> Pytree:
    """Mask the pruned representation features out of a client's DELTA (the
    shared gradient), leaving the rest of the update untouched.

    Pruning dL/dr_f zeroes feature f's contribution to the gradient of the
    layer PRODUCING the representation: ``layer_path`` addresses that layer's
    kernel (flax layout [in, F] — the feature axis is the LAST axis, so the
    mask broadcasts over leading axes; also correct for its bias [F])."""

    def walk(tree, gtree, path):
        if not path:
            delta = tree - gtree
            return gtree + delta * mask.reshape((1,) * (tree.ndim - 1) + (-1,))
        out = dict(tree)
        out[path[0]] = walk(tree[path[0]], gtree[path[0]], path[1:])
        return out

    out = dict(update)
    out["params"] = walk(update["params"], global_params["params"], list(layer_path))
    return out


# ---------------------------------------------------------------------------
# FL-WBC: white-blood-cell client-side perturbation (Sun et al., NeurIPS'21;
# reference wbc_defense.py)
# ---------------------------------------------------------------------------
def wbc_perturb(update: Pytree, prev_update: Pytree, key: jax.Array,
                strength: float = 1.0, lr: float = 0.1) -> Pytree:
    """Perturb the parameter space where an attack effect PERSISTS: where the
    update barely changed since the previous round (small |delta - prev|),
    a poisoning push can hide, so Laplace noise is injected there; fast-moving
    coordinates (|diff| > |noise|) are left alone to preserve accuracy
    (reference wbc_defense.py:55-70 per-tensor loop, vectorized here)."""
    vec, unravel = ravel_pytree(update)
    prev_vec, _ = ravel_pytree(prev_update)
    diff = vec - prev_vec
    noise = strength * _laplace(key, vec.shape)
    noise = jnp.where(jnp.abs(diff) > jnp.abs(noise), 0.0, noise)
    return unravel(vec + lr * noise)


def _laplace(key: jax.Array, shape) -> jnp.ndarray:
    u = jax.random.uniform(key, shape, minval=-0.5 + 1e-7, maxval=0.5)
    return -jnp.sign(u) * jnp.log1p(-2.0 * jnp.abs(u))
