"""Defense dispatcher singleton.

Parity with reference ``core/security/fedml_defender.py:27-71`` (gated by
``enable_defense`` + ``defense_type``), extended with the defenses the
reference ships as standalone modules but never wires (bulyan, coordinate-wise
median/trimmed-mean, 3sigma).  Unlike the reference — which refuses to run
defenses on non-torch engines — all rules here are pytree/JAX-native
(see defense_funcs.py) and run on TPU.

Hook protocol (reference ``defend_before/on/after_aggregation``):
* before: filter/clip the raw update list
* on: replace the aggregation rule entirely
* after: post-process the aggregated pytree
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from . import defense_funcs as F
from .constants import (
    DEFENSE_BULYAN,
    DEFENSE_CCLIP,
    DEFENSE_COORDINATE_WISE_MEDIAN,
    DEFENSE_COORDINATE_WISE_TRIMMED_MEAN,
    DEFENSE_FOOLSGOLD,
    DEFENSE_GEO_MEDIAN,
    DEFENSE_KRUM,
    DEFENSE_MULTI_KRUM,
    DEFENSE_NORM_DIFF_CLIPPING,
    DEFENSE_RFA,
    DEFENSE_ROBUST_LEARNING_RATE,
    DEFENSE_SLSGD,
    DEFENSE_SOTERIA,
    DEFENSE_THREE_SIGMA,
    DEFENSE_WBC,
    DEFENSE_WEAK_DP,
)

logger = logging.getLogger(__name__)

Updates = List[Tuple[float, Any]]

_BEFORE_DEFENSES = {
    DEFENSE_KRUM,
    DEFENSE_MULTI_KRUM,
    DEFENSE_NORM_DIFF_CLIPPING,
    DEFENSE_THREE_SIGMA,
    DEFENSE_SOTERIA,  # client-side in the paper; applied to each shared update
    DEFENSE_WBC,  # client-side in the paper; applied to each shared update
}
_ON_DEFENSES = {
    DEFENSE_SLSGD,
    DEFENSE_GEO_MEDIAN,
    DEFENSE_RFA,
    DEFENSE_CCLIP,
    DEFENSE_FOOLSGOLD,
    DEFENSE_ROBUST_LEARNING_RATE,
    DEFENSE_COORDINATE_WISE_MEDIAN,
    DEFENSE_COORDINATE_WISE_TRIMMED_MEAN,
    DEFENSE_BULYAN,
}
_AFTER_DEFENSES = {DEFENSE_WEAK_DP}

SUPPORTED_DEFENSES = sorted(_BEFORE_DEFENSES | _ON_DEFENSES | _AFTER_DEFENSES)


class FedMLDefender:
    _defender_instance: Optional["FedMLDefender"] = None

    @classmethod
    def get_instance(cls) -> "FedMLDefender":
        if cls._defender_instance is None:
            cls._defender_instance = cls()
        return cls._defender_instance

    def __init__(self):
        self.is_enabled = False
        self.defense_type: Optional[str] = None
        self.args = None
        self._history: Optional[jnp.ndarray] = None  # foolsgold per-client history
        self._key = jax.random.PRNGKey(17)

    def init(self, args: Any) -> None:
        if not getattr(args, "enable_defense", False):
            self.is_enabled = False
            return
        self.args = args
        self.is_enabled = True
        self.defense_type = str(args.defense_type).strip()
        self._history = None
        self._wbc_prev = None
        self._soteria_probe = None
        if self.defense_type not in SUPPORTED_DEFENSES:
            raise ValueError(
                f"unknown defense_type {self.defense_type!r}; supported: {SUPPORTED_DEFENSES}"
            )
        if self.defense_type == DEFENSE_WBC and int(
            getattr(args, "client_num_in_total", 0)
        ) != int(getattr(args, "client_num_per_round", 0)):
            # WBC compares each client's update to ITS OWN previous update;
            # the aggregation hook only sees positional slots, which map to
            # stable clients only under full participation — fail loudly
            # rather than comparing unrelated clients' updates.
            raise NotImplementedError(
                "defense 'wbc' requires full participation "
                "(client_num_per_round == client_num_in_total): per-client "
                "update history is keyed by round slot"
            )
        self._key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 1013)
        logger.info("defense enabled: %s", self.defense_type)

    def is_defense_enabled(self) -> bool:
        return self.is_enabled

    def is_defense_before_aggregation(self) -> bool:
        return self.defense_type in _BEFORE_DEFENSES

    def is_defense_on_aggregation(self) -> bool:
        return self.defense_type in _ON_DEFENSES

    def is_defense_after_aggregation(self) -> bool:
        return self.defense_type in _AFTER_DEFENSES

    # -- hooks ---------------------------------------------------------------
    def defend_before_aggregation(
        self, raw_client_grad_list: Updates, extra_auxiliary_info: Any = None
    ) -> Updates:
        if not self.is_defense_before_aggregation():
            return raw_client_grad_list
        a = self.args
        t = self.defense_type
        if t in (DEFENSE_KRUM, DEFENSE_MULTI_KRUM):
            return F.krum(
                raw_client_grad_list,
                byzantine_num=int(getattr(a, "byzantine_client_num", 1)),
                multi=(t == DEFENSE_MULTI_KRUM) or bool(getattr(a, "multi", False)),
                krum_param_m=int(getattr(a, "krum_param_m", 1)),
            )
        if t == DEFENSE_NORM_DIFF_CLIPPING:
            return F.norm_diff_clipping(
                raw_client_grad_list,
                extra_auxiliary_info,
                float(getattr(a, "norm_bound", 5.0)),
            )
        if t == DEFENSE_THREE_SIGMA:
            return F.three_sigma_filter(raw_client_grad_list, extra_auxiliary_info)
        if t == DEFENSE_SOTERIA:
            return self._soteria(raw_client_grad_list, extra_auxiliary_info)
        if t == DEFENSE_WBC:
            return self._wbc(raw_client_grad_list, extra_auxiliary_info)
        return raw_client_grad_list

    # -- client-side defenses run over the shared-update list ----------------
    def register_soteria_probe(self, feature_fn: Callable, probe_data) -> None:
        """Install the representation function + probe batch that Soteria
        scores sensitivities with (the client-side information the paper
        assumes).  Without a probe, sensitivities fall back to a
        delta-magnitude proxy on the defended layer."""
        self._soteria_probe = (feature_fn, probe_data)

    def _soteria(self, updates: Updates, global_params: Any) -> Updates:
        a = self.args
        layer_path = tuple(
            getattr(a, "soteria_layer", ("classifier", "kernel"))
        )
        pct = float(getattr(a, "soteria_percentile", 10.0))
        probe = getattr(self, "_soteria_probe", None)
        if probe is not None:
            feature_fn, xs = probe
            scores = F.soteria_scores(feature_fn, xs)
            mask = F.soteria_mask(scores, pct)
        else:
            mask = None
        out = []
        for n, p in updates:  # fedlint: allow[sec-host-fallback] — soteria is probe-driven and host-only by design
            if mask is None:
                # proxy: per-feature delta magnitude on the defended layer
                node, gnode = p["params"], global_params["params"]
                for kpath in layer_path:
                    node, gnode = node[kpath], gnode[kpath]
                # per-feature (last-axis) delta magnitude
                mag = jnp.sqrt(
                    jnp.sum((node - gnode).reshape(-1, node.shape[-1]) ** 2, axis=0)
                )
                m = F.soteria_mask(mag, pct)
            else:
                m = mask
            out.append((n, F.soteria_apply(p, global_params, m, layer_path)))
        return out

    def _wbc(self, updates: Updates, global_params: Any) -> Updates:
        a = self.args
        strength = float(getattr(a, "wbc_strength", 1.0))
        lr = float(getattr(a, "wbc_lr", 0.1))
        prev = getattr(self, "_wbc_prev", None) or {}
        out, new_prev = [], {}
        for i, (n, p) in enumerate(updates):
            new_prev[i] = p
            if i in prev:
                self._key, sub = jax.random.split(self._key)
                p = F.wbc_perturb(p, prev[i], sub, strength=strength, lr=lr)
            out.append((n, p))
        self._wbc_prev = new_prev
        return out

    def defend_on_aggregation(
        self,
        raw_client_grad_list: Updates,
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Any:
        if not self.is_defense_on_aggregation():
            if base_aggregation_func is None:
                raise ValueError("base_aggregation_func required")
            return base_aggregation_func(self.args, raw_client_grad_list)
        a = self.args
        t = self.defense_type
        if t in (DEFENSE_GEO_MEDIAN, DEFENSE_RFA):
            return F.geometric_median(
                raw_client_grad_list, max_iter=int(getattr(a, "geo_median_max_iter", 10))
            )
        if t == DEFENSE_SLSGD:
            return F.slsgd(
                raw_client_grad_list,
                extra_auxiliary_info,
                trim_count=int(getattr(a, "trim_param_b", 1)),
                alpha=float(getattr(a, "alpha", 0.5)),
            )
        if t == DEFENSE_CCLIP:
            return F.cclip(
                raw_client_grad_list,
                extra_auxiliary_info,
                tau=float(getattr(a, "tau", 10.0)),
                n_iter=int(getattr(a, "bucket_iter", 1)),
            )
        if t == DEFENSE_FOOLSGOLD:
            mat, _, _ = F._ravel_all(raw_client_grad_list)
            g_vec, _ = ravel_pytree(extra_auxiliary_info)
            deltas = mat - g_vec[None, :]
            if self._history is None or self._history.shape != deltas.shape:
                self._history = deltas
            else:
                self._history = self._history + deltas
            return F.foolsgold(raw_client_grad_list, self._history)
        if t == DEFENSE_ROBUST_LEARNING_RATE:
            return F.robust_learning_rate(
                raw_client_grad_list,
                extra_auxiliary_info,
                threshold=int(getattr(a, "robust_threshold", 4)),
            )
        if t == DEFENSE_COORDINATE_WISE_MEDIAN:
            return F.coordinate_wise_median(raw_client_grad_list)
        if t == DEFENSE_COORDINATE_WISE_TRIMMED_MEAN:
            return F.coordinate_wise_trimmed_mean(
                raw_client_grad_list, float(getattr(a, "beta", 0.1))
            )
        if t == DEFENSE_BULYAN:
            return F.bulyan(
                raw_client_grad_list, int(getattr(a, "byzantine_client_num", 1))
            )
        raise AssertionError(t)

    def defend_after_aggregation(self, global_model: Any) -> Any:
        if not self.is_defense_after_aggregation():
            return global_model
        if self.defense_type == DEFENSE_WEAK_DP:
            self._key, sub = jax.random.split(self._key)
            return F.weak_dp(
                global_model, float(getattr(self.args, "stddev", 0.025)), sub
            )
        return global_model

    def get_malicious_client_idxs(self) -> List[int]:
        return []
