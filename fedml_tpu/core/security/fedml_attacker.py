"""Attack dispatcher singleton.

Parity with reference ``core/security/fedml_attacker.py:7-40`` — gated by
``enable_attack`` + ``attack_type``; the server's ``on_before_aggregation``
calls ``attack_model`` to inject Byzantine behaviour into the collected
updates, and data loaders call ``poison_data`` for label-flipping.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from . import attack_funcs as A
from .constants import (
    ATTACK_METHOD_BYZANTINE_ATTACK,
    ATTACK_METHOD_LABEL_FLIPPING,
    ATTACK_METHOD_MODEL_REPLACEMENT,
)

logger = logging.getLogger(__name__)

_MODEL_ATTACKS = {ATTACK_METHOD_BYZANTINE_ATTACK, ATTACK_METHOD_MODEL_REPLACEMENT}
_DATA_ATTACKS = {ATTACK_METHOD_LABEL_FLIPPING}


class FedMLAttacker:
    _attacker_instance: Optional["FedMLAttacker"] = None

    @classmethod
    def get_instance(cls) -> "FedMLAttacker":
        if cls._attacker_instance is None:
            cls._attacker_instance = cls()
        return cls._attacker_instance

    def __init__(self):
        self.is_enabled = False
        self.attack_type: Optional[str] = None
        self.args = None
        self._key = jax.random.PRNGKey(23)

    def init(self, args: Any) -> None:
        if not getattr(args, "enable_attack", False):
            self.is_enabled = False
            return
        self.args = args
        self.is_enabled = True
        self.attack_type = str(args.attack_type).strip()
        self._key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 2027)
        logger.info("attack enabled: %s", self.attack_type)

    def is_attack_enabled(self) -> bool:
        return self.is_enabled

    def is_model_attack(self) -> bool:
        return self.is_enabled and self.attack_type in _MODEL_ATTACKS

    def is_data_poisoning_attack(self) -> bool:
        return self.is_enabled and self.attack_type in _DATA_ATTACKS

    def get_byzantine_idxs(self, num_clients: int) -> List[int]:
        k = int(getattr(self.args, "byzantine_client_num", 1))
        rng = np.random.RandomState(int(getattr(self.args, "random_seed", 0)))
        return sorted(rng.choice(num_clients, size=min(k, num_clients), replace=False).tolist())

    # -- hooks ---------------------------------------------------------------
    def attack_model(
        self, raw_client_grad_list: List[Tuple[float, Any]], extra_auxiliary_info: Any = None
    ) -> List[Tuple[float, Any]]:
        if not self.is_model_attack():
            return raw_client_grad_list
        idxs = self.get_byzantine_idxs(len(raw_client_grad_list))
        self._key, sub = jax.random.split(self._key)
        if self.attack_type == ATTACK_METHOD_BYZANTINE_ATTACK:
            return A.byzantine_attack(
                raw_client_grad_list,
                extra_auxiliary_info,
                idxs,
                mode=str(getattr(self.args, "attack_mode", "random")),
                key=sub,
            )
        if self.attack_type == ATTACK_METHOD_MODEL_REPLACEMENT:
            scale = float(getattr(self.args, "attack_scale", 10.0))
            out = list(raw_client_grad_list)
            for i in idxs:
                n, p = out[i]
                out[i] = (n, A.model_replacement(p, extra_auxiliary_info, scale))
            return out
        return raw_client_grad_list

    def poison_data(self, labels):
        if not self.is_data_poisoning_attack():
            return labels
        return np.asarray(
            A.flip_labels(
                labels,
                int(getattr(self.args, "original_class", 1)),
                int(getattr(self.args, "target_class", 7)),
            )
        )
