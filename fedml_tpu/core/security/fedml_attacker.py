"""Attack dispatcher singleton.

Parity with reference ``core/security/fedml_attacker.py:7-40`` — gated by
``enable_attack`` + ``attack_type``; the server's ``on_before_aggregation``
calls ``attack_model`` to inject Byzantine behaviour into the collected
updates, and data loaders call ``poison_data`` for label-flipping.
"""

from __future__ import annotations

import logging
import os
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from . import attack_funcs as A

_UNSET = object()  # edge-pool cache sentinel (None is a valid cached value)
from .constants import (
    ATTACK_METHOD_BACKDOOR,
    ATTACK_METHOD_BYZANTINE_ATTACK,
    ATTACK_METHOD_DLG,
    ATTACK_METHOD_EDGE_CASE_BACKDOOR,
    ATTACK_METHOD_INVERT_GRADIENT,
    ATTACK_METHOD_LABEL_FLIPPING,
    ATTACK_METHOD_MODEL_REPLACEMENT,
    ATTACK_METHOD_REVEALING_LABELS,
)

logger = logging.getLogger(__name__)

_MODEL_ATTACKS = {
    ATTACK_METHOD_BYZANTINE_ATTACK,
    ATTACK_METHOD_MODEL_REPLACEMENT,
    ATTACK_METHOD_BACKDOOR,  # ALIE in-range evasion on the update list
    ATTACK_METHOD_EDGE_CASE_BACKDOOR,  # scaled push projected into a norm ball
}
_DATA_ATTACKS = {
    ATTACK_METHOD_LABEL_FLIPPING,
    ATTACK_METHOD_BACKDOOR,  # trigger-pattern stamping + relabel
    ATTACK_METHOD_EDGE_CASE_BACKDOOR,  # tail-sample relabel
}
_ANALYSIS_ATTACKS = {
    # privacy/analysis primitives: run on ONE intercepted client update
    # (the round loop pulls a victim row off the update stack)
    ATTACK_METHOD_DLG,
    ATTACK_METHOD_INVERT_GRADIENT,
    ATTACK_METHOD_REVEALING_LABELS,
}


class FedMLAttacker:
    _attacker_instance: Optional["FedMLAttacker"] = None

    @classmethod
    def get_instance(cls) -> "FedMLAttacker":
        if cls._attacker_instance is None:
            cls._attacker_instance = cls()
        return cls._attacker_instance

    def __init__(self):
        self.is_enabled = False
        self.attack_type: Optional[str] = None
        self.args = None
        self._edge_pool_cache = _UNSET
        self._key = jax.random.PRNGKey(23)

    def init(self, args: Any) -> None:
        if not getattr(args, "enable_attack", False):
            self.is_enabled = False
            return
        self.args = args
        self.is_enabled = True
        self.attack_type = str(args.attack_type).strip()
        self._key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 2027)
        self._round_clients = None
        self._edge_pool_cache = _UNSET  # re-read edge_case_dir on re-init
        logger.info("attack enabled: %s", self.attack_type)

    def is_attack_enabled(self) -> bool:
        return self.is_enabled

    def is_model_attack(self) -> bool:
        return self.is_enabled and self.attack_type in _MODEL_ATTACKS

    def is_data_poisoning_attack(self) -> bool:
        return self.is_enabled and self.attack_type in _DATA_ATTACKS

    def is_analysis_attack(self) -> bool:
        return self.is_enabled and self.attack_type in _ANALYSIS_ATTACKS

    def get_byzantine_idxs(self, num_clients: int) -> List[int]:
        k = int(getattr(self.args, "byzantine_client_num", 1))
        # salt the stream: round-0 client sampling draws choice(N, m) from
        # np.random.seed(round_idx) and the default random_seed is also 0 —
        # an unsalted draw here would make the byzantine set exactly the
        # round-0 cohort, silently turning "k of N malicious" experiments
        # into "all of round 0 malicious"
        rng = np.random.RandomState(int(getattr(self.args, "random_seed", 0)) + 7919)
        return sorted(rng.choice(num_clients, size=min(k, num_clients), replace=False).tolist())

    def set_round_clients(self, client_ids) -> None:
        """Round loops call this with the round's sampled POPULATION client
        ids (in collection order) so the model-side attack corrupts the same
        clients the data-side poisoning targeted.  Without it, attack_model
        falls back to drawing slot positions — only correct under full
        participation."""
        self._round_clients = [int(c) for c in client_ids]

    def _malicious_slots(self, n_slots: int) -> List[int]:
        round_ids = getattr(self, "_round_clients", None)
        if round_ids is not None and len(round_ids) == n_slots:
            total = int(getattr(self.args, "client_num_in_total", n_slots))
            bad = set(self.get_byzantine_idxs(total))
            return [slot for slot, cid in enumerate(round_ids) if cid in bad]
        return self.get_byzantine_idxs(n_slots)

    # -- hooks ---------------------------------------------------------------
    def attack_model(
        self, raw_client_grad_list: List[Tuple[float, Any]], extra_auxiliary_info: Any = None
    ) -> List[Tuple[float, Any]]:
        if not self.is_model_attack():
            return raw_client_grad_list
        idxs = self._malicious_slots(len(raw_client_grad_list))
        self._key, sub = jax.random.split(self._key)
        if self.attack_type == ATTACK_METHOD_BYZANTINE_ATTACK:
            return A.byzantine_attack(
                raw_client_grad_list,
                extra_auxiliary_info,
                idxs,
                mode=str(getattr(self.args, "attack_mode", "random")),
                key=sub,
            )
        if self.attack_type == ATTACK_METHOD_MODEL_REPLACEMENT:
            scale = float(getattr(self.args, "attack_scale", 10.0))
            out = list(raw_client_grad_list)
            for i in idxs:
                n, p = out[i]
                out[i] = (n, A.model_replacement(p, extra_auxiliary_info, scale))
            return out
        if self.attack_type == ATTACK_METHOD_BACKDOOR:
            # model side of the backdoor: ALIE keeps malicious updates inside
            # the benign per-coordinate range ('craft' replaces them with
            # mean - z*std; 'clip' clips the backdoor-trained update into
            # range so the planted trigger survives)
            return A.alie_attack(
                raw_client_grad_list, idxs,
                num_std=float(getattr(self.args, "attack_num_std", 1.5)),
                mode=str(getattr(self.args, "attack_mode", "craft")),
            )
        if self.attack_type == ATTACK_METHOD_EDGE_CASE_BACKDOOR:
            # scaled push, then projected back into an eps-ball around the
            # global model to evade norm-based defenses
            scale = float(getattr(self.args, "attack_scale", 10.0))
            eps = float(getattr(self.args, "attack_norm_bound", 5.0))
            out = list(raw_client_grad_list)
            for i in idxs:
                n, p = out[i]
                pushed = A.model_replacement(p, extra_auxiliary_info, scale)
                out[i] = (n, A.project_to_norm_ball(pushed, extra_auxiliary_info, eps))
            return out
        return raw_client_grad_list

    def poison_data(self, labels):
        if not self.is_data_poisoning_attack():
            return labels
        if self.attack_type != ATTACK_METHOD_LABEL_FLIPPING:
            return labels  # backdoor variants poison (x, y) via poison_dataset
        return np.asarray(
            A.flip_labels(
                labels,
                int(getattr(self.args, "original_class", 1)),
                int(getattr(self.args, "target_class", 7)),
            )
        )

    def poison_dataset(self, x, y, logits=None):
        """Data side of the backdoor attacks: stamp triggers / relabel tails.
        ``logits`` (model outputs on x) are required for edge-case selection;
        without them the edge-case variant falls back to poisoning nothing."""
        if not self.is_data_poisoning_attack():
            return x, y
        import jax.numpy as jnp

        x = jnp.asarray(x)
        y = jnp.asarray(y)
        target = int(getattr(self.args, "target_class", 0))
        frac = float(getattr(self.args, "poison_fraction", 0.2))
        if self.attack_type == ATTACK_METHOD_BACKDOOR:
            self._key, sub = jax.random.split(self._key)
            return A.poison_backdoor(x, y, target, frac, sub)
        if self.attack_type == ATTACK_METHOD_EDGE_CASE_BACKDOOR:
            pool = self._edge_case_pool(x.shape[1:])
            if pool is not None:
                # reference variant (edge_case_examples ARDIS/Southwest
                # pickles): inject mounted edge-case inputs labeled target
                self._key, sub = jax.random.split(self._key)
                k = max(1, int(frac * len(y)))
                ksrc, kpos = jax.random.split(sub)
                src = jax.random.choice(ksrc, pool.shape[0], (k,))
                pos = jax.random.choice(kpos, len(y), (k,), replace=False)
                return x.at[pos].set(pool[src]), y.at[pos].set(target)
            if logits is not None:
                return A.poison_edge_cases(x, y, jnp.asarray(logits), target, frac)
        return x, y

    def _edge_case_pool(self, sample_shape):
        """Mounted edge-case example pool (``args.edge_case_dir`` pointing at
        reference-format pickles); cached per init(); pools are keyed by
        sample shape so only the matching-shape pool is injected (a mounted
        dir may mix ARDIS MNIST-shaped and Southwest CIFAR-shaped pickles)."""
        if self._edge_pool_cache is _UNSET:
            from ...data.loaders import load_edge_case_pool

            root = getattr(self.args, "edge_case_dir", None)
            self._edge_pool_cache = (
                load_edge_case_pool(root) if root and os.path.isdir(root) else None
            )
        pools = self._edge_pool_cache
        if pools is None:
            return None
        pool = pools.get(tuple(sample_shape))
        if pool is None:
            return None
        import jax.numpy as jnp

        return jnp.asarray(pool)

    def poison_local_data(self, client_idx: int, num_clients: int, x, y, logits=None):
        """Per-client data-poisoning entry the round loop calls before local
        training: applies this attack's data transformation IF ``client_idx``
        is one of the malicious clients (byzantine idxs drawn over the full
        population), else returns the data unchanged."""
        if not self.is_data_poisoning_attack():
            return x, y
        if int(client_idx) not in set(self.get_byzantine_idxs(num_clients)):
            return x, y
        if self.attack_type == ATTACK_METHOD_LABEL_FLIPPING:
            return x, self.poison_data(y)
        return self.poison_dataset(x, y, logits=logits)

    # -- privacy attacks ----------------------------------------------------
    def reconstruct_data(self, module, variables, client_update, x_shape, num_classes):
        """DLG (attack_type='dlg'): reconstruct a representative input batch
        from one intercepted client update; returns (x_rec, y_soft) and keeps
        the result on the instance for inspection."""
        if self.attack_type != ATTACK_METHOD_DLG:
            return None
        self._key, sub = jax.random.split(self._key)
        self.last_reconstruction = A.dlg_attack(
            module, variables, client_update, x_shape, num_classes, sub,
            lr_client=float(getattr(self.args, "learning_rate", 0.1)),
            steps=int(getattr(self.args, "dlg_steps", 200)),
            lr_attack=float(getattr(self.args, "dlg_lr", 0.1)),
        )
        return self.last_reconstruction

    def analyze_update(self, module, variables, client_update, x_shape, num_classes):
        """Unified analysis-attack entry the round loops call on one
        intercepted update: dlg (L2 gradient matching), invert_gradient
        (cosine matching + TV prior), revealing_labels (iDLG bias-sign).
        Results land on the instance (``last_reconstruction`` /
        ``last_revealed_labels``) for experiment inspection."""
        if self.attack_type == ATTACK_METHOD_DLG:
            return self.reconstruct_data(
                module, variables, client_update, x_shape, num_classes
            )
        lr = float(getattr(self.args, "learning_rate", 0.1))
        if self.attack_type == ATTACK_METHOD_INVERT_GRADIENT:
            self._key, sub = jax.random.split(self._key)
            self.last_reconstruction = A.invert_gradient_attack(
                module, variables, client_update, x_shape, num_classes, sub,
                lr_client=lr,
                steps=int(getattr(self.args, "dlg_steps", 200)),
                lr_attack=float(getattr(self.args, "dlg_lr", 0.1)),
                tv_weight=float(getattr(self.args, "invert_tv_weight", 1e-2)),
            )
            return self.last_reconstruction
        if self.attack_type == ATTACK_METHOD_REVEALING_LABELS:
            self.last_revealed_labels = A.reveal_labels_from_update(
                variables, client_update, num_classes, lr_client=lr
            )
            return self.last_revealed_labels
        return None
