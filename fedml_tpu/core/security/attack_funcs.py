"""Attack simulations as pure functions over client updates.

Counterparts of reference ``core/security/attack/*.py`` (8 modules), rebuilt
on pytrees + ``jax.random``:

* byzantine (zero / random / flip modes) — ``byzantine_attack.py``
* label flipping (poison a dataset's labels) — ``label_flipping_attack.py``
* model replacement / scaled backdoor push — ``backdoor_attack.py`` core step
* gradient inversion (DLG-style reconstruction by gradient matching)
  — ``dlg_attack.py`` / ``invert_gradient_attack.py``
* revealing labels from gradients (sign heuristic on the last-layer grad)
  — ``revealing_labels_from_gradients_attack.py``
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

Pytree = Any
Updates = List[Tuple[float, Pytree]]


# ---------------------------------------------------------------------------
# Byzantine
# ---------------------------------------------------------------------------
def byzantine_attack(
    updates: Updates,
    global_params: Pytree,
    byzantine_idxs: Sequence[int],
    mode: str,
    key: jax.Array,
) -> Updates:
    """Corrupt the updates at ``byzantine_idxs``.

    Modes (reference byzantine_attack.py): ``zero`` — zero update; ``random``
    — gaussian garbage; ``flip`` — push away from the global model
    (g - (x - g)).
    """
    out = list(updates)
    for j, i in enumerate(byzantine_idxs):
        n, p = updates[i]
        if mode == "zero":
            bad = jax.tree_util.tree_map(jnp.zeros_like, p)
        elif mode == "random":
            leaves, treedef = jax.tree_util.tree_flatten(p)
            keys = jax.random.split(jax.random.fold_in(key, j), len(leaves))
            bad = jax.tree_util.tree_unflatten(
                treedef,
                [jax.random.normal(k, jnp.shape(l), dtype=jnp.result_type(l, jnp.float32)) for l, k in zip(leaves, keys)],
            )
        elif mode == "flip":
            bad = jax.tree_util.tree_map(lambda g, x: 2.0 * g - x, global_params, p)
        else:
            raise ValueError(f"unknown byzantine mode {mode!r}")
        out[i] = (n, bad)
    return out


# ---------------------------------------------------------------------------
# Label flipping (data poisoning)
# ---------------------------------------------------------------------------
def flip_labels(labels: jnp.ndarray, src: int, dst: int) -> jnp.ndarray:
    return jnp.where(labels == src, dst, labels)


# ---------------------------------------------------------------------------
# Model replacement (scaled malicious push; backdoor core step)
# ---------------------------------------------------------------------------
def model_replacement(
    malicious_params: Pytree, global_params: Pytree, scale: float
) -> Pytree:
    """x_adv = g + scale * (x_mal - g): survives averaging with 1/scale dilution."""
    return jax.tree_util.tree_map(
        lambda g, x: g + scale * (x - g), global_params, malicious_params
    )


# ---------------------------------------------------------------------------
# Gradient inversion (DLG): reconstruct a batch by matching gradients
# ---------------------------------------------------------------------------
def invert_gradient(
    grad_fn: Callable[[jnp.ndarray, jnp.ndarray], Pytree],
    target_grads: Pytree,
    x_shape: Tuple[int, ...],
    y_logits_shape: Tuple[int, ...],
    key: jax.Array,
    steps: int = 100,
    lr: float = 0.1,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Minimize ||grad_fn(x, softmax(y)) - target||^2 over dummy (x, y).

    ``grad_fn`` maps (inputs, soft labels) -> parameter-gradient pytree of the
    victim model at the intercepted step.  One fused jitted Adam-free loop
    (plain GD with cosine-ish decay) — enough to demonstrate leakage, matching
    the role of reference dlg_attack.py.
    """
    kx, ky = jax.random.split(key)
    x0 = jax.random.normal(kx, x_shape)
    y0 = jax.random.normal(ky, y_logits_shape)
    tvec, _ = ravel_pytree(target_grads)

    def loss(xy):
        x, y = xy
        g = grad_fn(x, jax.nn.softmax(y, axis=-1))
        gvec, _ = ravel_pytree(g)
        return jnp.sum((gvec - tvec) ** 2)

    @jax.jit
    def run(x0, y0):
        def body(i, xy):
            g = jax.grad(loss)(xy)
            step = lr * (0.5 + 0.5 * jnp.cos(jnp.pi * i / steps))
            return (xy[0] - step * g[0], xy[1] - step * g[1])

        return jax.lax.fori_loop(0, steps, body, (x0, y0))

    return run(x0, y0)


# ---------------------------------------------------------------------------
# Revealing labels from gradients (sign heuristic)
# ---------------------------------------------------------------------------
def reveal_labels_from_gradients(last_layer_bias_grad: jnp.ndarray) -> jnp.ndarray:
    """Classes present in a cross-entropy batch have negative bias-gradient
    entries (iDLG observation) — return indices sorted by most-negative."""
    return jnp.argsort(last_layer_bias_grad)
