"""Attack simulations as pure functions over client updates.

Counterparts of reference ``core/security/attack/*.py`` (8 modules), rebuilt
on pytrees + ``jax.random``:

* byzantine (zero / random / flip modes) — ``byzantine_attack.py``
* label flipping (poison a dataset's labels) — ``label_flipping_attack.py``
* model replacement / scaled malicious push — ``model_replacement``
* backdoor: trigger-pattern poisoning + ALIE in-range evasion
  — ``backdoor_attack.py``
* edge-case backdoor: tail-sample relabeling + norm-ball projection
  — ``edge_case_backdoor_attack.py``
* DLG full reconstruction pipeline from an intercepted update
  — ``dlg_attack.py``
* gradient inversion core (reconstruction by gradient matching)
  — ``invert_gradient_attack.py``
* revealing labels from gradients (sign heuristic on the last-layer grad)
  — ``revealing_labels_from_gradients_attack.py``
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

Pytree = Any
Updates = List[Tuple[float, Pytree]]


# ---------------------------------------------------------------------------
# Byzantine
# ---------------------------------------------------------------------------
def byzantine_attack(
    updates: Updates,
    global_params: Pytree,
    byzantine_idxs: Sequence[int],
    mode: str,
    key: jax.Array,
) -> Updates:
    """Corrupt the updates at ``byzantine_idxs``.

    Modes (reference byzantine_attack.py): ``zero`` — zero update; ``random``
    — gaussian garbage; ``flip`` — push away from the global model
    (g - (x - g)).
    """
    out = list(updates)
    for j, i in enumerate(byzantine_idxs):
        n, p = updates[i]
        if mode == "zero":
            bad = jax.tree_util.tree_map(jnp.zeros_like, p)
        elif mode == "random":
            leaves, treedef = jax.tree_util.tree_flatten(p)
            keys = jax.random.split(jax.random.fold_in(key, j), len(leaves))
            bad = jax.tree_util.tree_unflatten(
                treedef,
                [jax.random.normal(k, jnp.shape(l), dtype=jnp.result_type(l, jnp.float32)) for l, k in zip(leaves, keys)],
            )
        elif mode == "flip":
            bad = jax.tree_util.tree_map(lambda g, x: 2.0 * g - x, global_params, p)
        else:
            raise ValueError(f"unknown byzantine mode {mode!r}")
        out[i] = (n, bad)
    return out


# ---------------------------------------------------------------------------
# Label flipping (data poisoning)
# ---------------------------------------------------------------------------
def flip_labels(labels: jnp.ndarray, src: int, dst: int) -> jnp.ndarray:
    return jnp.where(labels == src, dst, labels)


# ---------------------------------------------------------------------------
# Model replacement (scaled malicious push; backdoor core step)
# ---------------------------------------------------------------------------
def model_replacement(
    malicious_params: Pytree, global_params: Pytree, scale: float
) -> Pytree:
    """x_adv = g + scale * (x_mal - g): survives averaging with 1/scale dilution."""
    return jax.tree_util.tree_map(
        lambda g, x: g + scale * (x - g), global_params, malicious_params
    )


# ---------------------------------------------------------------------------
# Gradient inversion (DLG): reconstruct a batch by matching gradients
# ---------------------------------------------------------------------------
def invert_gradient(
    grad_fn: Callable[[jnp.ndarray, jnp.ndarray], Pytree],
    target_grads: Pytree,
    x_shape: Tuple[int, ...],
    y_logits_shape: Tuple[int, ...],
    key: jax.Array,
    steps: int = 100,
    lr: float = 0.1,
    match: str = "l2",
    tv_weight: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reconstruct a dummy (x, y) whose gradients match ``target_grads``.

    ``grad_fn`` maps (inputs, soft labels) -> parameter-gradient pytree of the
    victim model at the intercepted step.  One fused jitted Adam-free loop
    (plain GD with cosine-ish decay) — enough to demonstrate leakage.

    ``match``: the gradient-match loss — ``"l2"`` (DLG, Zhu et al.) or
    ``"cosine"`` (Inverting Gradients, Geiping et al.); ``tv_weight`` > 0
    adds a total-variation image prior on 4-D (NHWC) inputs.  Both analysis
    attacks delegate here so the GD loop exists once.
    """
    kx, ky = jax.random.split(key)
    x0 = jax.random.normal(kx, x_shape)
    y0 = jax.random.normal(ky, y_logits_shape)
    tvec, _ = ravel_pytree(target_grads)
    tnorm = jnp.linalg.norm(tvec)

    def loss(xy):
        x, y = xy
        g = grad_fn(x, jax.nn.softmax(y, axis=-1))
        gvec, _ = ravel_pytree(g)
        if match == "cosine":
            out = 1.0 - jnp.dot(gvec, tvec) / jnp.maximum(
                jnp.linalg.norm(gvec) * tnorm, 1e-12
            )
        else:
            out = jnp.sum((gvec - tvec) ** 2)
        if tv_weight > 0 and len(x_shape) == 4:  # NHWC image prior
            out = out + tv_weight * (
                jnp.abs(x[:, 1:, :, :] - x[:, :-1, :, :]).mean()
                + jnp.abs(x[:, :, 1:, :] - x[:, :, :-1, :]).mean()
            )
        return out

    @jax.jit
    def run(x0, y0):
        def body(i, xy):
            g = jax.grad(loss)(xy)
            step = lr * (0.5 + 0.5 * jnp.cos(jnp.pi * i / steps))
            return (xy[0] - step * g[0], xy[1] - step * g[1])

        return jax.lax.fori_loop(0, steps, body, (x0, y0))

    return run(x0, y0)


def invert_gradient_attack(
    module,
    variables: Pytree,
    client_update: Pytree,
    x_shape: Tuple[int, ...],
    num_classes: int,
    key: jax.Array,
    lr_client: float = 0.1,
    steps: int = 200,
    lr_attack: float = 0.1,
    tv_weight: float = 1e-2,
):
    """'Inverting Gradients' (Geiping et al.) reconstruction from an
    intercepted update — reference ``invert_gradient_attack.py``: COSINE
    gradient matching + a total-variation image prior, vs :func:`dlg_attack`'s
    plain L2 match.  Returns ``(x_rec, y_soft_logits)``.  Delegates the GD
    loop to :func:`invert_gradient` (one loop, two match losses)."""
    import optax

    target_grads = jax.tree_util.tree_map(
        lambda g, w: (g - w) / lr_client, variables["params"], client_update["params"]
    )

    def grad_fn(x, y_soft):
        def loss(params):
            logits = module.apply(dict(variables, params=params), x, train=False)
            per = optax.softmax_cross_entropy(logits.astype(jnp.float32), y_soft)
            return jnp.mean(per)

        return jax.grad(loss)(variables["params"])

    return invert_gradient(
        grad_fn, target_grads, x_shape, (x_shape[0], num_classes), key,
        steps=steps, lr=lr_attack, match="cosine", tv_weight=tv_weight,
    )


# ---------------------------------------------------------------------------
# Revealing labels from gradients (sign heuristic)
# ---------------------------------------------------------------------------
def reveal_labels_from_gradients(last_layer_bias_grad: jnp.ndarray) -> jnp.ndarray:
    """Classes present in a cross-entropy batch have negative bias-gradient
    entries (iDLG observation) — return indices sorted by most-negative."""
    return jnp.argsort(last_layer_bias_grad)


def reveal_labels_from_update(
    variables: Pytree,
    client_update: Pytree,
    num_classes: int,
    lr_client: float = 0.1,
    head_path=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Label revelation from an intercepted UPDATE (the simulator-facing
    wrapper over :func:`reveal_labels_from_gradients`): locate the classifier
    bias, estimate its gradient as ``(w_prev - w_new)/lr``, and return
    ``(order, present)``: class indices sorted most-likely-present first,
    and the boolean negative-entry mask (classes the iDLG heuristic says
    were in the batch).

    ``head_path`` names the classifier-head bias explicitly (mirroring the
    defender-side ``soteria_layer`` knob): a key tuple like
    ``("Dense_2", "bias")`` or the ``"/"``-joined string ``"Dense_2/bias"``.
    PASS IT for models of ten or more layers: the fallback heuristic walks
    leaves in pytree flatten order, which sorts keys LEXICOGRAPHICALLY —
    ``Dense_10`` < ``Dense_2`` — so "last bias" stops being the output layer
    once double-digit layer names appear.

    Heuristic fallback (``head_path=None``): among ``(num_classes,)``-shaped
    leaves, prefer those whose tree path names a bias (a hidden layer of
    width == num_classes would otherwise shadow the head), then take the
    LAST such leaf (flax orders the output layer last — for models under ten
    layers, where sorted order and definition order agree)."""
    if head_path is not None:
        keys = tuple(head_path.split("/")) if isinstance(head_path, str) else tuple(head_path)
        p, q = variables["params"], client_update["params"]
        try:
            for k in keys:
                p, q = p[k], q[k]
        except (KeyError, IndexError, TypeError):
            raise ValueError(f"head_path {head_path!r} not found in the params tree")
        p, q = jnp.asarray(p), jnp.asarray(q)
        if p.shape != (num_classes,):
            raise ValueError(
                f"head_path {head_path!r} leaf has shape {p.shape}, expected "
                f"({num_classes},) — it must name the classifier-head BIAS"
            )
    else:
        prev_paths = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
        new_leaves = jax.tree_util.tree_leaves(client_update["params"])
        candidates = []
        for (path, pl), ql in zip(prev_paths, new_leaves):
            if pl.shape != (num_classes,):
                continue
            names = "/".join(str(getattr(k, "key", k)) for k in path).lower()
            candidates.append(("bias" in names, pl, ql))
        if not candidates:
            raise ValueError(
                f"no ({num_classes},) bias leaf in the params tree — cannot "
                "locate the classifier head for label revelation"
            )
        has_bias = any(is_bias for is_bias, _, _ in candidates)
        p, q = [(pl, ql) for is_bias, pl, ql in candidates
                if is_bias or not has_bias][-1]
    bias_grad = (p.astype(jnp.float32) - q.astype(jnp.float32)) / lr_client
    return reveal_labels_from_gradients(bias_grad), bias_grad < 0


# ---------------------------------------------------------------------------
# Backdoor: trigger-pattern data poisoning + ALIE model-side evasion
# ---------------------------------------------------------------------------
def add_backdoor_pattern(x: jnp.ndarray, size: int = 5, value: float = 2.8) -> jnp.ndarray:
    """Stamp a corner trigger patch on a batch of images (reference
    ``backdoor_attack.py:91-94`` uses img[:, :5, :5] = 2.8; NHWC here)."""
    patch = jnp.full_like(x[:, :size, :size], value)
    return x.at[:, :size, :size].set(patch)


def poison_backdoor(
    x: jnp.ndarray,
    y: jnp.ndarray,
    target_class: int,
    fraction: float,
    key: jax.Array,
    size: int = 5,
    value: float = 2.8,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Poison a random ``fraction`` of a client's samples: stamp the trigger
    and relabel to ``target_class`` (reference backdoor_attack.py 'pattern'
    mode: triggered images always map to one class)."""
    n = x.shape[0]
    k = int(n * float(fraction))
    if k == 0:
        return x, y
    idx = jax.random.permutation(key, n)[:k]
    stamped = add_backdoor_pattern(x[idx], size=size, value=value)
    return x.at[idx].set(stamped), y.at[idx].set(target_class)


def alie_attack(
    updates: Updates, byzantine_idxs: Sequence[int], num_std: float,
    mode: str = "craft",
) -> Updates:
    """'A little is enough' (Baruch et al., reference backdoor_attack.py):
    keep malicious updates inside the benign per-coordinate range
    [mean - z*std, mean + z*std] so distance/range defenses struggle.

    ``mode='craft'`` places every malicious update at mean - z*std (the
    paper's parameter-crafting form — no malicious training needed);
    ``mode='clip'`` clips each malicious client's OWN update (e.g. one
    trained on backdoored data) into the range, the reference's
    backdoor_attack.py:83-85 form — the trigger survives to the degree it
    fits inside the benign envelope.  One vectorized pass over the raveled
    update matrix (vs the reference's per-name numpy loops)."""
    bad = set(int(i) for i in byzantine_idxs)
    benign = [p for j, (_, p) in enumerate(updates) if j not in bad]
    if not benign:
        return updates
    vecs = jnp.stack([ravel_pytree(p)[0] for p in benign], 0)
    _, unravel = ravel_pytree(benign[0])
    mean = jnp.mean(vecs, axis=0)
    std = jnp.std(vecs, axis=0)
    z = float(num_std)
    if mode == "craft":
        mal = unravel(mean - z * std)
        return [(n, mal if j in bad else p) for j, (n, p) in enumerate(updates)]
    if mode == "clip":
        out = list(updates)
        for j in bad:
            n, p = updates[j]
            v, _ = ravel_pytree(p)
            out[j] = (n, unravel(jnp.clip(v, mean - z * std, mean + z * std)))
        return out
    raise ValueError(f"unknown alie mode {mode!r}")


# ---------------------------------------------------------------------------
# Edge-case backdoor (Wang et al. 2020, reference edge_case_backdoor_attack.py)
# ---------------------------------------------------------------------------
def select_edge_cases(
    logits: jnp.ndarray, fraction: float
) -> jnp.ndarray:
    """Indices of the tail samples — lowest max-softmax confidence — the
    'edge cases' whose poisoning is hardest to detect (they sit in a region
    the benign distribution barely covers).  fraction=0 selects none (so an
    'attack disabled' ablation really is a no-op)."""
    conf = jnp.max(jax.nn.softmax(logits, axis=-1), axis=-1)
    k = int(conf.shape[0] * float(fraction))
    return jnp.argsort(conf)[:k]


def poison_edge_cases(
    x: jnp.ndarray,
    y: jnp.ndarray,
    logits: jnp.ndarray,
    target_class: int,
    fraction: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Relabel the edge-case tail to ``target_class`` (no visible trigger —
    the edge-case inputs themselves are the backdoor key)."""
    idx = select_edge_cases(logits, fraction)
    return x, y.at[idx].set(target_class)


def project_to_norm_ball(params: Pytree, global_params: Pytree, eps: float) -> Pytree:
    """PGD-style projection of a (malicious) model onto the eps-ball around
    the global model — the norm-evasion step edge-case backdoors pair with
    scaling (reference edge_case_backdoor_attack.py's projected variant)."""
    d_vec, unravel = ravel_pytree(
        jax.tree_util.tree_map(lambda p, g: p - g, params, global_params)
    )
    norm = jnp.linalg.norm(d_vec)
    scale = jnp.minimum(1.0, eps / jnp.maximum(norm, 1e-12))
    g_vec, _ = ravel_pytree(global_params)
    return unravel(g_vec + d_vec * scale)


# ---------------------------------------------------------------------------
# DLG: full reconstruction pipeline from an intercepted client update
# ---------------------------------------------------------------------------
def dlg_attack(
    module,
    variables: Pytree,
    client_update: Pytree,
    x_shape: Tuple[int, ...],
    num_classes: int,
    key: jax.Array,
    lr_client: float = 0.1,
    steps: int = 200,
    lr_attack: float = 0.1,
):
    """Deep-leakage-from-gradients (reference dlg_attack.py): approximate the
    client's step gradient as (w_global - w_client)/lr, then reconstruct a
    representative (x, y) by gradient matching (invert_gradient).  Returns
    ``(x_rec, y_soft)``."""
    import optax

    target_grads = jax.tree_util.tree_map(
        lambda g, w: (g - w) / lr_client, variables["params"], client_update["params"]
    )

    def grad_fn(x, y_soft):
        def loss(params):
            logits = module.apply(dict(variables, params=params), x, train=False)
            per = optax.softmax_cross_entropy(logits.astype(jnp.float32), y_soft)
            return jnp.mean(per)

        return jax.grad(loss)(variables["params"])

    return invert_gradient(
        grad_fn,
        target_grads,
        x_shape,
        (x_shape[0], num_classes),
        key,
        steps=steps,
        lr=lr_attack,
    )
