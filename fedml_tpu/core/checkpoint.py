"""Round checkpoint / resume.

The reference has NO general round-checkpointing — only per-round artifact
uploads to S3/MLOps (``core/mlops/__init__.py:351-399``
``log_aggregated_model_info`` / ``log_client_model_info``) and the MNN global
model file (``cross_device/server_mnn/fedml_aggregator.py:38``).  SURVEY.md §5
calls for the rebuild to add proper checkpoint/restore of
``(global params, round_idx, rng, optimizer state)`` — this module is that.

Design: one directory per run, one ``ckpt_<step>.msgpack`` per saved round
(flax msgpack serialization — restores to numpy leaves without needing a
target pytree), a JSON sidecar with step metadata, atomic tmp+rename writes
so a crash mid-save never corrupts the latest checkpoint, and a keep-last-N
retention policy.  Device arrays are pulled to host numpy on save; callers
``jax.device_put`` (or just feed into jit) on restore.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

logger = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")


def _to_host(tree: Any) -> Any:
    """Pull every array leaf to host numpy (msgpack can't see device arrays)."""

    def leaf(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(leaf, tree)


class CheckpointManager:
    """Save/restore a state pytree keyed by integer step (FL round index)."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = max(int(keep), 1)
        os.makedirs(directory, exist_ok=True)

    # -- paths ---------------------------------------------------------------
    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step}.msgpack")

    def all_steps(self) -> List[int]:
        steps = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save/restore --------------------------------------------------------
    def save(self, step: int, state: Any, metadata: Optional[Dict[str, Any]] = None) -> str:
        """Atomically write ``state`` for ``step``; prunes old checkpoints."""
        payload = serialization.msgpack_serialize(_to_host(state))
        path = self._path(step)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        meta = {"step": int(step), "time": time.time()}
        if metadata:
            meta.update(metadata)
        meta_tmp = path + ".json.tmp"
        with open(meta_tmp, "w") as f:
            json.dump(meta, f)
        os.replace(meta_tmp, path + ".json")
        self._prune()
        logger.info("checkpoint saved: %s", path)
        return path

    def restore(self, step: Optional[int] = None) -> Tuple[int, Any]:
        """Restore ``(step, state)``; latest step when ``step`` is None.

        Raises ``FileNotFoundError`` when the directory holds no checkpoint.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoint in {self.directory}")
        with open(self._path(step), "rb") as f:
            state = serialization.msgpack_restore(f.read())
        return int(step), state

    def metadata(self, step: int) -> Dict[str, Any]:
        try:
            with open(self._path(step) + ".json") as f:
                return json.load(f)
        except FileNotFoundError:
            return {"step": step}

    def _prune(self) -> None:
        steps = self.all_steps()
        for old in steps[: -self.keep]:
            for suffix in ("", ".json"):
                try:
                    os.remove(self._path(old) + suffix)
                except FileNotFoundError:
                    pass


def maybe_checkpointer(args: Any) -> Optional[CheckpointManager]:
    """Build a CheckpointManager from config, or None when disabled.

    Config keys (train_args): ``checkpoint_dir`` (enables), ``checkpoint_keep``
    (default 3), ``checkpoint_frequency`` (rounds between saves, default 1).
    """
    directory = getattr(args, "checkpoint_dir", None)
    if not directory:
        return None
    return CheckpointManager(str(directory), keep=int(getattr(args, "checkpoint_keep", 3)))


def checkpoint_frequency(args: Any) -> int:
    return max(int(getattr(args, "checkpoint_frequency", 1)), 1)
