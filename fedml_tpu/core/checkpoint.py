"""Round checkpoint / resume.

The reference has NO general round-checkpointing — only per-round artifact
uploads to S3/MLOps (``core/mlops/__init__.py:351-399``
``log_aggregated_model_info`` / ``log_client_model_info``) and the MNN global
model file (``cross_device/server_mnn/fedml_aggregator.py:38``).  SURVEY.md §5
calls for the rebuild to add proper checkpoint/restore of
``(global params, round_idx, rng, optimizer state)`` — this module is that.

Design: one directory per run, one ``ckpt_<step>.msgpack`` per saved round
(flax msgpack serialization — restores to numpy leaves without needing a
target pytree), a JSON sidecar with step metadata, atomic tmp+rename writes
so a crash mid-save never corrupts the latest checkpoint, and a keep-last-N
retention policy.  Device arrays are pulled to host numpy on save; callers
``jax.device_put`` (or just feed into jit) on restore.
"""

from __future__ import annotations

import json
import logging
import os
import re
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from . import ingest, obs

logger = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")
_JOURNAL_RE = re.compile(r"^journal_r(\d+)\.bin$")

JOURNAL_FSYNC_POLICIES = ("always", "never")


def _fsync_dir(directory: str) -> None:
    """fsync a directory so a rename into it survives power loss (POSIX
    requires the directory entry itself to be synced, not just the file)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:  # pragma: no cover - non-POSIX dir-open semantics
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename + dir-fsync: the file at ``path`` is either the
    old complete version or the new complete version, never empty/partial."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _to_host(tree: Any) -> Any:
    """Pull every array leaf to host numpy (msgpack can't see device arrays)."""

    def leaf(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(leaf, tree)


class CheckpointManager:
    """Save/restore a state pytree keyed by integer step (FL round index)."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = max(int(keep), 1)
        os.makedirs(directory, exist_ok=True)

    # -- paths ---------------------------------------------------------------
    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step}.msgpack")

    def all_steps(self) -> List[int]:
        steps = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save/restore --------------------------------------------------------
    def save(self, step: int, state: Any, metadata: Optional[Dict[str, Any]] = None) -> str:
        """Atomically + durably write ``state`` for ``step``; prunes old
        checkpoints.  The payload is fsynced before the rename and the
        directory after it, so a power cut can never leave an empty "latest"
        file shadowing a good older one."""
        payload = serialization.msgpack_serialize(_to_host(state))
        path = self._path(step)
        _atomic_write(path, payload)
        meta = {"step": int(step), "time": time.time()}
        if metadata:
            meta.update(metadata)
        _atomic_write(path + ".json", json.dumps(meta).encode("utf-8"))
        self._prune()
        obs.counter_inc("checkpoint.saves")
        obs.histogram_observe("checkpoint.bytes", len(payload),
                              buckets=(2**10, 2**14, 2**18, 2**22, 2**26, 2**30))
        logger.info("checkpoint saved: %s", path)
        return path

    def restore(self, step: Optional[int] = None) -> Tuple[int, Any]:
        """Restore ``(step, state)``; latest *readable* step when ``step`` is
        None — a truncated/corrupt latest file is logged, pruned, and the
        walk falls back to the previous retained step instead of failing the
        resume.  An explicitly requested ``step`` still raises on corruption.

        Raises ``FileNotFoundError`` when the directory holds no (readable)
        checkpoint.
        """
        if step is not None:
            return int(step), self._load(step)
        for cand in reversed(self.all_steps()):
            try:
                return int(cand), self._load(cand)
            except FileNotFoundError:
                raise
            except Exception as e:
                logger.warning(
                    "checkpoint ckpt_%d.msgpack is unreadable (%s): pruning it "
                    "and falling back to the previous retained step", cand, e)
                for suffix in ("", ".json"):
                    try:
                        os.remove(self._path(cand) + suffix)
                    except FileNotFoundError:
                        pass
        raise FileNotFoundError(f"no checkpoint in {self.directory}")

    def _load(self, step: int) -> Any:
        with open(self._path(step), "rb") as f:
            payload = f.read()
        if not payload:
            raise ValueError("empty checkpoint file")
        return serialization.msgpack_restore(payload)

    def metadata(self, step: int) -> Dict[str, Any]:
        try:
            with open(self._path(step) + ".json") as f:
                return json.load(f)
        except FileNotFoundError:
            return {"step": step}

    def _prune(self) -> None:
        steps = self.all_steps()
        for old in steps[: -self.keep]:
            for suffix in ("", ".json"):
                try:
                    os.remove(self._path(old) + suffix)
                except FileNotFoundError:
                    pass


def maybe_checkpointer(args: Any) -> Optional[CheckpointManager]:
    """Build a CheckpointManager from config, or None when disabled.

    Config keys (train_args): ``checkpoint_dir`` (enables), ``checkpoint_keep``
    (default 3), ``checkpoint_frequency`` (rounds between saves, default 1).
    """
    directory = getattr(args, "checkpoint_dir", None)
    if not directory:
        return None
    return CheckpointManager(str(directory), keep=int(getattr(args, "checkpoint_keep", 3)))


def checkpoint_frequency(args: Any) -> int:
    return max(int(getattr(args, "checkpoint_frequency", 1)), 1)


# ---------------------------------------------------------------------------
# Message-plane server recovery: update journal + state snapshot + mixin.
#
# The simulators above checkpoint a closed-form state between rounds; the
# message-plane servers additionally hold *mid-round* state — the aggregator
# slot table filling up with client uploads.  Recovery therefore needs two
# artifacts with different write cadences:
#
#   * a per-round **snapshot** (CheckpointManager) written once at round open:
#     (global params, round_idx, participant list, registry columns,
#     incarnation epoch, eval history);
#   * a per-round **update journal** appended once per accepted upload,
#     *before* the upload is acked — a restarted server replays the journal
#     into the slot table, so an acked upload is never lost and a retransmit
#     of a journaled upload is discarded instead of double-counted.
# ---------------------------------------------------------------------------

_FRAME_HEADER = struct.Struct("!II")  # (payload length, crc32)

_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


class JournalTicket:
    """Durability handle for one asynchronously appended journal record.

    Returned by :meth:`UpdateJournal.append_async`; becomes *durable* when
    the group-commit thread has fsynced the batch containing the record.
    Callbacks added via :meth:`add_done_callback` run on the committer
    thread (or inline when the ticket is already settled) — the ingest
    pipeline uses them to release the transport ack, so ``error`` must be
    checked: an ack for a failed append would break "ack implies journaled".
    """

    __slots__ = ("_event", "_lock", "_callbacks", "error")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._callbacks: List[Callable[["JournalTicket"], None]] = []
        self.error: Optional[BaseException] = None

    @property
    def durable(self) -> bool:
        return self._event.is_set() and self.error is None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def add_done_callback(self, fn: Callable[["JournalTicket"], None]) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _mark(self, error: Optional[BaseException] = None) -> None:
        with self._lock:
            self.error = error
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception:  # a bad callback must not kill the committer
                logger.exception("journal ticket callback failed")


class UpdateJournal:
    """Append-only per-round journal of accepted client uploads.

    One ``journal_r<round>.bin`` per round; each record is a length+crc32
    framed msgpack blob appended with O_APPEND semantics and (policy
    permitting) fsynced before the caller acks the upload.  ``replay()``
    tolerates a truncated or corrupt tail — exactly what a crash mid-append
    leaves behind — by returning every complete record before it.

    **Group commit** (``group_commit_ms > 0``): concurrent appends coalesce
    into one write+fsync batch, bounded by a time window and by
    ``group_commit_max`` records.  :meth:`append_async` serializes and
    frames the record *eagerly* on the calling thread (so the caller may
    reuse/mutate the tree afterwards), enqueues the frame, and returns a
    :class:`JournalTicket` that settles once the batch is durable — the
    PR 4 "ack implies journaled" contract is preserved, the fsync merely
    amortized.  A torn *batch* tail looks to :meth:`replay` exactly like a
    torn record tail (frames are self-delimiting), and every record in a
    torn batch was by construction un-acked, so clients retransmit them.
    """

    def __init__(self, directory: str, fsync: str = "always",
                 group_commit_ms: float = 0.0, group_commit_max: int = 32):
        fsync = str(fsync).lower()
        if fsync not in JOURNAL_FSYNC_POLICIES:
            raise ValueError(
                f"journal fsync policy must be one of {JOURNAL_FSYNC_POLICIES}, "
                f"got {fsync!r}")
        self.directory = directory
        self.fsync = fsync
        self.group_commit_ms = float(group_commit_ms)
        self.group_commit_max = max(int(group_commit_max), 1)
        self._gc_cond = threading.Condition()
        self._gc_queue: List[Tuple[int, bytes, JournalTicket, float]] = []
        self._gc_urgent = False
        self._gc_stop = False
        self._gc_thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    @property
    def group_commit_enabled(self) -> bool:
        return self.group_commit_ms > 0.0

    def _path(self, round_idx: int) -> str:
        return os.path.join(self.directory, f"journal_r{int(round_idx)}.bin")

    def rounds(self) -> List[int]:
        found = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _JOURNAL_RE.match(name)
            if m:
                found.append(int(m.group(1)))
        return sorted(found)

    def _frame(self, record: Dict[str, Any]) -> bytes:
        return self._frame_payload(
            serialization.msgpack_serialize(_to_host(record)))

    @staticmethod
    def _frame_payload(payload: bytes) -> bytes:
        header = _FRAME_HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        return header + payload

    def append(self, round_idx: int, record: Dict[str, Any]) -> None:
        """Durably append one record; returns only once it is on disk (under
        the default ``always`` policy), so callers may ack afterwards.

        With group commit enabled the append still routes through the
        committer thread (single-writer: two threads appending to the same
        file could interleave torn frames) as an *urgent* entry and blocks
        on its ticket — durable-on-return semantics are unchanged."""
        if self.group_commit_enabled:
            ticket = self.append_async(round_idx, record, urgent=True)
            ticket.wait()
            if ticket.error is not None:
                raise ticket.error
            return
        t0 = time.perf_counter()
        frame = self._frame(record)
        with open(self._path(round_idx), "ab") as f:
            f.write(frame)
            f.flush()
            if self.fsync == "always":
                t_sync = time.perf_counter()
                os.fsync(f.fileno())
                obs.histogram_observe("journal.fsync_seconds",
                                      time.perf_counter() - t_sync)
        obs.counter_inc("journal.appends")
        obs.histogram_observe("journal.append_seconds",
                              time.perf_counter() - t0)

    def append_async(self, round_idx: int, record: Dict[str, Any],
                     urgent: bool = False) -> JournalTicket:
        """Enqueue one record for the next group-commit batch and return its
        :class:`JournalTicket`.  Serialization happens HERE, on the calling
        thread — the record (and any arena-backed arrays inside it) may be
        reused the moment this returns.  With group commit disabled this
        degrades to a blocking :meth:`append` returning a settled ticket."""
        ticket = JournalTicket()
        if not self.group_commit_enabled:
            try:
                self.append(round_idx, record)
            except Exception as e:
                ticket._mark(e)
                return ticket
            ticket._mark()
            return ticket
        t0 = time.perf_counter()
        frame = self._frame(record)
        return self._enqueue(round_idx, frame, ticket, t0, urgent)

    def append_blob_async(self, round_idx: int, payload: bytes,
                          urgent: bool = False) -> JournalTicket:
        """Zero-copy variant of :meth:`append_async`: ``payload`` is already
        the canonical msgpack record bytes (e.g. the received wire blob, the
        exact bytes :meth:`_frame` would have produced), so it is framed
        verbatim with no decode→re-encode round trip.  :meth:`replay` reads
        it back identically to a record serialized here."""
        ticket = JournalTicket()
        t0 = time.perf_counter()
        frame = self._frame_payload(payload)
        if not self.group_commit_enabled:
            try:
                with open(self._path(round_idx), "ab") as f:
                    f.write(frame)
                    f.flush()
                    if self.fsync == "always":
                        t_sync = time.perf_counter()
                        os.fsync(f.fileno())
                        obs.histogram_observe("journal.fsync_seconds",
                                              time.perf_counter() - t_sync)
            except Exception as e:
                ticket._mark(e)
                return ticket
            obs.counter_inc("journal.appends")
            obs.histogram_observe("journal.append_seconds",
                                  time.perf_counter() - t0)
            ticket._mark()
            return ticket
        return self._enqueue(round_idx, frame, ticket, t0, urgent)

    def _enqueue(self, round_idx: int, frame: bytes, ticket: JournalTicket,
                 t0: float, urgent: bool) -> JournalTicket:
        with self._gc_cond:
            if self._gc_stop:
                ticket._mark(RuntimeError("journal is closed"))
                return ticket
            if self._gc_thread is None:
                self._gc_watchdog = obs.health_watchdog("journal.committer")
                self._gc_thread = threading.Thread(
                    target=self._commit_loop, daemon=True,
                    name="journal-group-commit")
                self._gc_thread.start()
            self._gc_queue.append((int(round_idx), frame, ticket, t0))
            if urgent:
                self._gc_urgent = True
                self._gc_cond.notify_all()
            elif (len(self._gc_queue) == 1
                    or len(self._gc_queue) >= self.group_commit_max):
                # wake the committer only when its state can change: the
                # first record ends its idle wait, a full batch ends the
                # coalesce window early.  Waking it on EVERY append costs
                # two context switches per record and dominates the enqueue
                # path; mid-window it re-checks on its own timed wait.
                self._gc_cond.notify_all()
        return ticket

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every record enqueued so far is durable."""
        with self._gc_cond:
            pending = [t for _, _, t, _ in self._gc_queue]
            self._gc_urgent = self._gc_urgent or bool(pending)
            self._gc_cond.notify_all()
        for t in pending:
            t.wait(timeout)

    def close(self) -> None:
        """Commit any pending batch and stop the committer thread."""
        with self._gc_cond:
            self._gc_stop = True
            self._gc_cond.notify_all()
            thread = self._gc_thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=10.0)
        wd = getattr(self, "_gc_watchdog", None)
        if wd is not None:
            wd.close()

    def _commit_loop(self) -> None:
        wd = getattr(self, "_gc_watchdog", obs.NULL_WATCHDOG)
        while True:
            with self._gc_cond:
                # disarm across the unbounded idle wait (an empty queue is
                # not a wedge); re-arm the moment there is work to commit
                wd.idle()
                while not self._gc_queue and not self._gc_stop:
                    self._gc_cond.wait()
                if not self._gc_queue and self._gc_stop:
                    return
                wd.beat()
                # window: give concurrent appends a chance to coalesce,
                # bounded by time, batch size, and urgency (blocking append
                # or explicit flush must not eat the full window)
                deadline = time.monotonic() + self.group_commit_ms / 1000.0
                while (len(self._gc_queue) < self.group_commit_max
                       and not self._gc_urgent and not self._gc_stop):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._gc_cond.wait(timeout=remaining)
                batch = self._gc_queue[:self.group_commit_max]
                del self._gc_queue[:self.group_commit_max]
                self._gc_urgent = bool(self._gc_queue)
            self._commit_batch(batch)

    def _commit_batch(
            self, batch: List[Tuple[int, bytes, JournalTicket, float]]) -> None:
        t_batch = time.perf_counter()
        by_round: Dict[int, List[Tuple[bytes, JournalTicket, float]]] = {}
        for rid, frame, ticket, t0 in batch:
            by_round.setdefault(rid, []).append((frame, ticket, t0))
        for rid, entries in by_round.items():
            err: Optional[BaseException] = None
            try:
                with open(self._path(rid), "ab") as f:
                    f.write(b"".join(frame for frame, _, _ in entries))
                    f.flush()
                    if self.fsync == "always":
                        t_sync = time.perf_counter()
                        os.fsync(f.fileno())
                        obs.histogram_observe("journal.fsync_seconds",
                                              time.perf_counter() - t_sync)
            except Exception as e:  # tickets carry the error; acks stay held
                logger.exception("journal group commit failed for round %d", rid)
                err = e
            now = time.perf_counter()
            for _, ticket, t0 in entries:
                if err is None:
                    obs.counter_inc("journal.appends")
                    obs.histogram_observe("journal.append_seconds", now - t0)
                ticket._mark(err)
        obs.histogram_observe("journal.batch_records", len(batch),
                              buckets=_BATCH_BUCKETS)
        obs.histogram_observe("ingest.batch_fsync_seconds",
                              time.perf_counter() - t_batch)

    def replay(self, round_idx: int) -> Tuple[List[Dict[str, Any]], int]:
        """Read back ``(records, bad_tail)`` for a round.  ``bad_tail`` is 1
        when a truncated/corrupt trailing frame was discarded (a crash hit
        mid-append; that upload was never acked, so the client re-sends)."""
        path = self._path(round_idx)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return [], 0
        records: List[Dict[str, Any]] = []
        offset = 0
        while offset + _FRAME_HEADER.size <= len(blob):
            length, crc = _FRAME_HEADER.unpack_from(blob, offset)
            start = offset + _FRAME_HEADER.size
            payload = blob[start:start + length]
            if len(payload) < length or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                logger.warning(
                    "journal %s: discarding corrupt/truncated tail frame at "
                    "byte %d", path, offset)
                obs.counter_inc("journal.bad_tail")
                return records, 1
            records.append(serialization.msgpack_restore(payload))
            offset = start + length
        if offset != len(blob):
            logger.warning("journal %s: discarding truncated tail header at "
                           "byte %d", path, offset)
            obs.counter_inc("journal.bad_tail")
            return records, 1
        return records, 0

    def reset_round(self, round_idx: int) -> None:
        """Start a round's journal from scratch (a *fresh* round open after a
        crash that predated its snapshot leaves stale same-round entries)."""
        try:
            os.remove(self._path(round_idx))
        except FileNotFoundError:
            pass

    def prune_before(self, round_idx: int) -> None:
        for old in self.rounds():
            if old < int(round_idx):
                try:
                    os.remove(self._path(old))
                except FileNotFoundError:
                    pass


class ServerStateStore:
    """Snapshot + journal pair backing one message-plane server run.

    Layout under ``directory``: ``state/ckpt_<round>.msgpack`` (snapshot at
    round open, keep-last-N) and ``journal/journal_r<round>.bin`` (one accepted
    upload per frame).  The snapshot is authoritative for which round is in
    flight; journals for finished rounds are pruned at the next round open.
    """

    def __init__(self, directory: str, keep: int = 3, fsync: str = "always",
                 group_commit_ms: float = 0.0, group_commit_max: int = 32):
        self.directory = directory
        self.snapshots = CheckpointManager(os.path.join(directory, "state"), keep=keep)
        self.journal = UpdateJournal(os.path.join(directory, "journal"), fsync=fsync,
                                     group_commit_ms=group_commit_ms,
                                     group_commit_max=group_commit_max)

    def close(self) -> None:
        self.journal.flush(timeout=10.0)
        self.journal.close()

    def save_round_start(self, round_idx: int, state: Any,
                         metadata: Optional[Dict[str, Any]] = None) -> str:
        path = self.snapshots.save(int(round_idx), state, metadata)
        self.journal.prune_before(round_idx)
        self.journal.reset_round(round_idx)
        return path

    def load_latest(self) -> Optional[Tuple[int, Any]]:
        try:
            return self.snapshots.restore()
        except FileNotFoundError:
            return None


def maybe_server_store(args: Any) -> Optional[ServerStateStore]:
    """Build a ServerStateStore from config, or None when disabled.

    Config keys: ``server_checkpoint_dir`` (enables), ``checkpoint_keep``
    (snapshot retention, default 3), ``server_journal_fsync``
    (``always`` | ``never``, default ``always``),
    ``journal_group_commit_ms`` / ``journal_group_commit_max`` (group-commit
    window; 0 ms = per-record commits, the pre-PR-10 behaviour)."""
    directory = getattr(args, "server_checkpoint_dir", None)
    if not directory:
        return None
    return ServerStateStore(
        str(directory),
        keep=int(getattr(args, "checkpoint_keep", 3)),
        fsync=str(getattr(args, "server_journal_fsync", "always")),
        group_commit_ms=float(getattr(args, "journal_group_commit_ms", 0.0)),
        group_commit_max=int(getattr(args, "journal_group_commit_max", 32)),
    )


def edge_journal_dir(base: str, edge_id: int) -> str:
    """Per-edge journal directory under a deployment's checkpoint root.

    Deterministic in ``edge_id`` so a REPLACEMENT incarnation of a killed
    edge finds its predecessor's journal and can replay the round."""
    return os.path.join(str(base), f"edge_{int(edge_id)}", "journal")


def make_edge_journal(args: Any, edge_id: int) -> Optional[UpdateJournal]:
    """Build an edge aggregator's :class:`UpdateJournal`, or None when
    durability is disabled.

    Edges reuse the server journal knobs (``server_journal_fsync``,
    ``journal_group_commit_ms`` / ``_max``) — the journal-before-ack
    contract is tier-independent — rooted at ``edge_checkpoint_dir`` when
    set, else ``server_checkpoint_dir``.  Edges keep no model snapshot:
    their only durable state is the round's accepted uploads, which is
    exactly what replay needs to re-fold and re-forward the same fused
    delta under the same forward id."""
    base = (getattr(args, "edge_checkpoint_dir", None)
            or getattr(args, "server_checkpoint_dir", None))
    if not base:
        return None
    return UpdateJournal(
        edge_journal_dir(base, edge_id),
        fsync=str(getattr(args, "server_journal_fsync", "always")),
        group_commit_ms=float(getattr(args, "journal_group_commit_ms", 0.0)),
        group_commit_max=int(getattr(args, "journal_group_commit_max", 32)),
    )


class ServerRecoveryMixin:
    """Crash-resumable rounds for the message-plane server managers.

    Mixed into ``cross_silo.server.FedMLServerManager`` and
    ``cross_device.FedMLServerManager``; the host provides four hooks —
    ``_capture_global_params`` / ``_restore_global_params`` (model tree in/out
    of the aggregator), ``_round_start_extras`` / ``_restore_round_extras``
    (stack-specific state: silo index map, eval history) — plus
    ``_replay_upload(record)`` to push one journaled upload back into its
    slot table, and the optional ``_capture_server_opt_state`` /
    ``_restore_server_opt_state`` pair for the sharded server-optimizer
    state (``server_state=sharded``).  Lifecycle:

    * ``init_server_recovery(args)`` at the end of ``__init__``: loads the
      latest snapshot (if any), bumps the incarnation epoch, replays the
      open round's journal, and marks the manager initialized so the
      ONLINE/epoch rejoin machinery (``straggler.RoundTimeoutMixin``)
      re-syncs every client into the restored round — the inverse of the
      client rejoin flow, reusing the same resync path.
    * ``_save_round_start()`` at every round open (after the participant
      list is fixed, before any sync/init send).
    * ``_journal_upload(sender, ...)`` in the upload handler, before the
      slot-table insert; returns False for a duplicate (already journaled
      this round), which the handler drops un-counted.
    """

    def init_server_recovery(self, args: Any) -> None:
        self._store = maybe_server_store(args)
        self.server_epoch = 0
        self._uploads_this_round: set = set()
        self._recovered_pending_close = False
        if self._store is not None:
            # chunked uploads journal each accepted chunk before its ack
            # (sub-message granularity of the same contract _journal_upload
            # implements at message granularity)
            chunking = getattr(self, "_chunking", None)
            if chunking is not None:
                chunking.bind_journal(self._journal_chunk)
        if self._store is None:
            return
        loaded = self._store.load_latest()
        if loaded is None:
            return
        round_idx, state = loaded
        logger.warning("server restore: resuming round %d from %s",
                       round_idx, self._store.directory)
        self.server_epoch = int(state.get("server_epoch", 0)) + 1
        self.args.round_idx = int(round_idx)
        self.client_id_list_in_this_round = [int(c) for c in state["participants"]]
        self._had_timeout_close = bool(state.get("had_timeout_close", False))
        self._restore_global_params(state["global_params"])
        if state.get("server_opt") is not None:
            # sharded server state: params must be installed (the line
            # above) before the optimizer snapshot loads onto the mesh
            self._restore_server_opt_state(state["server_opt"])
        self._restore_round_extras(state)
        pop = getattr(self, "population", None)
        if pop is not None:
            pop.restore_registry(state["registry"])
            pop.resume_round(round_idx, self.per_round,
                             self.client_id_list_in_this_round)
        records, bad_tail = self._store.journal.replay(round_idx)
        replayed = 0
        # chunk records (journal-before-ack one level DOWN: each accepted
        # chunk of a partial upload) route to the reassembler, never the
        # slot table — a complete-but-unacked stream re-dispatches when its
        # sender retransmits, and _journal_upload's sender dedup below keeps
        # the finished upload exactly-once either way
        chunk_recs = [r for r in records if r.get("kind") == "chunk"]
        for rec in records:
            if rec.get("kind") == "chunk":
                continue
            sender = int(rec["sender"])
            if sender in self._uploads_this_round:
                self._comm_stats.inc("dup_uploads_discarded")
                continue
            if self._replay_upload(rec):
                self._uploads_this_round.add(sender)
                replayed += 1
        if chunk_recs:
            chunking = getattr(self, "_chunking", None)
            if chunking is not None:
                chunking.restore(chunk_recs)
        # already-initialized: the ONLINE handshake must NOT restart round 0.
        # _client_epochs is deliberately NOT restored — every client's next
        # ONLINE therefore reads as a rejoin and flows through the existing
        # _resync_rejoined_client path into the restored round.
        self.is_initialized = True
        self._comm_stats.inc("server_restores")
        self._comm_stats.inc("epoch_bumps")
        self._comm_stats.inc("journal_replays", replayed)
        obs.counter_inc("journal.replay_records", replayed)
        # annotate the recovery onto the restored round's root span: the id
        # is deterministic in (run_id, round_idx), so these land on the tree
        # the dead incarnation opened
        node = getattr(self, "rank", 0)
        obs.span_event("server_restore", round_idx=int(round_idx), node=node,
                       epoch=self.server_epoch, replayed=replayed,
                       bad_tail=bad_tail)
        obs.span_event("epoch_bump", round_idx=int(round_idx), node=node,
                       epoch=self.server_epoch)
        self._recovered_pending_close = True
        logger.warning(
            "server restore: epoch=%d round=%d participants=%s replayed=%d "
            "bad_tail=%d", self.server_epoch, round_idx,
            self.client_id_list_in_this_round, replayed, bad_tail)

    def _server_round_updater(self) -> Optional[Any]:
        """The sharded ``ServerRoundUpdater`` behind this manager's
        aggregator, or None for replicated runs.  Covers both stacks:
        cross_device's ``FedMLAggregator`` owns ``round_updater`` directly,
        cross_silo's wraps the ServerAggregator hook object that owns it."""
        agg = getattr(self, "aggregator", None)
        for obj in (agg, getattr(agg, "aggregator", None)):
            upd = getattr(obj, "round_updater", None)
            if upd is not None:
                return upd
        return None

    def maybe_remesh(self) -> bool:
        """Round-boundary elastic check: when the live device set no longer
        matches the round plane's mesh (device loss, pod grow, operator
        resize), re-shard the resident server state onto a mesh rebuilt
        from the surviving devices and bump the incarnation epoch.
        In-flight uploads from the old epoch flow through the same
        journal/dedup machinery as a crash recovery — re-deliveries are
        discarded by ``_journal_upload``, never double-counted.  Called at
        every round open (``_save_round_start``); no-op for replicated
        runs and for an unchanged topology."""
        updater = self._server_round_updater()
        if updater is None or updater.mesh_key() is None:
            return False
        try:
            from ..parallel.agg_plane import round_mesh_for
            from ..parallel.mesh import mesh_fingerprint
            live = mesh_fingerprint(round_mesh_for(self.args))
        except Exception:  # a broken probe must not take the round down
            logger.exception("maybe_remesh: live-mesh probe failed")
            return False
        if live == updater.mesh_key():
            return False
        info = updater.remesh()
        if not (info and info.get("changed")):
            return False
        self.server_epoch = int(getattr(self, "server_epoch", 0)) + 1
        node = getattr(self, "rank", 0)
        self._comm_stats.inc("epoch_bumps")
        obs.span_event("epoch_bump", round_idx=int(self.args.round_idx),
                       node=node, epoch=self.server_epoch, reason="remesh")
        logger.warning(
            "elastic remesh at round %d: %s -> %s (epoch=%d, %d bytes "
            "resharded, recompile %.3fs)", int(self.args.round_idx),
            info["old"], info["new"], self.server_epoch,
            info["reshard_bytes"], info["recompile_s"])
        return True

    def _save_round_start(self) -> None:
        """Persist the round-open snapshot; also resets the per-round upload
        dedup set (kept even with persistence off — a same-round re-upload
        must never double-count).  The elastic check runs first, so the
        snapshot captures the post-resize state and epoch."""
        self._uploads_this_round = set()
        self.maybe_remesh()
        if self._store is None:
            return
        state = {
            "server_epoch": int(self.server_epoch),
            "participants": np.asarray(
                [int(c) for c in self.client_id_list_in_this_round], np.int64),
            "had_timeout_close": bool(getattr(self, "_had_timeout_close", False)),
            "global_params": self._capture_global_params(),
        }
        opt_state = self._capture_server_opt_state()
        if opt_state is not None:
            state["server_opt"] = opt_state
        pop = getattr(self, "population", None)
        if pop is not None:
            state["registry"] = pop.export_registry()
        state.update(self._round_start_extras())
        self._store.save_round_start(int(self.args.round_idx), state)

    def _capture_server_opt_state(self) -> Optional[Any]:
        """Optional fifth hook pair: hosts running ``server_state=sharded``
        return the sharded optimizer/params snapshot here (and load it in
        ``_restore_server_opt_state``) so a server kill restores the
        server-optimizer state bit-identically.  Default: nothing to save."""
        return None

    def _restore_server_opt_state(self, state: Any) -> None:
        pass

    def _journal_upload(self, sender: int, **payload: Any) -> bool:
        """Record one accepted upload; False = duplicate for this round (the
        caller must drop it without touching the slot table).  On the host
        path the append is durable before return, and the transport ack
        happens only after the handler returns (ack-after-dispatch), so ack
        implies journaled.  Under the ingest pipeline the append is enqueued
        for group commit and its ticket handed to the ambient
        :func:`~fedml_tpu.core.ingest.deferred_ack_scope` sink — the
        pipeline releases the ack only once the ticket is durable, so the
        contract holds there too, just amortized."""
        sender = int(sender)
        if sender in self._uploads_this_round:
            self._comm_stats.inc("dup_uploads_discarded")
            logger.info("duplicate upload from %d for round %d discarded",
                        sender, self.args.round_idx)
            return False
        if self._store is not None:
            record = {"round_idx": int(self.args.round_idx), "sender": sender}
            record.update(payload)
            journal = self._store.journal
            sink = (ingest.current_sink()
                    if journal.group_commit_enabled else None)
            if sink is not None:
                sink.add(journal.append_async(self.args.round_idx, record))
            else:
                journal.append(self.args.round_idx, record)
        self._uploads_this_round.add(sender)
        return True

    def _journal_chunk(self, round_idx: int, record: Dict[str, Any]) -> None:
        """Journal hook for the chunk reassembler: one record per accepted
        chunk, durable before that chunk's transport ack (same sink-or-
        blocking idiom as ``_journal_upload``, one level down)."""
        if self._store is None:
            return
        journal = self._store.journal
        sink = ingest.current_sink() if journal.group_commit_enabled else None
        if sink is not None:
            sink.add(journal.append_async(int(round_idx), record))
        else:
            journal.append(int(round_idx), record)

    def finish(self) -> None:
        """Flush any pending group-commit batch (releasing its held acks)
        before the transport goes down, then tear the store down."""
        store = getattr(self, "_store", None)
        if store is not None:
            store.close()
        super().finish()

    def _maybe_close_recovered_round(self) -> None:
        """One-shot, called from the status handler once transport is live:
        if the crash happened *after* the cohort's last upload was journaled
        but *before* the round closed, re-close it now (aggregation is
        deterministic in (params, uploads), so the result is bit-identical)."""
        if not self._recovered_pending_close:
            return
        self._recovered_pending_close = False
        self._close_round_if_complete()
